"""paddle.fft vs numpy.fft (the reference's kernels follow the same
norm conventions)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fft as pfft


rng = np.random.RandomState(0)


def a(t):
    return np.asarray(t.value)


class TestFFT:
    @pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
    def test_fft_ifft_roundtrip(self, norm):
        x = rng.randn(4, 16).astype(np.float32)
        t = paddle.to_tensor(x)
        y = pfft.fft(t, norm=norm)
        back = pfft.ifft(y, norm=norm)
        np.testing.assert_allclose(a(back).real, x, atol=1e-5)
        np.testing.assert_allclose(a(y), np.fft.fft(x, norm=norm),
                                   rtol=1e-4, atol=1e-4)

    def test_rfft_irfft(self):
        x = rng.randn(8, 32).astype(np.float32)
        t = paddle.to_tensor(x)
        y = pfft.rfft(t)
        assert a(y).shape == (8, 17)
        np.testing.assert_allclose(a(y), np.fft.rfft(x), rtol=1e-4,
                                   atol=1e-4)
        np.testing.assert_allclose(a(pfft.irfft(y)), x, atol=1e-5)

    def test_hfft_ihfft(self):
        x = rng.randn(16).astype(np.float32)
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(a(pfft.hfft(t)), np.fft.hfft(x),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(a(pfft.ihfft(t)), np.fft.ihfft(x),
                                   rtol=1e-4, atol=1e-4)

    def test_2d_and_nd(self):
        x = rng.randn(3, 8, 8).astype(np.float32)
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(a(pfft.fft2(t)), np.fft.fft2(x),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(a(pfft.rfft2(t)), np.fft.rfft2(x),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(a(pfft.fftn(t)), np.fft.fftn(x),
                                   rtol=1e-4, atol=1e-3)
        np.testing.assert_allclose(a(pfft.irfft2(pfft.rfft2(t))), x,
                                   atol=1e-5)

    def test_helpers(self):
        np.testing.assert_allclose(a(pfft.fftfreq(8, 0.5)),
                                   np.fft.fftfreq(8, 0.5))
        np.testing.assert_allclose(a(pfft.rfftfreq(8, 0.5)),
                                   np.fft.rfftfreq(8, 0.5))
        x = rng.randn(4, 5).astype(np.float32)
        np.testing.assert_allclose(a(pfft.fftshift(paddle.to_tensor(x))),
                                   np.fft.fftshift(x))
        np.testing.assert_allclose(
            a(pfft.ifftshift(paddle.to_tensor(x))), np.fft.ifftshift(x))

    def test_bad_norm_rejected(self):
        with pytest.raises(ValueError):
            pfft.fft(paddle.to_tensor(np.ones(4, np.float32)),
                     norm="bogus")

    def test_grad_through_rfft(self):
        x = paddle.to_tensor(rng.randn(16).astype(np.float32))
        x.stop_gradient = False
        y = pfft.rfft(x)
        loss = (y.abs() ** 2).sum()
        loss.backward()
        assert x.grad is not None
        assert np.isfinite(a(x.grad)).all()
