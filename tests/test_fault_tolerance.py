"""Fault-tolerant training runtime (distributed/{fault,checkpoint,guard}).

Every recovery path is exercised through a PLANTED fault driven by the
deterministic injection registry (`paddle_tpu.distributed.fault`):

  * torn shard (truncate), bit-rot (corrupt), writer IO error (error),
    missing manifest / missing `latest` commit — checkpoint hardening;
  * async writer fail-fast at the next save (satellite);
  * NaN step — compiled skip-step guard + consecutive-bad budget + AMP
    loss-scale backoff;
  * transient KV connection blips — bounded retry (satellite);
  * watchdog task leak on a raising body (satellite);

plus the acceptance-bar bit-exact resume parity: N steps of
ShardedTrainStep / OffloadPipelineStep / hapi fit ≡ N/2 steps + save +
restore-into-fresh-state + N/2 steps.
"""
import json
import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.distributed import fault
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed.guard import (StepAnomalyGuard,
                                          BadStepBudgetExceeded)
from paddle_tpu.distributed.topology import build_mesh
from paddle_tpu.parallel import ShardedTrainStep, OffloadPipelineStep


# ---------------------------------------------------------------------------
# shared tiny models / data
# ---------------------------------------------------------------------------

class MLP(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = paddle.nn.Linear(8, 16)
        self.fc2 = paddle.nn.Linear(16, 1)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x)))


class Block(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = paddle.nn.Linear(16, 16)

    def forward(self, x):
        return paddle.nn.functional.relu(self.fc(x))


class StackedNet(paddle.nn.Layer):
    """Block-stacked net for the offload pipeline."""

    def __init__(self, L=3):
        super().__init__()
        self.inp = paddle.nn.Linear(8, 16)
        self.layers = paddle.nn.LayerList([Block() for _ in range(L)])
        self.head = paddle.nn.Linear(16, 1)

    def forward(self, x):
        h = self.inp(x)
        for b in self.layers:
            h = b(h)
        return self.head(h)


def _mse(out, y):
    return paddle.nn.functional.mse_loss(out, y)


def _batch(i, n=4):
    rng = np.random.RandomState(100 + i)
    return (paddle.to_tensor(rng.randn(n, 8).astype(np.float32)),
            paddle.to_tensor(rng.randn(n, 1).astype(np.float32)))


def _sharded(seed=7, lr_sched=False, **kw):
    paddle.seed(seed)
    m = MLP()
    lr = paddle.optimizer.lr.StepDecay(1e-2, step_size=2, gamma=0.5) \
        if lr_sched else 1e-2
    opt = paddle.optimizer.AdamW(lr, parameters=m.parameters(),
                                 weight_decay=0.1)
    mesh = build_mesh(devices=jax.devices()[:1])
    return m, ShardedTrainStep(m, opt, mesh, loss_fn=_mse, **kw)


def _offload(seed=7):
    paddle.seed(seed)
    m = StackedNet()
    opt = paddle.optimizer.AdamW(1e-2, parameters=m.parameters(),
                                 weight_decay=0.1)
    mesh = build_mesh(devices=jax.devices()[:1])
    return m, OffloadPipelineStep(m, opt, mesh, loss_fn=_mse,
                                  cast_dtype=None)


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    yield
    paddle.set_flags({"FLAGS_fault_injection": ""})
    fault.reset()


# ---------------------------------------------------------------------------
# injection registry
# ---------------------------------------------------------------------------

class TestFaultSpecs:
    def test_grammar(self):
        specs = fault.parse_specs(
            "ckpt.write:step=3:mode=truncate;"
            "kv.request:times=2;step.data:mode=nan:times=*")
        assert [s.point for s in specs] == ["ckpt.write", "kv.request",
                                           "step.data"]
        assert specs[0].step == 3 and specs[0].mode == "truncate"
        assert specs[1].times == 2 and specs[1].mode == "error"
        assert specs[2].times == -1

    def test_bad_specs_fail_loudly(self):
        with pytest.raises(fault.FaultSpecError):
            fault.parse_specs("nonexistent.point:mode=error")
        with pytest.raises(fault.FaultSpecError):
            fault.parse_specs("ckpt.write:mode=frobnicate")
        with pytest.raises(fault.FaultSpecError):
            fault.parse_specs("ckpt.write:stepthree")

    def test_deterministic_nth_hit(self):
        with fault.scope("kv.request:step=2:mode=error"):
            assert fault.hit("kv.request") is None
            with pytest.raises(fault.FaultError):
                fault.hit("kv.request")
            assert fault.hit("kv.request") is None  # times=1 consumed

    def test_times_and_match(self):
        with fault.scope("ckpt.write:times=2:mode=corrupt:match=special"):
            assert fault.hit("ckpt.write", key="other") is None
            assert fault.hit("ckpt.write", key="special-1").mode \
                == "corrupt"
            assert fault.hit("ckpt.write", key="special-2") is not None
            assert fault.hit("ckpt.write", key="special-3") is None

    def test_step_with_times_fires_consecutively(self):
        """step=N:times=k fires at hits N..N+k-1 (the docstring's own
        `kv.request:step=1:times=2` example means TWO blips)."""
        with fault.scope("kv.request:step=2:times=2:mode=error"):
            fired = []
            for _ in range(4):
                try:
                    fault.hit("kv.request")
                    fired.append(False)
                except fault.FaultError:
                    fired.append(True)
            assert fired == [False, True, True, False]

    def test_unknown_point_raises_even_when_armed(self):
        with fault.scope("kv.request:mode=error"):
            with pytest.raises(fault.FaultSpecError, match="unregist"):
                fault.hit("ckpt.writ")      # typo'd call site

    def test_unset_is_inert(self):
        assert not fault.is_active()
        assert fault.hit("step.begin") is None
        assert fault.hit_counts() == {}


# ---------------------------------------------------------------------------
# checkpoint hardening — one planted defect per feature
# ---------------------------------------------------------------------------

def _w(val):
    return {"w": paddle.to_tensor(np.full((4, 4), val, np.float32))}


def _load_w(root):
    tgt = _w(0.0)
    got = ckpt.load_checkpoint(tgt, root)
    if got is None:
        return None
    return got[0], float(np.asarray(tgt["w"].value)[0, 0])


class TestCheckpointHardening:
    def test_commit_and_load_latest(self, tmp_path):
        root = str(tmp_path)
        for s in (1, 2, 3):
            ckpt.save_checkpoint(_w(s), root, s)
        assert (tmp_path / "latest").read_text() == "step_00000003"
        assert _load_w(root) == (3, 3.0)

    def test_torn_shard_falls_back(self, tmp_path):
        """Planted torn write (truncate): the save fails verification at
        commit, `latest` stays put, load falls back to the previous
        complete step."""
        root = str(tmp_path)
        ckpt.save_checkpoint(_w(1), root, 1)
        with fault.scope("ckpt.write:step=1:mode=truncate"):
            with pytest.raises(IOError, match="verification"):
                ckpt.save_checkpoint(_w(2), root, 2)
        assert _load_w(root) == (1, 1.0)

    def test_bad_crc_detected_and_skipped(self, tmp_path):
        """Planted bit-rot (corrupt): the sidecar CRC catches it; the
        torn dir is skipped on load."""
        root = str(tmp_path)
        ckpt.save_checkpoint(_w(1), root, 1)
        with fault.scope("ckpt.write:step=1:mode=corrupt"):
            with pytest.raises(IOError):
                ckpt.save_checkpoint(_w(2), root, 2)
        step2 = str(tmp_path / "step_00000002")
        assert not ckpt.is_complete(step2)
        assert ckpt.is_complete(str(tmp_path / "step_00000001"))
        assert _load_w(root) == (1, 1.0)

    def test_missing_manifest_is_torn(self, tmp_path):
        root = str(tmp_path)
        ckpt.save_checkpoint(_w(1), root, 1)
        with fault.scope("ckpt.manifest:mode=skip"):
            with pytest.raises(IOError, match="verification"):
                ckpt.save_checkpoint(_w(2), root, 2)
        assert _load_w(root) == (1, 1.0)

    def test_uncommitted_latest_still_recovered(self, tmp_path):
        """Crash between shard landing and the `latest` commit (the
        emergency-drain window): the complete-but-unpointed step is
        found by the verification scan and preferred."""
        root = str(tmp_path)
        ckpt.save_checkpoint(_w(1), root, 1)
        with fault.scope("ckpt.latest:mode=skip"):
            ckpt.save_checkpoint(_w(2), root, 2)
        assert (tmp_path / "latest").read_text() == "step_00000001"
        assert _load_w(root) == (2, 2.0)

    def test_transient_write_error_retried(self, tmp_path):
        """Two injected IO errors are absorbed by the bounded
        retry-with-backoff; the third attempt lands the shard."""
        root = str(tmp_path)
        with fault.scope("ckpt.write:times=2:mode=error"):
            ckpt.save_checkpoint(_w(5), root, 5)
        assert _load_w(root) == (5, 5.0)

    def test_persistent_write_error_raises(self, tmp_path):
        with fault.scope("ckpt.write:times=*:mode=error"):
            with pytest.raises(IOError):
                ckpt.save_checkpoint(_w(1), str(tmp_path), 1)

    def test_retention_gc(self, tmp_path):
        root = str(tmp_path)
        for s in range(1, 6):
            ckpt.save_checkpoint(_w(s), root, s, keep=2)
        dirs = sorted(d for d in os.listdir(root)
                      if d.startswith("step_"))
        assert dirs == ["step_00000004", "step_00000005"]
        assert _load_w(root) == (5, 5.0)

    def test_async_writer_fail_fast(self, tmp_path):
        """Satellite: a failed async save surfaces at the NEXT
        save_state_dict immediately (and is cleared), not only at
        synchronize_async_saves."""
        with fault.scope("ckpt.write:times=*:mode=error"):
            fut = ckpt.save_state_dict(_w(1), str(tmp_path / "a"),
                                       async_save=True)
            with pytest.raises(Exception):
                fut.result()          # writer job has failed
            with pytest.raises(IOError):
                ckpt.save_state_dict(_w(2), str(tmp_path / "b"))
        # error observed + cleared: the next save succeeds
        ckpt.save_state_dict(_w(3), str(tmp_path / "c"))
        ckpt.synchronize_async_saves()

    def test_async_save_checkpoint_commits_in_order(self, tmp_path):
        root = str(tmp_path)
        ckpt.save_checkpoint(_w(1), root, 1, async_save=True)
        ckpt.save_checkpoint(_w(2), root, 2, async_save=True)
        ckpt.synchronize_async_saves()
        assert _load_w(root) == (2, 2.0)

    def test_sync_save_behind_inflight_async(self, tmp_path):
        """A sync save issued while an async save is still writing (the
        SIGTERM emergency-drain shape) must not let its commit's GC
        reap the in-flight older step as a torn leftover: the sync save
        rides the writer queue, and both steps land complete."""
        root = str(tmp_path)
        with fault.scope("ckpt.write:step=1:mode=delay:secs=0.8"):
            ckpt.save_checkpoint(_w(1), root, 1, async_save=True)
            got = ckpt.save_checkpoint(_w(2), root, 2)     # sync
        assert got == os.path.join(root, "step_00000002")
        ckpt.synchronize_async_saves()     # no stored writer error
        assert ckpt.is_complete(os.path.join(root, "step_00000001"))
        assert (tmp_path / "latest").read_text() == "step_00000002"
        assert _load_w(root) == (2, 2.0)

    def test_mixed_path_training_warns_keeps_jit_capture(self):
        """An eager fallthrough AFTER jitted steps must not silently
        flip checkpoints to near-fresh eager accumulators: it warns,
        and train_state keeps capturing the jit TrainStep side."""
        from paddle_tpu.hapi.model import Model

        def loss(out, y, w=None):
            l = paddle.nn.functional.mse_loss(out, y)
            return l if w is None else l * w.mean()

        paddle.seed(5)
        m = Model(MLP())
        opt = paddle.optimizer.AdamW(1e-2, parameters=m.parameters())
        m.prepare(opt, loss)
        x, y = _batch(0)
        m.train_batch([x], [y])            # jit path
        ones = paddle.to_tensor(np.ones((4, 1), np.float32))
        with pytest.warns(RuntimeWarning, match="split"):
            m.train_batch([x], [y, ones])  # eager fallthrough
        arrays, meta = m.train_state()
        assert meta["hapi_path"] == "jit"

    def test_partial_restore_warns(self, tmp_path):
        """Restoring into a trainer whose key set no longer matches the
        checkpoint (renamed/resized net) must warn loudly instead of
        silently resuming half-fresh with a late-schedule LR."""
        root = str(tmp_path)
        _, s_a = _sharded()
        for i in range(2):
            s_a(*_batch(i))
        ckpt.save_train_checkpoint(s_a, root)
        paddle.seed(11)
        m2 = StackedNet()
        opt = paddle.optimizer.AdamW(1e-2, parameters=m2.parameters())
        s_b = ShardedTrainStep(m2, opt,
                               build_mesh(devices=jax.devices()[:1]),
                               loss_fn=_mse)
        with pytest.warns(RuntimeWarning, match="PARTIAL"):
            ckpt.restore_train_checkpoint(s_b, root)

    def test_stale_wider_world_shards_ignored(self, tmp_path):
        """Elastic world shrink: a re-save into a step dir can leave
        higher-rank shards from the wider pre-resize incarnation
        behind; load must read exactly the ranks the manifest's
        __world__ declares, not mix stale values back in."""
        root = str(tmp_path)
        ckpt.save_checkpoint(_w(9), root, 1)      # the "stale" payload
        step1 = os.path.join(root, "step_00000001")
        import shutil
        stale = os.path.join(step1, "3.distcp")
        shutil.copy(os.path.join(step1, "0.distcp"), stale)
        shutil.copy(os.path.join(step1, "0.distcp.shard.json"),
                    stale + ".shard.json")
        # overwrite rank 0 in place (the post-shrink re-save)
        ckpt.save_state_dict(_w(1), step1)
        assert ckpt.is_complete(step1)            # stale rank-3 ignored
        assert _load_w(root) == (1, 1.0)          # ... by the load too

    def test_sync_behind_async_failure_not_reraised(self, tmp_path):
        """A sync save queued behind a healthy async save whose OWN
        write fails raises once at the call — synchronize_async_saves
        must not surface the same error again."""
        root = str(tmp_path)
        with fault.scope("ckpt.write:after=1:times=*:mode=error"):
            ckpt.save_checkpoint(_w(1), root, 1, async_save=True)
            with pytest.raises(IOError):
                ckpt.save_checkpoint(_w(2), root, 2)   # sync, fails
        ckpt.synchronize_async_saves()     # first save landed, no raise
        assert _load_w(root) == (1, 1.0)

    def test_failed_async_error_surfaces_exactly_once(self, tmp_path):
        """The fail-fast raise consumes the failure: the dead save's
        chained commit must not re-raise the same error a second time
        at synchronize_async_saves."""
        root = str(tmp_path)
        with fault.scope("ckpt.write:times=*:mode=error"):
            fut = ckpt.save_checkpoint(_w(1), root, 1, async_save=True)
            # the chained commit settles only after the write job: a
            # reliable barrier — and it must swallow the write failure
            assert fut.result() is None
            with pytest.raises(IOError):   # fail-fast observes it once
                ckpt.save_state_dict(_w(2), str(tmp_path / "b"))
        ckpt.synchronize_async_saves()     # ... and exactly once


# ---------------------------------------------------------------------------
# bit-exact resume parity (acceptance bar)
# ---------------------------------------------------------------------------

class TestBitExactResume:
    def _run(self, step, lo, hi):
        out = []
        for i in range(lo, hi):
            x, y = _batch(i)
            out.append(float(np.asarray(step(x, y).value)))
        return out

    def test_sharded_trainer_resume_parity(self, tmp_path):
        """8 steps ≡ 4 steps + save + restore-into-fresh-state + 4
        steps: losses identical, LR schedule and RNG restored."""
        _, s_ref = _sharded(lr_sched=True)
        ref = self._run(s_ref, 0, 8)
        _, s_a = _sharded(lr_sched=True)
        first = self._run(s_a, 0, 4)
        ckpt.save_train_checkpoint(s_a, str(tmp_path))
        paddle.seed(999)                  # clobber process RNG ...
        _, s_b = _sharded(seed=31337, lr_sched=True)  # ... and init
        meta = ckpt.restore_train_checkpoint(s_b, str(tmp_path))
        assert meta["step_count"] == 4
        rest = self._run(s_b, 4, 8)
        assert ref == first + rest        # bit-exact, not allclose

    def test_offload_pipeline_resume_parity(self, tmp_path):
        """Same bar for the streamed ZeRO-3 pipeline: host-parked
        param/state STACKS captured and restored exactly."""
        _, s_ref = _offload()
        ref = self._run(s_ref, 0, 6)
        _, s_a = _offload()
        first = self._run(s_a, 0, 3)
        ckpt.save_train_checkpoint(s_a, str(tmp_path))
        paddle.seed(999)
        _, s_b = _offload(seed=31337)
        meta = ckpt.restore_train_checkpoint(s_b, str(tmp_path))
        assert meta["step_count"] == 3
        rest = self._run(s_b, 3, 6)
        assert ref == first + rest

    def test_resume_survives_torn_newest_step(self, tmp_path):
        """Kill-anywhere guarantee: the newest checkpoint is torn (the
        crash hit mid-save) — resume transparently falls back to the
        previous complete step and stays bit-exact from there."""
        _, s_ref = _sharded()
        ref = self._run(s_ref, 0, 6)
        _, s_a = _sharded()
        first = self._run(s_a, 0, 3)
        ckpt.save_train_checkpoint(s_a, str(tmp_path))     # step 3, good
        self._run(s_a, 3, 4)
        with fault.scope("ckpt.write:step=1:mode=truncate"):
            with pytest.raises(IOError):
                ckpt.save_train_checkpoint(s_a, str(tmp_path))  # torn
        _, s_b = _sharded(seed=31337)
        meta = ckpt.restore_train_checkpoint(s_b, str(tmp_path))
        assert meta["step_count"] == 3    # fell back past the torn dir
        rest = self._run(s_b, 3, 6)
        assert ref == first + rest

    def test_hapi_eager_path_resume_parity(self, tmp_path):
        """jit=True with a multi-label loss falls through to hapi's
        EAGER train path; train_state must capture the eager optimizer
        accumulators (not a never-used TrainStep's fresh zeros) and the
        restore must follow the same branch — bit-exact."""
        from paddle_tpu.hapi.model import Model

        def loss2(out, y, w):
            return paddle.nn.functional.mse_loss(out * w, y * w)

        def make(seed=7):
            paddle.seed(seed)
            m = Model(MLP())
            opt = paddle.optimizer.AdamW(
                1e-2, parameters=m.parameters(), weight_decay=0.1)
            m.prepare(opt, loss2)          # jit=True (the default)
            return m

        ones = paddle.to_tensor(np.ones((4, 1), np.float32))

        def run(m, lo, hi):
            out = []
            for i in range(lo, hi):
                x, y = _batch(i)
                out.append(m.train_batch([x], [y, ones])[0])
            return out

        ref = run(make(), 0, 6)
        m_a = make()
        first = run(m_a, 0, 3)
        ckpt.save_train_checkpoint(m_a, str(tmp_path))
        paddle.seed(999)
        m_b = make(seed=31337)
        meta = ckpt.restore_train_checkpoint(m_b, str(tmp_path))
        assert meta["hapi_path"] == "eager"
        rest = run(m_b, 3, 6)
        assert ref == first + rest


# ---------------------------------------------------------------------------
# nonfinite step guard
# ---------------------------------------------------------------------------

@pytest.fixture
def _guard_flags():
    paddle.set_flags({"FLAGS_skip_nonfinite_steps": True})
    yield
    paddle.set_flags({"FLAGS_skip_nonfinite_steps": False,
                      "FLAGS_max_consecutive_bad_steps": 8})


class TestNonfiniteGuard:
    def test_nan_step_skipped_params_untouched(self, _guard_flags):
        """Planted NaN batch: the step's loss is nonfinite, params and
        optimizer state stay EXACTLY as before, training continues."""
        m, s = _sharded()
        x, y = _batch(0)
        s(x, y)
        snap = {n: np.asarray(t.value).copy()
                for n, t in m.state_dict().items()}
        states = [{k: np.asarray(v).copy() for k, v in st.items()}
                  for st in s._opt_states]
        with fault.scope("step.data:step=1:mode=nan"):
            x, y = _batch(1)
            bad = float(np.asarray(s(x, y).value))
        assert not np.isfinite(bad)
        for n, t in m.state_dict().items():
            np.testing.assert_array_equal(np.asarray(t.value), snap[n])
        for st, st0 in zip(s._opt_states, states):
            for k in st0:
                np.testing.assert_array_equal(np.asarray(st[k]), st0[k])
        x, y = _batch(2)
        assert np.isfinite(float(np.asarray(s(x, y).value)))

    def test_offload_pipeline_nan_step_skipped(self, _guard_flags):
        m, s = _offload()
        x, y = _batch(0)
        s(x, y)
        snap = {k: np.asarray(v).copy() for k, v in s._stk_param.items()}
        with fault.scope("step.data:step=1:mode=nan"):
            x, y = _batch(1)
            bad = float(np.asarray(s(x, y).value))
        assert not np.isfinite(bad)
        for k in snap:
            np.testing.assert_array_equal(np.asarray(s._stk_param[k]),
                                          snap[k])
        x, y = _batch(2)
        assert np.isfinite(float(np.asarray(s(x, y).value)))

    def test_budget_abort_with_diagnostics_and_backoff(self, _guard_flags):
        scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0,
                                       use_dynamic_loss_scaling=True)
        _, s = _sharded(grad_scaler=scaler)
        paddle.set_flags({"FLAGS_max_consecutive_bad_steps": 3})
        with fault.scope("step.data:mode=nan:times=*"):
            with pytest.raises(BadStepBudgetExceeded,
                               match="consecutive nonfinite"):
                for i in range(10):
                    x, y = _batch(i)
                    s(x, y)
        # one backoff per bad step: 1024 * 0.5^3
        assert scaler._scale == 1024.0 * 0.5 ** 3

    def test_transient_spike_resets_budget(self, _guard_flags):
        _, s = _sharded()
        paddle.set_flags({"FLAGS_max_consecutive_bad_steps": 2})
        with fault.scope("step.data:step=2:mode=nan;"
                         "step.data:step=4:mode=nan"):
            for i in range(6):      # bad steps 2 and 4, never 2 in a row
                x, y = _batch(i)
                s(x, y)
        assert s._guard.total_bad == 2
        assert s._guard.consecutive_bad == 0

    def test_flags_off_compiles_no_guard_ops(self):
        _, s = _sharded()
        x, y = _batch(0)
        hlo = s.compiled_hlo(x, y, optimized=False)
        assert "is_finite" not in hlo
        paddle.set_flags({"FLAGS_skip_nonfinite_steps": True})
        try:
            _, s2 = _sharded()
            assert "is_finite" in s2.compiled_hlo(x, y, optimized=False)
        finally:
            paddle.set_flags({"FLAGS_skip_nonfinite_steps": False})

    def test_guard_unit(self):
        g = StepAnomalyGuard(budget=2, name="unit")
        assert g.record(1.0) is False
        assert g.record(float("nan")) is True
        assert g.record(2.0) is False          # streak reset
        g.record(float("inf"))
        with pytest.raises(BadStepBudgetExceeded):
            g.record(float("nan"))


# ---------------------------------------------------------------------------
# KV client retry (satellite)
# ---------------------------------------------------------------------------

class TestKVRetry:
    def test_transient_blips_absorbed(self):
        from paddle_tpu.distributed.launch.master import KVServer, KVClient
        srv = KVServer(0).start()
        try:
            kv = KVClient(f"127.0.0.1:{srv.port}")
            with fault.scope("kv.request:times=2:mode=error"):
                assert kv.put("ft/x", "1") is True   # 3rd attempt lands
            assert kv.get("ft/x") == "1"
            with fault.scope("kv.request:times=*:mode=error"):
                assert kv.put("ft/y", "1") is False  # exhausted: old
                assert kv.get("ft/y") is None        # contract holds
        finally:
            srv.stop()

    def test_heartbeat_rides_retry(self):
        from paddle_tpu.distributed.launch.master import KVServer, KVClient
        srv = KVServer(0).start()
        try:
            kv = KVClient(f"127.0.0.1:{srv.port}")
            with fault.scope("kv.request:step=1:mode=error"):
                assert kv.stamp("hb/pod0") is True
            assert kv.time() is not None
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# watchdog leak (satellite)
# ---------------------------------------------------------------------------

class TestWatchdogLeak:
    def test_raising_body_deregisters(self):
        from paddle_tpu.distributed.watchdog import (watched,
                                                     get_comm_task_manager)
        mgr = get_comm_task_manager()
        paddle.set_flags({"FLAGS_stop_check_timeout": 30})
        try:
            with pytest.raises(ValueError):
                with watched("raises mid-flight"):
                    raise ValueError("boom")
            assert "raises mid-flight" not in mgr.active_tasks()
        finally:
            paddle.set_flags({"FLAGS_stop_check_timeout": 0})

    def test_reentrant_instance_leaks_nothing(self):
        from paddle_tpu.distributed.watchdog import (watched,
                                                     get_comm_task_manager)
        mgr = get_comm_task_manager()
        paddle.set_flags({"FLAGS_stop_check_timeout": 30})
        try:
            w = watched("reused")
            with w:
                with w:
                    pass
            assert "reused" not in mgr.active_tasks()
        finally:
            paddle.set_flags({"FLAGS_stop_check_timeout": 0})

    def test_failed_arming_leaves_no_ghost(self):
        from paddle_tpu.distributed.watchdog import CommTaskManager
        mgr = CommTaskManager()

        def boom():
            raise RuntimeError("thread limit")
        mgr._ensure_thread = boom
        with pytest.raises(RuntimeError):
            mgr.start_task("ghost", timeout=5)
        assert mgr.active_tasks() == []


# ---------------------------------------------------------------------------
# SIGTERM drain protocol — fast in-process twins of the slow e2e test
# ---------------------------------------------------------------------------

class TestSigtermDrainProtocol:
    def _controller(self, tmp_path, cmd):
        import argparse
        from paddle_tpu.distributed.launch.controller import (
            CollectiveController, ProcEntry)
        args = argparse.Namespace(
            master=None, rank=-1, nnodes=1, nnodes_min=1, nnodes_max=1,
            nproc_per_node=1, log_dir=str(tmp_path / "log"),
            job_id="drain-unit", devices=None, max_restart=0,
            elastic_timeout=5, training_script="x.py",
            training_script_args=[])
        c = CollectiveController(args)
        p = ProcEntry(cmd, dict(os.environ),
                      str(tmp_path / "log" / "w.log"), 0)
        p.start()
        c.procs = [p]
        return c

    def test_drain_propagates_elastic_exit(self, tmp_path):
        """begin_drain forwards SIGTERM; a child that checkpoints and
        exits ELASTIC_EXIT_CODE makes the controller exit with it."""
        from paddle_tpu.distributed.launch.controller import \
            ELASTIC_EXIT_CODE
        c = self._controller(
            tmp_path, ["bash", "-c",
                       f"trap 'exit {ELASTIC_EXIT_CODE}' TERM; "
                       "sleep 30 & wait"])
        time.sleep(0.3)
        c.begin_drain()
        deadline = time.time() + 20
        rc = None
        while rc is None and time.time() < deadline:
            time.sleep(0.1)
            rc = c._watch_drain([p.poll() for p in c.procs])
        assert rc == ELASTIC_EXIT_CODE

    def test_drain_grace_expiry_terminates(self, tmp_path):
        """A child that ignores SIGTERM is terminated once the grace
        window lapses; the controller reports the signal death."""
        c = self._controller(
            tmp_path, ["bash", "-c", "trap '' TERM; sleep 30 & wait"])
        time.sleep(0.3)
        c.begin_drain()
        c._drain_deadline = time.time() - 1     # grace already over
        rc = c._watch_drain([p.poll() for p in c.procs])
        assert rc == 128 + 15
        assert c.procs[0].poll() is not None

    def test_drain_flag_roundtrip(self):
        from paddle_tpu.distributed import guard
        assert not guard.drain_requested()
        guard._drain.set()
        try:
            assert guard.drain_requested()
        finally:
            guard.clear_drain()
        assert not guard.drain_requested()

    def test_stale_drain_cleared_on_new_fit(self, tmp_path):
        """The drain event is a sticky process-global: a SIGTERM that
        landed after a PREVIOUS fit finished must not make a fresh fit
        with FaultTolerantCheckpoint emergency-exit at its first
        batch."""
        from paddle_tpu.distributed import guard
        from paddle_tpu.hapi.callbacks import FaultTolerantCheckpoint
        from paddle_tpu.hapi.model import Model

        class DS(paddle.io.Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                rng = np.random.RandomState(i)
                return (rng.randn(8).astype(np.float32),
                        rng.randn(1).astype(np.float32))

        guard._drain.set()          # stale SIGTERM from an earlier run
        try:
            paddle.seed(3)
            m = Model(MLP())
            opt = paddle.optimizer.AdamW(1e-2,
                                         parameters=m.parameters())
            m.prepare(opt, paddle.nn.MSELoss())
            # pre-fix this dies with SystemExit(ELASTIC_EXIT_CODE) at
            # the first on_train_batch_end
            m.fit(DS(), batch_size=4, epochs=1, shuffle=False,
                  verbose=0,
                  callbacks=[FaultTolerantCheckpoint(str(tmp_path))])
            assert not guard.drain_requested()
        finally:
            guard.clear_drain()


# ---------------------------------------------------------------------------
# flags-off zero overhead
# ---------------------------------------------------------------------------

class TestZeroOverhead:
    def test_flags_off_no_ckpt_io_no_fault_hits(self, tmp_path):
        """The flags-off step path performs zero checkpoint IO and
        never consults the armed-fault machinery (bench.py asserts the
        same invariant before every config)."""
        assert not fault.is_active()
        writes = ckpt.WRITE_CALLS
        hits_before = fault.hit_counts()
        _, s = _sharded()
        for i in range(2):
            x, y = _batch(i)
            s(x, y)
        assert ckpt.WRITE_CALLS == writes
        assert fault.hit_counts() == hits_before
