"""Op-coverage audit regression (VERDICT r3 item 4): the checked-in
audit must keep coverage over the bar and leave no uncategorized miss."""
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "tools"))


@pytest.mark.skipif(
    not os.path.exists("/root/reference/paddle/phi/ops/yaml/ops.yaml"),
    reason="reference checkout not present")
def test_ops_yaml_coverage():
    from op_audit import audit
    rows = audit()
    by = {}
    for op, cat in rows:
        by.setdefault(cat, []).append(op)
    total = len(rows)
    covered = len(by.get("covered", []))
    assert covered / total >= 0.70, f"{covered}/{total}"
    assert not by.get("todo"), by.get("todo")
