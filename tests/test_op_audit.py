"""Op-coverage audit regression (VERDICT r3 item 4, r5 item 1): the
checked-in audit must keep coverage over the bar, leave no uncategorized
miss, and prove EXECUTED coverage (ops with passing numeric tests) —
including the fused/sparse yaml tables."""
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "tools"))

_REF = os.path.exists("/root/reference/paddle/phi/ops/yaml/ops.yaml")


@pytest.mark.skipif(not _REF, reason="reference checkout not present")
def test_ops_yaml_coverage():
    from op_audit import audit
    rows = audit()
    by = {}
    executed = 0
    for op, cat, ex in rows:
        by.setdefault(cat, []).append(op)
        if ex and cat == "covered":
            executed += 1
    total = len(rows)
    covered = len(by.get("covered", []))
    assert covered / total >= 0.70, f"{covered}/{total}"
    assert not by.get("todo"), by.get("todo")
    # round-5 bar: executed coverage ≥70% of the yaml, and every covered
    # op must have a numeric test behind it
    assert executed / total >= 0.70, f"executed {executed}/{total}"
    assert executed == covered, \
        f"{covered - executed} covered ops lack numeric tests"


@pytest.mark.skipif(not _REF, reason="reference checkout not present")
def test_fused_sparse_yaml_audited():
    from op_audit import audit_fused, audit_sparse
    frows = audit_fused()
    assert len(frows) >= 70
    f_cov = [op for op, cat, ex in frows if cat == "covered"]
    f_exec = [op for op, cat, ex in frows if cat == "covered" and ex]
    assert f_cov and f_exec == f_cov, set(f_cov) - set(f_exec)
    srows = audit_sparse()
    assert len(srows) >= 45
    s_by = {}
    for op, cat, ex in srows:
        s_by.setdefault(cat, []).append((op, ex))
    assert not s_by.get("todo"), s_by.get("todo")
    cov = s_by.get("covered", [])
    assert len(cov) >= 40
    missing = [op for op, ex in cov if not ex]
    assert not missing, missing


@pytest.mark.skipif(not _REF, reason="reference checkout not present")
def test_specialized_bucket_is_justified():
    """Round-5 verdict item 10: `todo: 0` must be earned — every
    specialized exclusion carries a written justification."""
    from op_audit import SPECIALIZED_OPS
    for op, why in SPECIALIZED_OPS.items():
        assert isinstance(why, str) and len(why) > 20, op
    # the detection core is implemented, not excluded
    for op in ("yolo_box", "box_coder", "prior_box",
               "generate_proposals", "nms", "roi_align"):
        assert op not in SPECIALIZED_OPS, op
