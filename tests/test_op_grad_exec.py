"""Generated gradient verification over the exec-spec table.

Reference: `test/legacy_test/op_test.py:3129 check_grad` — every op's
analytic gradient is checked against a numeric one.  Here the analytic
side is jax autodiff THROUGH the public api and the numeric side is a
directional (dot-product) derivative; see
`paddle_tpu.ops.exec_specs.check_grad_spec`.  Ops in GRAD_CHECK_SKIP
(non-smooth, stochastic, in-place, index-valued) are excluded and remain
forward-only in the audit's backward.yaml accounting.
"""
import pytest

from paddle_tpu.ops.exec_specs import (EXEC_SPECS, GRAD_CHECK_SKIP,
                                       NO_FLOAT_OUTPUT, check_grad_spec)

_ELIGIBLE = [s for s in EXEC_SPECS
             if s.custom is None and s.sample is not None
             and s.op not in GRAD_CHECK_SKIP
             and s.op not in NO_FLOAT_OUTPUT]


@pytest.mark.parametrize("spec", _ELIGIBLE, ids=lambda s: s.op)
def test_grad_matches_directional_derivative(spec):
    ran = check_grad_spec(spec)
    if not ran:
        pytest.skip("no float inputs / no float outputs")


def test_eligible_count_does_not_regress():
    """The grad-checked surface only grows: 190 specs ran the check at
    round 5 (audit backward.yaml 'numerically executed' relies on it)."""
    assert len(_ELIGIBLE) >= 190


class TestSkipListedGradsAtSafePoints:
    """Ops excluded from the generic sweep because their SAMPLE sits at
    a kink (dist: x==y) or an FD step crosses a selection boundary
    (reduce max/min): verify their gradients at constructed points
    where the closed form is unambiguous."""

    def test_reduce_max_grad_is_argmax_one_hot(self):
        import numpy as np
        import paddle_tpu as paddle
        x = paddle.to_tensor(np.array([[0., 2., 1.],
                                       [5., -1., 3.]], np.float32))
        x.stop_gradient = False
        paddle.max(x, axis=1).sum().backward()
        np.testing.assert_allclose(
            np.asarray(x.grad.value),
            [[0., 1., 0.], [1., 0., 0.]])

    def test_reduce_min_grad_is_argmin_one_hot(self):
        import numpy as np
        import paddle_tpu as paddle
        x = paddle.to_tensor(np.array([[0., 2., 1.]], np.float32))
        x.stop_gradient = False
        paddle.min(x, axis=1).sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad.value),
                                   [[1., 0., 0.]])

    def test_dist_grad_is_normalized_difference(self):
        import numpy as np
        import paddle_tpu as paddle
        xv = np.array([3., 0., 4.], np.float32)
        yv = np.zeros(3, np.float32)
        x = paddle.to_tensor(xv)
        x.stop_gradient = False
        paddle.dist(x, paddle.to_tensor(yv), p=2).backward()
        np.testing.assert_allclose(np.asarray(x.grad.value),
                                   xv / 5.0, rtol=1e-6)
