"""Generated gradient verification over the exec-spec table.

Reference: `test/legacy_test/op_test.py:3129 check_grad` — every op's
analytic gradient is checked against a numeric one.  Here the analytic
side is jax autodiff THROUGH the public api and the numeric side is a
directional (dot-product) derivative; see
`paddle_tpu.ops.exec_specs.check_grad_spec`.  Ops in GRAD_CHECK_SKIP
(non-smooth, stochastic, in-place, index-valued) are excluded and remain
forward-only in the audit's backward.yaml accounting.
"""
import pytest

from paddle_tpu.ops.exec_specs import (EXEC_SPECS, GRAD_CHECK_SKIP,
                                       NO_FLOAT_OUTPUT, check_grad_spec)

_ELIGIBLE = [s for s in EXEC_SPECS
             if s.custom is None and s.sample is not None
             and s.op not in GRAD_CHECK_SKIP
             and s.op not in NO_FLOAT_OUTPUT]


@pytest.mark.parametrize("spec", _ELIGIBLE, ids=lambda s: s.op)
def test_grad_matches_directional_derivative(spec):
    ran = check_grad_spec(spec)
    if not ran:
        pytest.skip("no float inputs / no float outputs")


def test_eligible_count_does_not_regress():
    """The grad-checked surface only grows: 190 specs ran the check at
    round 5 (audit backward.yaml 'numerically executed' relies on it)."""
    assert len(_ELIGIBLE) >= 190


class TestSkipListedGradsAtSafePoints:
    """Ops excluded from the generic sweep because their SAMPLE sits at
    a kink (dist: x==y) or an FD step crosses a selection boundary
    (reduce max/min): verify their gradients at constructed points
    where the closed form is unambiguous."""

    def test_reduce_max_grad_is_argmax_one_hot(self):
        import numpy as np
        import paddle_tpu as paddle
        x = paddle.to_tensor(np.array([[0., 2., 1.],
                                       [5., -1., 3.]], np.float32))
        x.stop_gradient = False
        paddle.max(x, axis=1).sum().backward()
        np.testing.assert_allclose(
            np.asarray(x.grad.value),
            [[0., 1., 0.], [1., 0., 0.]])

    def test_reduce_min_grad_is_argmin_one_hot(self):
        import numpy as np
        import paddle_tpu as paddle
        x = paddle.to_tensor(np.array([[0., 2., 1.]], np.float32))
        x.stop_gradient = False
        paddle.min(x, axis=1).sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad.value),
                                   [[1., 0., 0.]])

    def test_dist_grad_is_normalized_difference(self):
        import numpy as np
        import paddle_tpu as paddle
        xv = np.array([3., 0., 4.], np.float32)
        yv = np.zeros(3, np.float32)
        x = paddle.to_tensor(xv)
        x.stop_gradient = False
        paddle.dist(x, paddle.to_tensor(yv), p=2).backward()
        np.testing.assert_allclose(np.asarray(x.grad.value),
                                   xv / 5.0, rtol=1e-6)

    def test_piecewise_constant_ops_have_zero_grad(self):
        """ceil/floor/round/sign: derivative is 0 a.e. — the backward
        must return exact zeros, not NaNs (reference *_grad kernels
        emit zeros)."""
        import numpy as np
        import paddle_tpu as paddle
        for fn in (paddle.ceil, paddle.floor, paddle.round, paddle.sign):
            x = paddle.to_tensor(np.array([0.3, -1.7, 2.2], np.float32))
            x.stop_gradient = False
            fn(x).sum().backward()
            np.testing.assert_array_equal(np.asarray(x.grad.value),
                                          np.zeros(3, np.float32))

    def test_cast_grad_casts_back(self):
        import numpy as np
        import paddle_tpu as paddle
        x = paddle.to_tensor(np.array([1., 2.], np.float32))
        x.stop_gradient = False
        (x.astype("float64") * 3.0).sum().backward()
        g = np.asarray(x.grad.value)
        assert g.dtype == np.float32
        np.testing.assert_allclose(g, [3., 3.])

    def test_complex_real_imag_grads(self):
        """complex/real/imag/as_complex/as_real round-trip grads."""
        import numpy as np
        import paddle_tpu as paddle
        re = paddle.to_tensor(np.array([1., 2.], np.float32))
        im = paddle.to_tensor(np.array([3., 4.], np.float32))
        re.stop_gradient = False
        im.stop_gradient = False
        z = paddle.complex(re, im)
        (paddle.real(z) * 2 + paddle.imag(z) * 5).sum().backward()
        np.testing.assert_allclose(np.asarray(re.grad.value), [2., 2.])
        np.testing.assert_allclose(np.asarray(im.grad.value), [5., 5.])
        # as_complex/as_real reinterpret pair: grad passes through
        p = paddle.to_tensor(np.array([[1., 3.], [2., 4.]], np.float32))
        p.stop_gradient = False
        z2 = paddle.as_complex(p)
        (paddle.as_real(z2) * paddle.to_tensor(
            np.array([[2., 7.], [2., 7.]], np.float32))).sum().backward()
        np.testing.assert_allclose(np.asarray(p.grad.value),
                                   [[2., 7.], [2., 7.]])

    def test_selection_grads_scatter_to_sources(self):
        """topk/kthvalue/mode/argsort-values/nanmedian: gradient routes
        1.0 to each selected source element (reference *_grad scatter
        kernels), checked at distinct-valued points."""
        import numpy as np
        import paddle_tpu as paddle
        xv = np.array([[1., 9., 3., 7.]], np.float32)

        def grad_of(out_fn):
            x = paddle.to_tensor(xv)
            x.stop_gradient = False
            out_fn(x).sum().backward()
            return np.asarray(x.grad.value)

        np.testing.assert_allclose(
            grad_of(lambda x: paddle.topk(x, k=2)[0]),
            [[0., 1., 0., 1.]])
        np.testing.assert_allclose(
            grad_of(lambda x: paddle.kthvalue(x, k=2)[0]),
            [[0., 0., 1., 0.]])
        np.testing.assert_allclose(
            grad_of(lambda x: paddle.sort(x, axis=1) * paddle.to_tensor(
                np.array([[1., 2., 3., 4.]], np.float32))),
            [[1., 4., 2., 3.]])  # sorted position weights route back
        xm = np.array([[5., 5., 2.]], np.float32)
        x = paddle.to_tensor(xm)
        x.stop_gradient = False
        paddle.mode(x, axis=1)[0].sum().backward()
        assert float(np.asarray(x.grad.value).sum()) == 1.0
        # nanmedian of [1, nan, 3] = mean of the two non-NaN values:
        # the gradient scatters exactly 0.5 to each, 0 to the NaN slot
        xn = np.array([[1., np.nan, 3.]], np.float32)
        x = paddle.to_tensor(xn)
        x.stop_gradient = False
        paddle.nanmedian(x, axis=1).sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad.value),
                                   [[0.5, 0.0, 0.5]])

    def test_fill_inplace_detaches_and_zero_grads(self):
        """fill_ severs dependence on the pre-fill value: the recorded
        grad to the producer is exact zeros (reference fill_grad) —
        regression for the raw _value overwrite that left the old
        autograd ref attached."""
        import numpy as np
        import paddle_tpu as paddle
        w = paddle.to_tensor(np.array([2., 3.], np.float32))
        w.stop_gradient = False
        x = w * 5.0
        x.fill_(7.0)
        (x * x).sum().backward()
        assert w.grad is None or not np.asarray(w.grad.value).any()
        np.testing.assert_allclose(np.asarray(x.value), [7., 7.])
        # a filled requires-grad tensor STAYS a trainable leaf: grads
        # accumulate on it and a second backward works
        p = paddle.to_tensor(np.array([9., 9.], np.float32))
        p.stop_gradient = False
        p.fill_(1.0)
        (p * 2.0).sum().backward()
        np.testing.assert_allclose(np.asarray(p.grad.value), [2., 2.])
        p.clear_grad()
        (p * 3.0).sum().backward()
        np.testing.assert_allclose(np.asarray(p.grad.value), [3., 3.])

    def test_repeat_interleave_size1_tensor_reps_broadcasts(self):
        import numpy as np
        import paddle_tpu as paddle
        x = paddle.to_tensor(np.array([1., 2., 3.], np.float32))
        out = paddle.repeat_interleave(x, paddle.to_tensor(
            np.array([2], np.int64)))
        np.testing.assert_allclose(np.asarray(out.value),
                                   [1., 1., 2., 2., 3., 3.])

    def test_view_dtype_grad_bitcasts_back(self):
        """view(dtype) reinterprets bits; the cotangent must come back
        through the inverse reinterpret (reference view_dtype_grad),
        not jax's zero bitcast gradient."""
        import numpy as np
        import paddle_tpu as paddle
        xv = np.array([1.5, -2.25], np.float32)
        x = paddle.to_tensor(xv)
        x.stop_gradient = False
        y = paddle.view(x, "uint8")   # 4 bytes each -> shape [8]
        assert tuple(y.shape) == (8,)
        # float32 -> float32 view is identity incl. gradient
        x2 = paddle.to_tensor(xv)
        x2.stop_gradient = False
        (paddle.view(x2, "float32") * 3.0).sum().backward()
        np.testing.assert_allclose(np.asarray(x2.grad.value), [3., 3.])

    def test_masked_scatter_grads_to_both_operands(self):
        """masked_scatter_grad: x gets zeros at masked slots, value
        gets the masked cotangents (reference masked_scatter_grad)."""
        import numpy as np
        import paddle_tpu as paddle
        x = paddle.to_tensor(np.array([1., 2., 3.], np.float32))
        v = paddle.to_tensor(np.array([10., 20., 30.], np.float32))
        x.stop_gradient = False
        v.stop_gradient = False
        mask = paddle.to_tensor(np.array([True, False, True]))
        out = paddle.masked_scatter(x, mask, v)
        (out * paddle.to_tensor(
            np.array([2., 5., 7.], np.float32))).sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad.value),
                                   [0., 5., 0.])
        np.testing.assert_allclose(np.asarray(v.grad.value),
                                   [2., 7., 0.])

    def test_repeat_interleave_tensor_reps_grad_accumulates(self):
        import numpy as np
        import paddle_tpu as paddle
        x = paddle.to_tensor(np.array([1., 2.], np.float32))
        x.stop_gradient = False
        reps = paddle.to_tensor(np.array([2, 3], np.int64))
        paddle.repeat_interleave(x, reps).sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad.value), [2., 3.])

    def test_bool_mask_getitem_grad_scatters(self):
        import numpy as np
        import paddle_tpu as paddle
        x = paddle.to_tensor(np.array([1., -2., 3., -4.], np.float32))
        x.stop_gradient = False
        mask = paddle.to_tensor(np.array([True, False, False, True]))
        (x[mask] * paddle.to_tensor(
            np.array([3., 9.], np.float32))).sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad.value),
                                   [3., 0., 0., 9.])

    def test_dropout_grad_is_scaled_mask(self):
        """dropout_grad: dx = dy · mask/(1-p) — equals y/x wherever
        x != 0 for the same drawn mask."""
        import numpy as np
        import paddle_tpu as paddle
        paddle.seed(123)
        xv = np.full((64,), 2.0, np.float32)
        x = paddle.to_tensor(xv)
        x.stop_gradient = False
        y = paddle.nn.functional.dropout(x, p=0.5, training=True)
        y.sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad.value),
                                   np.asarray(y.value) / xv, rtol=1e-6)

    def test_rnn_family_grads_match_directional_derivative(self):
        """lstm/gru/rnn grads via the dot-product test on the layer
        forward (smooth tanh/sigmoid cells)."""
        import numpy as np
        import jax
        import jax.numpy as jnp
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        for layer_cls in (nn.LSTM, nn.GRU, nn.SimpleRNN):
            paddle.seed(11)
            layer = layer_cls(8, 16)
            xv = np.random.RandomState(0).randn(2, 5, 8).astype(
                np.float32)
            d = np.random.RandomState(1).randn(2, 5, 8).astype(
                np.float64) * 0.1

            def scalar(arr):
                out = layer(paddle.framework.tensor.Tensor(arr))[0]
                return jnp.sum(out.value.astype(jnp.float32))

            g = jax.grad(scalar)(jnp.asarray(xv))
            ad = float(np.sum(np.asarray(g, np.float64) * d))
            eps = 1e-2
            fd = (float(scalar(jnp.asarray(xv + eps * d, jnp.float32)))
                  - float(scalar(jnp.asarray(xv - eps * d,
                                             jnp.float32)))) / (2 * eps)
            assert abs(fd - ad) <= 3e-2 * max(1.0, abs(fd), abs(ad)), \
                (layer_cls.__name__, fd, ad)

    def test_fft_grads_match_directional_derivative(self):
        """fft_r2c/c2c/c2r grads through |spectrum|² energy."""
        import numpy as np
        import jax
        import jax.numpy as jnp
        import paddle_tpu as paddle
        xv = np.random.RandomState(3).randn(16).astype(np.float32)
        d = np.random.RandomState(4).randn(16).astype(np.float64)

        def scalar(arr):
            t = paddle.framework.tensor.Tensor(arr)
            spec = paddle.fft.fft(t)           # c2c on real-cast input
            rspec = paddle.fft.rfft(t)         # r2c
            back = paddle.fft.irfft(rspec, n=16)  # c2r
            return (jnp.sum(jnp.abs(spec.value) ** 2).astype(jnp.float32)
                    + jnp.sum(jnp.abs(rspec.value) ** 2)
                    + jnp.sum(back.value ** 2)).astype(jnp.float32)

        g = jax.grad(scalar)(jnp.asarray(xv))
        ad = float(np.sum(np.asarray(g, np.float64) * d))
        eps = 1e-3
        fd = (float(scalar(jnp.asarray(xv + eps * d, jnp.float32)))
              - float(scalar(jnp.asarray(xv - eps * d, jnp.float32)))) \
            / (2 * eps)
        assert abs(fd - ad) <= 3e-2 * max(1.0, abs(fd), abs(ad)), (fd, ad)
