"""Generated gradient verification over the exec-spec table.

Reference: `test/legacy_test/op_test.py:3129 check_grad` — every op's
analytic gradient is checked against a numeric one.  Here the analytic
side is jax autodiff THROUGH the public api and the numeric side is a
directional (dot-product) derivative; see
`paddle_tpu.ops.exec_specs.check_grad_spec`.  Ops in GRAD_CHECK_SKIP
(non-smooth, stochastic, in-place, index-valued) are excluded and remain
forward-only in the audit's backward.yaml accounting.
"""
import pytest

from paddle_tpu.ops.exec_specs import (EXEC_SPECS, GRAD_CHECK_SKIP,
                                       check_grad_spec)

_ELIGIBLE = [s for s in EXEC_SPECS
             if s.custom is None and s.sample is not None
             and s.op not in GRAD_CHECK_SKIP]


@pytest.mark.parametrize("spec", _ELIGIBLE, ids=lambda s: s.op)
def test_grad_matches_directional_derivative(spec):
    ran = check_grad_spec(spec)
    if not ran:
        pytest.skip("no float inputs / no float outputs")


def test_eligible_count_does_not_regress():
    """The grad-checked surface only grows: 190 specs ran the check at
    round 5 (audit backward.yaml 'numerically executed' relies on it)."""
    assert len(_ELIGIBLE) >= 190
