"""paddle_tpu.device — reference: python/paddle/device/.

Stream/event APIs are no-op shims: XLA owns scheduling on TPU (there is no
user-visible stream model; the reference's stream-safe allocator and event
machinery have no TPU analog).
"""
from __future__ import annotations

import contextlib

from ..framework.device import (Place, CPUPlace, TPUPlace, CUDAPlace,
                                XPUPlace, set_device, get_device,
                                get_all_devices, is_compiled_with_cuda,
                                is_compiled_with_rocm, is_compiled_with_xpu,
                                device_count, cuda_device_count)

__all__ = ["set_device", "get_device", "get_all_devices",
           "get_available_device", "get_available_custom_device",
           "is_compiled_with_cuda", "is_compiled_with_rocm",
           "is_compiled_with_xpu", "Stream", "Event", "synchronize",
           "stream_guard", "current_stream", "device_count", "cuda"]


def get_available_device():
    return get_all_devices()


def get_available_custom_device():
    return []


def synchronize(device=None):
    import jax
    (jax.device_put(0) + 0).block_until_ready()


class Stream:
    """No-op stream shim (XLA owns ordering on TPU)."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, device=None, enable_timing=False, blocking=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


_current_stream = Stream()


def current_stream(device=None):
    return _current_stream


@contextlib.contextmanager
def stream_guard(stream):
    yield


class cuda:
    """paddle.device.cuda shim (maps to the accelerator)."""
    Stream = Stream
    Event = Event

    @staticmethod
    def device_count():
        return 0

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def current_stream(device=None):
        return _current_stream

    @staticmethod
    def stream_guard(stream):
        return stream_guard(stream)

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def memory_allocated(device=None):
        """Live HBM bytes on the accelerator (reference: phi memory
        stats facade `memory_allocated`); PJRT device stats when the
        runtime exposes them, else a live-array census."""
        return _device_mem_stat("bytes_in_use")

    @staticmethod
    def max_memory_allocated(device=None):
        return _device_mem_stat("peak_bytes_in_use")

    @staticmethod
    def memory_reserved(device=None):
        return _device_mem_stat("bytes_reserved")

    @staticmethod
    def max_memory_reserved(device=None):
        return _device_mem_stat("peak_bytes_in_use")


def _device_mem_stat(key):
    import jax
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        if key in stats:
            return int(stats[key])
    except Exception:
        stats = {}
    if key.startswith("peak"):
        # runtimes without peak counters: fall back to the live census
        key = "bytes_in_use"
    if key in stats:
        return int(stats[key])
    total = 0
    for a in jax.live_arrays():
        total += a.size * a.dtype.itemsize
    return total
