"""Serve-fleet router (ISSUE 15) — prefix-aware, SLO-aware routing
across N `ContinuousBatcher` replicas with lossless drain-and-requeue.

The single-host batcher already carries the whole r12/r13/r15 serving
story (paged prefix-shared KV, SLO admission + shedding + drain,
speculative decode, streaming); this module is the layer ABOVE it —
the millions-of-users architecture of ROADMAP item 2: a `ServeRouter`
fronting N replicas, each its own batcher with its own KV pool, slots
and queues.  Reference shape: the disaggregated multi-replica serving
designs in the Orca/vLLM lineage (continuous batching + paged KV as
the per-replica substrate, a prefix-cache-aware scheduler on top).

Routing policy (``pick_replica`` — a pure function over per-replica
policy views, unit-testable with synthetic stats):

  1. **prefix affinity** — every replica's `PageAllocator` trie is
     probed READ-ONLY for the longest resident prefix of the incoming
     prompt (`ContinuousBatcher.prefix_match_len`: no page pinned, no
     LRU touch).  Hit tokens are prefill work the route would skip,
     weighted by ``FLAGS_router_prefix_weight``.
  2. **load/SLO balance** — the score subtracts queue depth and shed
     rate (in token-cost units), ties break deterministically by
     (fewer queued, fewer active, lowest replica index).  The r13 SLO
     classes are honored end-to-end: an interactive request never
     routes to a replica whose interactive attainment sits below
     ``FLAGS_router_attainment_floor`` while another candidate has
     headroom; draining/dead replicas are never picked.

Drain-and-requeue (the r13 contract lifted fleet-wide): on replica
SIGTERM/kill the router harvests what finished, then requeues the
replica's queued AND non-terminal in-flight requests onto survivors AT
ARRIVAL POSITION — the router assigns GLOBAL arrival numbers, so FIFO
within an SLO class is fleet-consistent across migrations.  Greedy
decode is deterministic, so a migrated request's re-decode is
bit-exact vs a fault-free run (``chaos_check --serve`` replica-kill
specs pin this), and a STREAMING request keeps its delivered prefix:
the router's dedup wrapper replays the survivor's re-decode against
the tokens already handed out and forwards only the new suffix — no
duplicate delivery, ever.

Replica-per-rank mode rides the existing ``distributed/launch``
KVClient/KVServer plane, reusing the r14 FleetSink key schema:
``ReplicaPublisher`` PUTs each replica's ``router_view()`` under
``<job>/serve/<replica>/latest`` (+ a master-clock heartbeat stamp),
``discover_replicas`` reads them back, and ``pick_replica`` runs the
same policy over the discovered views — discovery, heartbeat and
per-replica stats publication share one store with the train fleet.

Everything here is HOST-plane control flow: no compiled program, cache
key or donation contract changes — per-replica serve programs remain
exactly 2 per shape (replicas of one geometry share them through the
model-level program cache), and the flags-off single-batcher serve HLO
is byte-identical with this module imported (bench-asserted).
"""
from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ..framework.flags import get_flag
from ..framework.tensor import Tensor
from .serving import ContinuousBatcher, SLO_CLASSES

__all__ = ["ServeRouter", "pick_replica", "ReplicaPublisher",
           "discover_replicas"]

#: load penalty per queued request, in prefix-hit-token units — one
#: queued request costs the route as much as ~a page of skipped
#: prefill buys it (policy scale, overridable per call)
QUEUE_COST_TOKENS = 16.0


# ---------------------------------------------------------------------------
# the policy — a pure function over per-replica views
# ---------------------------------------------------------------------------

def pick_replica(views: List[dict], slo: str = "batch",
                 prefix_weight: Optional[float] = None,
                 attainment_floor: Optional[float] = None,
                 queue_cost: float = QUEUE_COST_TOKENS,
                 prompt=None) -> Optional[int]:
    """Choose one replica for a request of class `slo` from per-replica
    policy views (`ContinuousBatcher.router_view()` dicts, or the same
    records read back off the KV plane) — returns the chosen view's
    ``replica`` id, or None when nothing is routable (every replica
    draining/dead).

    Two-tier, deterministic:

      1. draining/dead replicas are dropped;
      2. interactive traffic drops replicas whose interactive
         attainment sits below the floor WHILE another candidate has
         headroom (at/above it, or no attainment signal yet); if every
         candidate is below the floor the tier is waived — degraded
         service beats no service;
      3. score = prefix_weight * prefix_hit_tokens
                 - queue_cost * queued  - queue_cost * shed_rate,
         ties broken by (fewer queued, fewer active, lowest replica
         id) — byte-for-byte reproducible for a given view list.

    The shed penalty reads the SLIDING-WINDOW rate
    (``shed_rate_window``, ISSUE 19 satellite) when the view carries
    one — current pressure, not lifetime history — and falls back to
    the cumulative ``shed_rate`` for older/synthetic views.

    Cross-replica prefix scores (ISSUE 20): with `prompt`, a view
    that carries no in-process ``prefix_hit_tokens`` but publishes a
    ``trie_digest`` (replica-per-rank mode over the KV plane) is
    scored by `paged_kv.probe_digest` — the advisory hash-chain
    estimate of the prompt's resident depth on that replica.
    """
    if prefix_weight is None:
        prefix_weight = float(get_flag("router_prefix_weight") or 0.0)
    if attainment_floor is None:
        attainment_floor = float(
            get_flag("router_attainment_floor") or 0.0)
    cands = [v for v in views
             if not v.get("draining") and not v.get("dead")]
    if not cands:
        return None
    if slo == "interactive" and attainment_floor > 0:
        def headroom(v):
            att = (v.get("attainment") or {}).get("interactive")
            return att is None or att >= attainment_floor
        floored = [v for v in cands if headroom(v)]
        if floored:
            cands = floored

    def hits(v):
        got = v.get("prefix_hit_tokens")
        if got is None and prompt is not None and v.get("trie_digest"):
            from .paged_kv import probe_digest
            got = probe_digest(v["trie_digest"], prompt,
                               int(v.get("page_size") or 16))
        return float(got or 0)

    def rank(v):
        shed = v.get("shed_rate_window")
        if shed is None:
            shed = v.get("shed_rate") or 0.0
        score = (prefix_weight * hits(v)
                 - queue_cost * float(v.get("queued") or 0)
                 - queue_cost * float(shed))
        return (score, -float(v.get("queued") or 0),
                -float(v.get("active") or 0),
                -int(v.get("replica", 0)))
    return int(max(cands, key=rank)["replica"])


# ---------------------------------------------------------------------------
# router bookkeeping
# ---------------------------------------------------------------------------

class _RouterReq:
    """The router's own record of one global request — everything a
    migration needs to re-place it losslessly: the prompt, the GLOBAL
    arrival number (FIFO across the fleet), the absolute deadline, and
    the streaming dedup state (`delivered` is authoritative across
    incarnations; `seen` counts the CURRENT incarnation's replay)."""
    __slots__ = ("gid", "prompt", "max_new", "slo", "deadline",
                 "arrival", "on_token", "delivered", "seen",
                 "incarnation", "replica", "local_id", "requeues",
                 "done", "shed", "shed_reason")

    def __init__(self, gid, prompt, max_new, slo, deadline, arrival,
                 on_token):
        self.gid = gid
        self.prompt = prompt
        self.max_new = max_new
        self.slo = slo
        self.deadline = deadline        # absolute monotonic, or None
        self.arrival = arrival
        self.on_token = on_token
        self.delivered: List[int] = []  # tokens the consumer HOLDS
        self.seen = 0                   # replay cursor, this incarnation
        self.incarnation = 0
        self.replica: Optional[int] = None
        self.local_id: Optional[int] = None
        self.requeues = 0
        self.done = False
        self.shed = False
        self.shed_reason: Optional[str] = None


class _Replica:
    """One in-process replica handle: the batcher plus the router's
    local-id <-> global-id mapping, the replica's ROLE (host-plane
    metadata the autoscaler flips: "serve", or the item-2 disaggregated
    "prefill"/"decode" split) and per-replica route counters."""
    __slots__ = ("idx", "bat", "dead", "draining", "role", "local2g",
                 "routed", "requeued_in")

    def __init__(self, idx, bat, role: str = "serve"):
        self.idx = idx
        self.bat = bat
        self.dead = False
        self.draining = False
        self.role = role
        self.local2g: Dict[int, int] = {}
        self.routed = 0
        self.requeued_in = 0


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------

class ServeRouter:
    """Front N `ContinuousBatcher` replicas with one submit/run API.

    Construction: either pass pre-built ``batchers=[...]`` (replicas
    may differ in geometry/KV precision) or a `model` plus `replicas=N`
    and batcher kwargs — N batchers are built over the shared model, so
    same-geometry replicas share their 2 compiled serve programs
    through the model-level program cache.  ``replicas=None`` reads
    ``FLAGS_serve_replicas`` (0 -> 2).

    kv/job_id: optional KV plane (endpoint string or
    `launch.master.KVClient`) — every router step publishes each live
    replica's `router_view()` under ``<job_id>/serve/<replica>/latest``
    (the r14 FleetSink key schema) so coordinators/ops discover the
    fleet with `discover_replicas` and replay `pick_replica` offline.

    The router is single-threaded over its replicas (one scheduling
    round steps each replica that has work); submit() may race run()
    from another thread — the batcher's queue lock (ISSUE 15
    satellite) keeps the structure consistent.
    """

    def __init__(self, model=None, replicas: Optional[int] = None,
                 batchers: Optional[List[ContinuousBatcher]] = None,
                 kv=None, job_id: str = "serve",
                 roles: Optional[List[str]] = None, **batcher_kw):
        if batchers is None:
            if model is None:
                raise ValueError("ServeRouter needs a model (plus "
                                 "replicas=N) or explicit batchers=")
            n = int(replicas if replicas is not None
                    else get_flag("serve_replicas") or 0) or 2
            if roles is None and get_flag("serve_disagg", False):
                # FLAGS_serve_disagg default split (ISSUE 20): half the
                # fleet prefills, half decodes — decode gets the odd
                # replica (decode rounds emit chunk tokens per program
                # call vs the admit program's admit_steps, so decode
                # capacity is the scarcer resource on mixed workloads)
                n_pre = max(1, n // 2)
                roles = ["prefill"] * n_pre + ["decode"] * (n - n_pre) \
                    if n >= 2 else ["serve"]
            batchers = [ContinuousBatcher(
                model, role=self._bat_role(roles[i])
                if roles else "unified", **batcher_kw)
                for i in range(n)]
        elif batcher_kw or model is not None or replicas is not None:
            raise ValueError("pass model/replicas/batcher kwargs OR "
                             "batchers=, not both")
        if not batchers:
            raise ValueError("ServeRouter needs >= 1 replica")
        if roles is not None and len(roles) != len(batchers):
            raise ValueError(f"roles= has {len(roles)} entries for "
                             f"{len(batchers)} replicas")
        self._reps = []
        for i, b in enumerate(batchers):
            role = roles[i] if roles else (
                b.role if b.role != "unified" else "serve")
            if b.role != self._bat_role(role):
                b.set_role(self._bat_role(role))
            self._reps.append(_Replica(i, b, role=role))
        self._reqs: Dict[int, _RouterReq] = {}
        self._results: Dict[int, np.ndarray] = {}
        self._next_gid = 0
        self._arrival = 0
        self._completed = 0
        self._shed_count = 0
        self._requeued = 0
        self._rebalanced = 0
        self._kills = 0
        self._prefix_routed = 0
        self._routes = 0
        self._decision_ms: deque = deque(maxlen=4096)
        self._handoffs = 0
        self._handoff_bytes = 0
        self._handoff_ms: deque = deque(maxlen=4096)
        # hand-offs whose import failed (sink raced out of slots/
        # pages): the exported blob outlives even its source replica,
        # retried every sweep until a sink takes it
        self._handoff_staged: deque = deque()
        self._replicate_q: deque = deque()
        self._replicated_pages = 0
        self._last_rebalance = time.monotonic()
        self._draining = False
        self._kv = kv
        self._job = job_id
        self._pubs: List[Optional["ReplicaPublisher"]] = []
        if kv is not None:
            self._pubs = [ReplicaPublisher(kv, job_id=job_id,
                                           replica=r.idx)
                          for r in self._reps]

    # -- introspection -----------------------------------------------------
    @property
    def replicas(self) -> int:
        return len(self._reps)

    @property
    def live_replicas(self) -> int:
        return sum(not r.dead for r in self._reps)

    @property
    def drained(self) -> bool:
        """True once the process-level SIGTERM drain reached the
        fleet — same caller cue as `ContinuousBatcher.drained`."""
        return self._draining

    def _live(self) -> List[_Replica]:
        return [r for r in self._reps if not r.dead]

    @staticmethod
    def _bat_role(role: str) -> str:
        """Router role label -> batcher role knob ("serve" is the
        router's historical name for a unified replica)."""
        return role if role in ("prefill", "decode") else "unified"

    def _disagg_active(self) -> bool:
        return any(r.role in ("prefill", "decode")
                   for r in self._reps if not r.dead)

    def _views(self, prompt=None, exclude: Optional[int] = None,
               admission: bool = False) -> List[dict]:
        # prefix affinity off (weight 0) -> the hit count is
        # multiplied by zero anyway; skip the O(replicas x prompt)
        # trie probes on the routing hot path entirely
        if prompt is not None \
                and not float(get_flag("router_prefix_weight") or 0.0):
            prompt = None
        views = []
        for rep in self._reps:
            if rep.dead or rep.idx == exclude:
                continue
            v = rep.bat.router_view(prompt)
            v["replica"] = rep.idx
            v["role"] = rep.role
            if rep.draining:
                v["draining"] = True
            views.append(v)
        if admission and self._disagg_active():
            # fresh prompts start with a prefill: route them to
            # prefill-capable replicas only — decode replicas receive
            # work through the hand-off plane.  Degraded-fleet
            # fallback: with no prefill-capable replica left, a
            # decode-role replica still admits (its programs run both
            # phases; the role only governs the freeze-at-prompt-end
            # behaviour), which beats shedding
            adm = [v for v in views if v["role"] != "decode"]
            if adm:
                views = adm
        return views

    # -- submission --------------------------------------------------------
    def submit(self, input_ids, max_new_tokens: int = 32,
               slo: str = "batch",
               deadline_ms: Optional[float] = None,
               on_token=None) -> int:
        """Route one request to a replica; returns its GLOBAL id (the
        key of run()'s results).  Same contract as the batcher's
        submit — SLO classes, deadlines (resolved to an absolute
        deadline HERE so a migration never restarts the clock),
        streaming on_token(gid, tokens, done) — plus the routing
        decision: prefix affinity first, load/SLO balance second."""
        ids = np.asarray(input_ids.value
                         if isinstance(input_ids, Tensor)
                         else input_ids, np.int32).reshape(-1)
        if deadline_ms is None:
            deadline_ms = float(get_flag("serve_default_deadline_ms")
                                or 0.0)
        deadline = (time.monotonic() + float(deadline_ms) / 1e3) \
            if deadline_ms and deadline_ms > 0 else None
        gid = self._next_gid
        self._next_gid += 1
        rr = _RouterReq(gid, ids, int(max_new_tokens), slo, deadline,
                        self._arrival, on_token)
        self._arrival += 1
        self._reqs[gid] = rr
        t0 = time.perf_counter()
        views = self._views(ids, admission=True)
        idx = pick_replica(views, slo=slo)
        dt_ms = (time.perf_counter() - t0) * 1e3
        self._decision_ms.append(dt_ms)
        if idx is None:
            # nothing routable (whole fleet draining): terminal no-
            # service, accounted like a batcher-side drain shed — the
            # no-leak contract holds fleet-wide
            self._shed_router(rr, "drain")
            return gid
        chosen = next(v for v in views if v["replica"] == idx)
        hit = int(chosen.get("prefix_hit_tokens") or 0)
        self._routes += 1
        if hit > 0:
            self._prefix_routed += 1
        if int(get_flag("router_migration_budget") or 0) > 0:
            # cache PLACEMENT (ISSUE 20): when another replica holds a
            # longer resident prefix than where load/SLO pressure sent
            # this request, queue a bounded background copy of that
            # prefix TO the chosen replica — traffic pulls hot
            # prefixes to where it lands instead of chasing them
            best = max(views, key=lambda v: float(
                v.get("prefix_hit_tokens") or 0.0))
            bh = int(best.get("prefix_hit_tokens") or 0)
            if best["replica"] != idx and bh > hit and bh > 0:
                self._replicate_q.append(
                    (ids, int(best["replica"]), idx))
        rep = self._reps[idx]
        rep.routed += 1
        self._place(rr, rep)
        from .. import telemetry as _tel
        _tel.counter("router.routed").inc()
        if _tel.active():
            _tel.emit("router.route", req=gid, slo=slo, replica=idx,
                      prefix_hit=hit,
                      queued=int(chosen.get("queued") or 0),
                      decision_ms=round(dt_ms, 4))
        return gid

    def _shed_router(self, rr: _RouterReq, reason: str):
        rr.done = True
        rr.shed = True
        rr.shed_reason = reason
        self._results[rr.gid] = np.asarray(rr.delivered, np.int32)
        self._shed_count += 1
        if rr.on_token is not None:
            try:
                rr.on_token(rr.gid, [], True)
            except Exception:
                from .. import telemetry as _tel
                _tel.counter("serve.callback_errors").inc()
        from .. import telemetry as _tel
        _tel.counter("router.shed").inc()
        if _tel.active():
            _tel.emit("router.shed", req=rr.gid, slo=rr.slo,
                      reason=reason)

    def _make_cb(self, rr: _RouterReq, incarnation: int):
        """Streaming dedup wrapper for one PLACEMENT of a request: the
        replica replays the request's whole output stream (a migrated
        request re-decodes from scratch, bit-exactly), and only tokens
        past the globally-delivered frontier are forwarded — the
        consumer never sees a duplicate across requeues.  A stale
        incarnation (a replica flushing after its request migrated)
        is ignored outright."""
        def cb(_local_id, burst, done):
            if rr.incarnation != incarnation:
                return
            new = []
            for t in burst:
                rr.seen += 1
                if rr.seen > len(rr.delivered):
                    rr.delivered.append(int(t))
                    new.append(int(t))
            if not new and not done:
                return
            rr.on_token(rr.gid, new, done)
        return cb

    def _place(self, rr: _RouterReq, rep: _Replica):
        """Submit `rr` to `rep` and rewrite the created Request to the
        router's GLOBAL coordinates: arrival number (re-sorted to its
        arrival position — fleet-wide FIFO within a class survives
        migrations) and the ABSOLUTE deadline (a migrated request's
        clock never restarts)."""
        if rep.dead or rep.draining:
            # the routing decision raced a drain/kill of its chosen
            # replica (ISSUE 19 satellite: drain_replica landing
            # between pick_replica and the enqueue): re-pick among the
            # survivors instead of parking the request on a replica
            # that stopped accepting routes — it lands on a survivor
            # or sheds only when the WHOLE fleet is draining, exactly
            # the submit-path contract
            views = self._views(rr.prompt, exclude=rep.idx,
                                admission=True)
            idx = pick_replica(views, slo=rr.slo)
            if idx is None:
                self._shed_router(rr, "drain")
                return
            rep = self._reps[idx]
        bat = rep.bat
        cb = None
        if rr.on_token is not None:
            cb = self._make_cb(rr, rr.incarnation)
        # ONE critical section for the enqueue AND the global-arrival/
        # absolute-deadline rewrite (the queue lock is reentrant, so
        # submit's own acquisition nests): a run() thread's admit()
        # must never pop the request in between — it would keep its
        # batcher-local arrival (fleet FIFO broken) and a freshly
        # restarted deadline clock
        with bat._qlock:
            lid = bat.submit(rr.prompt, rr.max_new, slo=rr.slo,
                             deadline_ms=None, on_token=cb)
            rep.local2g[lid] = rr.gid
            rr.replica, rr.local_id = rep.idx, lid
            rr.seen = 0
            q = bat._queues[rr.slo]
            req = next((r for r in q if r.req_id == lid), None)
            if req is not None:
                q.remove(req)
                req.arrival = rr.arrival
                req.deadline = rr.deadline
                if rr.deadline is not None:
                    bat._has_deadlines = True
                i = 0
                while i < len(q) and q[i].arrival <= rr.arrival:
                    i += 1
                q.insert(i, req)
        # shed on arrival (replica-side bounded queue / drain): the
        # terminal state is harvested like any other finish

    # -- scheduling --------------------------------------------------------
    def _harvest(self, rep: _Replica) -> List[int]:
        """Collect `rep`'s newly-terminal requests into the router's
        results (completed and shed both — the no-leak contract)."""
        out = []
        for lid, gid in list(rep.local2g.items()):
            req = rep.bat._finished.get(lid)
            if req is None:
                continue
            rr = self._reqs[gid]
            rr.done = True
            self._results[gid] = req.output()
            if req.shed:
                rr.shed, rr.shed_reason = True, req.shed_reason
                self._shed_count += 1
            else:
                self._completed += 1
            del rep.local2g[lid]
            out.append(gid)
        return out

    # -- the hand-off plane (disaggregated prefill -> decode) --------------
    def _handoff_import(self, meta, data, rr: _RouterReq,
                        sinks: List[_Replica], frm: int,
                        t0: Optional[float] = None) -> bool:
        """Land one exported hand-off on the least-loaded decode-
        capable sink.  The incarnation bump BEFORE the import means
        the decode side's full-stream replay (req.tokens re-seeded
        with the already-emitted prefix) dedups against the delivered
        frontier — the consumer never sees a duplicate token across
        the hand-off.  False when every sink refused (no free slot /
        pool pressure): the caller stages the blob for the next
        sweep."""
        for sink in sorted(sinks, key=lambda s: (s.bat.active, s.idx)):
            rr.incarnation += 1
            rr.seen = 0
            cb = None
            if rr.on_token is not None:
                cb = self._make_cb(rr, rr.incarnation)
            lid = sink.bat.import_handoff(meta, data, on_token=cb)
            if lid is None:
                continue
            sink.local2g[lid] = rr.gid
            rr.replica, rr.local_id = sink.idx, lid
            ms = (time.perf_counter() - t0) * 1e3 if t0 else 0.0
            self._handoffs += 1
            self._handoff_bytes += int(meta.get("nbytes") or 0)
            self._handoff_ms.append(ms)
            from .. import telemetry as _tel
            _tel.counter("router.handoffs").inc()
            if _tel.active():
                _tel.emit("router.handoff", req=rr.gid, frm=frm,
                          to=sink.idx, pages=int(meta["n_pages"]),
                          bytes=int(meta.get("nbytes") or 0),
                          ms=round(ms, 4))
            return True
        return False

    def _handoff_sweep(self):
        """Move finished-prefill requests off their prefill replicas:
        each frozen (hand-off-ready) slot exports its prompt KV pages
        and re-admits on a decode-capable sink at pos=prompt_len —
        zero prefill recomputed.  Exports happen only when some sink
        has a free slot (otherwise the request stays frozen, its pages
        pinned on the source, and retries next sweep); an import that
        still fails (lost the slot race) is staged host-side and
        survives even the source replica dying.  With NO decode-
        capable replica in the fleet the frozen slot unfreezes and
        decodes in place — degraded, never deadlocked."""
        srcs = [r for r in self._live() if r.bat._handoff_ready]
        if not srcs and not self._handoff_staged:
            return
        sinks = [r for r in self._live()
                 if not r.draining and r.role != "prefill"
                 and r.bat.role != "prefill"
                 and r.bat.kv_layout == "paged"]
        if self._handoff_staged:
            if sinks:
                for _ in range(len(self._handoff_staged)):
                    meta, data, rr = self._handoff_staged.popleft()
                    if rr.done:
                        continue
                    if not self._handoff_import(meta, data, rr, sinks,
                                                frm=-1):
                        self._handoff_staged.append((meta, data, rr))
            elif not any(r.bat.kv_layout == "paged"
                         and r.bat.role != "prefill"
                         for r in self._live()):
                # a staged blob has no source slot left to unfreeze;
                # with no import-capable replica even in the pipeline
                # (draining ones will retire, not recover) the request
                # is terminally unplaceable — shed it like a whole-
                # fleet drain, delivered prefix preserved
                while self._handoff_staged:
                    meta, data, rr = self._handoff_staged.popleft()
                    if not rr.done:
                        self._shed_router(rr, "drain")
        for src in srcs:
            for rid in list(src.bat._handoff_ready):
                gid = src.local2g.get(rid)
                if gid is None:
                    # not router-managed (submitted straight to the
                    # batcher): its owner drives the hand-off
                    continue
                if not sinks:
                    src.bat.unfreeze_handoff(rid)
                    continue
                free = any(s.bat.active - (1 if s is src else 0)
                           < s.bat.B for s in sinks)
                if not free:
                    break
                rr = self._reqs[gid]
                t0 = time.perf_counter()
                meta, data = src.bat.export_handoff(rid)
                del src.local2g[rid]
                if not self._handoff_import(meta, data, rr, sinks,
                                            frm=src.idx, t0=t0):
                    self._handoff_staged.append((meta, data, rr))

    def _maybe_replicate(self):
        """FLAGS_router_migration_budget pages per sweep of hot-prefix
        placement: pop queued (prompt, holder, target) intents, export
        the resident chain on the holder and graft it on the target.
        Best-effort end to end — a dead replica, an evicted chain or
        target pool pressure just drops the intent (the next routed
        request re-queues it); the budget caps device-copy bytes per
        round so placement never starves serving."""
        budget = int(get_flag("router_migration_budget") or 0)
        if budget <= 0:
            self._replicate_q.clear()
            return
        pages = 0
        rounds = len(self._replicate_q)
        while self._replicate_q and pages < budget and rounds > 0:
            rounds -= 1
            prompt, frm, to = self._replicate_q.popleft()
            src, dst = self._reps[frm], self._reps[to]
            if src.dead or dst.dead:
                continue
            got = src.bat.export_prefix(prompt)
            if not got:
                continue
            n_tokens, data = got
            n = dst.bat.import_prefix(prompt, n_tokens, data)
            if n <= 0:
                continue
            pages += n
            self._replicated_pages += n
            from .. import telemetry as _tel
            _tel.counter("router.replicated_pages").inc(n)
            if _tel.active():
                _tel.emit("router.replicate", frm=frm, to=to,
                          pages=n, tokens=int(n_tokens))

    def step(self) -> List[int]:
        """One scheduling round across the fleet: every live replica
        with work runs one batcher round; newly-terminal global ids
        are returned.  A replica whose own drain protocol engaged
        (process-level SIGTERM) marks the router drained; a
        gracefully-draining replica with nothing left is retired
        (frozen hand-off-ready slots count as active, so a draining
        prefill replica exports them before retiring)."""
        finished: List[int] = []
        # placement BEFORE the batcher round: a prefix replicated now
        # is shared by this very round's admissions (grafting after
        # the admit would lose the race to the admit's own trie
        # registration and no-op)
        self._maybe_replicate()
        for rep in self._live():
            bat = rep.bat
            if bat.queued or bat.active:
                bat.step()
            finished += self._harvest(rep)
            if bat.drained:
                self._draining = True
            if rep.draining and not bat.queued and not bat.active:
                rep.dead = True
                self._retire_pub(rep)
        self._handoff_sweep()
        self._maybe_rebalance()
        self._publish()
        return finished

    def run(self) -> Dict[int, np.ndarray]:
        """Drive the fleet until every replica's queue and slots drain;
        returns {gid: tokens} for EVERY submitted request (shed ones
        included — empty or partial outputs), exactly the batcher's
        run() contract lifted fleet-wide.  Staged hand-offs count as
        live work: their requests occupy no slot anywhere until a sink
        admits them."""
        while any(r.bat.queued or r.bat.active
                  for r in self._live()) or self._handoff_staged:
            self.step()
        for rep in self._live():
            self._harvest(rep)
        return dict(self._results)

    # -- drain-and-requeue (the r13 contract, fleet-wide) ------------------
    def kill_replica(self, idx: int, reason: str = "kill") -> int:
        """Replica `idx` died (SIGTERM'd subprocess, poisoned host):
        harvest what it finished, collect its queued AND non-terminal
        in-flight requests, retire it, and requeue the collected
        requests onto survivors at their ARRIVAL POSITIONS.  Greedy
        re-decode is bit-exact, and streaming requests keep their
        delivered prefix (the dedup wrapper never re-sends it).
        Returns the number of migrated requests."""
        rep = self._reps[idx]
        if rep.dead:
            return 0
        self._harvest(rep)
        bat = rep.bat
        pending = []
        with bat._qlock:
            for cls in SLO_CLASSES:
                q = bat._queues[cls]
                while q:
                    pending.append(q.popleft())
            for i, req in enumerate(bat._slots):
                if req is not None:
                    pending.append(req)
                    bat._slots[i] = None    # host detach only: the
                    #                         replica is dead, its
                    #                         device state unreachable
            # frozen hand-off-ready slots are swept with the rest
            # (their requests migrate for a full re-prefill, which is
            # bit-exact); the ready-map must not dangle
            bat._handoff_ready.clear()
        rep.dead = True
        self._kills += 1
        migs = []
        for req in pending:
            gid = rep.local2g.pop(req.req_id, None)
            if gid is not None:
                migs.append(self._reqs[gid])
            else:
                # not router-managed (submitted straight to the
                # batcher): the router cannot re-place it, but it must
                # not vanish — shed it through the batcher so ITS
                # no-leak accounting (and any direct caller's run())
                # stays whole
                bat._shed(req, "drain")
        migs.sort(key=lambda r: r.arrival)
        self._retire_pub(rep)
        from .. import telemetry as _tel
        _tel.counter("router.kills").inc()
        if _tel.active():
            _tel.emit("router.kill", replica=idx, reason=reason,
                      migrated=len(migs))
        for rr in migs:
            self._migrate(rr, frm=idx)
        return len(migs)

    def drain_replica(self, idx: int) -> int:
        """Graceful replica drain (the planned-maintenance half):
        queued requests migrate to survivors NOW, in-flight decodes
        finish on the replica (it stops receiving routes), and the
        replica retires once empty — nothing is lost, nothing
        re-decoded.  Returns the number of migrated requests."""
        rep = self._reps[idx]
        if rep.dead or rep.draining:
            return 0
        rep.draining = True
        bat = rep.bat
        pending = []
        with bat._qlock:
            for cls in SLO_CLASSES:
                q = bat._queues[cls]
                while q:
                    pending.append(q.popleft())
        migs = []
        unmapped = []
        for req in pending:
            gid = rep.local2g.pop(req.req_id, None)
            if gid is not None:
                migs.append(self._reqs[gid])
            else:
                unmapped.append(req)
        if unmapped:
            # not router-managed: leave them queued on the draining
            # replica — it keeps stepping until empty, so they finish
            # there (unlike a kill, nothing is lost by waiting)
            with bat._qlock:
                for req in unmapped:
                    q = bat._queues[req.slo]
                    i = 0
                    while i < len(q) and q[i].arrival < req.arrival:
                        i += 1
                    q.insert(i, req)
        migs.sort(key=lambda r: r.arrival)
        from .. import telemetry as _tel
        _tel.counter("router.drains").inc()
        if _tel.active():
            _tel.emit("router.drain", replica=idx,
                      migrated=len(migs))
        for rr in migs:
            self._migrate(rr, frm=idx)
        return len(migs)

    def undrain_replica(self, idx: int) -> bool:
        """Return a DRAINING replica to rotation (the autoscaler's
        rollback half, ISSUE 19): routes flow to it again and it no
        longer retires when empty.  Requests already migrated off it
        stay where they landed (re-migrating them back would re-decode
        for nothing).  False when the replica already retired — a
        retired replica's device state is gone, only add_replica can
        re-grow the fleet."""
        rep = self._reps[idx]
        if rep.dead:
            return False
        if rep.draining:
            rep.draining = False
            from .. import telemetry as _tel
            _tel.counter("router.undrains").inc()
            if _tel.active():
                _tel.emit("router.undrain", replica=idx)
        return True

    def add_replica(self, bat: ContinuousBatcher,
                    role: str = "serve") -> int:
        """Grow the fleet by one live replica (the autoscaler's
        scale-out half, ISSUE 19): `bat` joins the rotation at the next
        routing decision under a fresh replica id.  Same-geometry
        replicas share their 2 compiled serve programs through the
        model-level program cache, so a scale-out compiles nothing.
        With a KV plane attached the new replica publishes under the
        same ``<job>/serve/<idx>`` schema.  Returns the replica id."""
        idx = len(self._reps)
        rep = _Replica(idx, bat, role=role)
        if bat.role != self._bat_role(role) \
                and (self._bat_role(role) == "unified"
                     or bat.kv_layout == "paged"):
            bat.set_role(self._bat_role(role))
        self._reps.append(rep)
        if self._kv is not None:
            self._pubs.append(ReplicaPublisher(self._kv,
                                               job_id=self._job,
                                               replica=idx))
        elif self._pubs:
            self._pubs.append(None)
        from .. import telemetry as _tel
        _tel.counter("router.adds").inc()
        if _tel.active():
            _tel.emit("router.add", replica=idx, role=role)
        return idx

    def set_role(self, idx: int, role: str) -> str:
        """Flip replica `idx`'s role — routing metadata AND the
        batcher's own role knob (host-plane only: no program changes —
        the autoscaler drains before flipping so in-flight work never
        straddles a role change, and a slot already frozen for
        hand-off still leaves via the hand-off sweep).  Returns the
        previous role."""
        rep = self._reps[idx]
        prev, rep.role = rep.role, role
        want = self._bat_role(role)
        if rep.bat.role != want \
                and (want == "unified"
                     or rep.bat.kv_layout == "paged"):
            rep.bat.set_role(want)
        from .. import telemetry as _tel
        if _tel.active():
            _tel.emit("router.role", replica=idx, role=role, prev=prev)
        return prev

    def _retire_pub(self, rep: _Replica):
        """Tombstone a RETIRED replica's KV presence (ISSUE 19
        satellite): its stale published view must never read as a live
        straggling replica to discover_replicas or a fleet
        aggregator."""
        if rep.idx < len(self._pubs) and self._pubs[rep.idx] is not None:
            self._pubs[rep.idx].retire()

    def _migrate(self, rr: _RouterReq, frm: int):
        rr.requeues += 1
        rr.incarnation += 1         # invalidates the old placement's
        rr.seen = 0                 # streaming wrapper
        views = self._views(rr.prompt, exclude=frm, admission=True)
        idx = pick_replica(views, slo=rr.slo)
        if idx is None:
            self._shed_router(rr, "drain")
            return
        rep = self._reps[idx]
        rep.requeued_in += 1
        self._requeued += 1
        self._place(rr, rep)
        from .. import telemetry as _tel
        _tel.counter("router.requeues").inc()
        if _tel.active():
            _tel.emit("router.requeue", req=rr.gid, slo=rr.slo,
                      frm=frm, to=idx,
                      delivered=len(rr.delivered))

    # -- periodic rebalance ------------------------------------------------
    def _pop_newest_queued(self, rep: _Replica) -> Optional[_RouterReq]:
        """Detach `rep`'s lowest-SLO newest-arrival QUEUED request (the
        one that would wait longest — the shed-victim rank, reused for
        the opposite purpose: it migrates instead of dying)."""
        order = {c: i for i, c in enumerate(SLO_CLASSES)}
        bat = rep.bat
        with bat._qlock:
            victim = None
            for cls in SLO_CLASSES:
                for r in bat._queues[cls]:
                    # only router-managed requests are movable — one
                    # submitted straight to the batcher has no global
                    # record and must stay where its caller put it
                    if r.req_id not in rep.local2g:
                        continue
                    if victim is None or (order[r.slo], r.arrival) \
                            > (order[victim.slo], victim.arrival):
                        victim = r
            if victim is None:
                return None
            bat._queues[victim.slo].remove(victim)
        return self._reqs[rep.local2g.pop(victim.req_id)]

    def _maybe_rebalance(self):
        """FLAGS_router_rebalance_ms sweep: while some replica has
        queued work and another sits idle with a free slot, migrate
        the overloaded replica's newest queued request — lossless
        (only never-started requests move; their streaming state is
        empty) and bounded per sweep."""
        ms = float(get_flag("router_rebalance_ms") or 0.0)
        if ms <= 0:
            return
        now = time.monotonic()
        if (now - self._last_rebalance) * 1e3 < ms:
            return
        self._last_rebalance = now
        moved = 0
        while moved < 64:
            live = [r for r in self._live()
                    if not r.draining and not r.bat.drained]
            donors = [r for r in live if r.bat.queued > 0]
            takers = [r for r in live if r.bat.queued == 0
                      and r.bat.active < r.bat.B]
            if not donors or not takers:
                break
            donor = max(donors, key=lambda r: (r.bat.queued, -r.idx))
            taker = min(takers, key=lambda r: (r.bat.active, r.idx))
            if donor is taker:
                break
            rr = self._pop_newest_queued(donor)
            if rr is None:
                break
            rr.incarnation += 1
            rr.seen = 0
            taker.requeued_in += 1
            self._place(rr, taker)
            moved += 1
        if moved:
            self._rebalanced += moved
            from .. import telemetry as _tel
            _tel.counter("router.rebalances").inc(moved)
            if _tel.active():
                _tel.emit("router.rebalance", moved=moved)

    # -- KV-plane publication ----------------------------------------------
    def _publish(self):
        if not self._pubs:
            return
        for rep, pub in zip(self._reps, self._pubs):
            if rep.dead or pub is None:
                continue
            v = rep.bat.router_view(digest=True)
            v["role"] = rep.role
            pub.publish(v)

    # -- stats -------------------------------------------------------------
    def stats(self) -> Dict[str, object]:
        """Fleet-level counters: the no-leak partition
        (submitted == completed + shed), routing/requeue accounting
        per replica, the prefix-route hit rate (fraction of routes
        whose chosen replica held a resident prefix) and the routing
        decision-time percentiles — what the `llama_serve_fleet`
        bench and telemetry_report's fleet section consume."""
        from ..telemetry import summary_of
        per = []
        for rep in self._reps:
            rec: Dict[str, object] = {
                "replica": rep.idx, "dead": rep.dead,
                "routed": rep.routed, "requeued_in": rep.requeued_in}
            if not rep.dead:
                rec.update(rep.bat.router_view())
            rec["role"] = rep.role
            if rep.draining:        # router-level drain wins over the
                rec["draining"] = True  # batcher's own SIGTERM flag
            per.append(rec)
        dec = summary_of(list(self._decision_ms))
        hand = summary_of(list(self._handoff_ms))
        cross = 0
        lat: Dict[str, list] = {}
        for rep in self._reps:
            if rep.dead:
                continue
            alloc = getattr(rep.bat, "_alloc", None)
            if alloc is not None:
                cross += int(getattr(alloc, "import_hit_tokens", 0))
            for k, window in rep.bat._lat.items():
                lat.setdefault(k, []).extend(window)
        return {
            "replicas": len(self._reps),
            "live_replicas": self.live_replicas,
            "requests_submitted": self._next_gid,
            "requests_completed": self._completed,
            "requests_shed": self._shed_count,
            "requests_requeued": self._requeued,
            "rebalanced": self._rebalanced,
            "kills": self._kills,
            "routes": self._routes,
            "prefix_routed": self._prefix_routed,
            "prefix_route_hit_rate": round(
                self._prefix_routed / self._routes, 4)
            if self._routes else 0.0,
            "routed_by_replica": {r.idx: r.routed for r in self._reps},
            "requeued_by_replica": {r.idx: r.requeued_in
                                    for r in self._reps},
            "decision_ms": {"count": dec["count"],
                            "p50": round(dec["p50"], 4),
                            "p99": round(dec["p99"], 4),
                            "max": round(dec["max"], 4)},
            "handoffs": self._handoffs,
            "handoff_bytes": self._handoff_bytes,
            "handoff_staged": len(self._handoff_staged),
            "handoff_ms": {"count": hand["count"],
                           "p50": round(hand["p50"], 4),
                           "p99": round(hand["p99"], 4),
                           "max": round(hand["max"], 4)},
            "cross_prefix_hit_tokens": cross,
            "replicated_pages": self._replicated_pages,
            "latency": {k: summary_of(v) for k, v in lat.items()},
            "per_replica": per,
        }


# ---------------------------------------------------------------------------
# replica-per-rank mode: discovery/heartbeat/stats over the launch KV plane
# ---------------------------------------------------------------------------

class ReplicaPublisher:
    """Worker-side publication for the replica-per-rank mode — the r14
    FleetSink key schema on the same `launch.master` KVClient/KVServer
    store that carries train-fleet summaries:

        ``<job>/serve/<replica>/latest``  the replica's router_view()
        ``<job>/serve/<replica>/hb``      master-clock heartbeat stamp

    A subprocess replica calls ``publish(bat.router_view())`` at chunk
    boundaries (one JSON PUT + one stamp — KVClient retries transient
    blips with bounded backoff and never raises); the coordinator's
    `discover_replicas` + `pick_replica` then run the routing policy
    over the fleet without sharing a process with any replica.  The
    replica id defaults to the launcher's PADDLE_TRAINER_ID."""

    def __init__(self, kv, job_id: str = "serve",
                 replica: Optional[int] = None):
        if isinstance(kv, str):
            from ..distributed.launch.master import KVClient
            kv = KVClient(kv)
        self._kv = kv
        self._job = job_id
        if replica is None:
            replica = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.replica = int(replica)

    def publish(self, view: dict) -> bool:
        rec = dict(view, replica=self.replica)
        pre = f"{self._job}/serve/{self.replica}"
        ok = self._kv.put(f"{pre}/latest", json.dumps(rec))
        self._kv.stamp(f"{pre}/hb")
        return bool(ok)

    def retire(self) -> bool:
        """Tombstone this replica on the KV plane (ISSUE 19 satellite):
        a master-clock stamp under ``<job>/serve/<replica>/tombstone``.
        A retired/scaled-in replica stops heartbeating, so without the
        tombstone its last published view would read as a stale live
        replica forever; `discover_replicas` skips tombstoned ids."""
        ok = self._kv.stamp(f"{self._job}/serve/{self.replica}/tombstone")
        return bool(ok)


def discover_replicas(kv, job_id: str = "serve") -> Dict[int, dict]:
    """{replica: latest router_view} discovered from the KV plane —
    the coordinator-side read of ReplicaPublisher's schema.  Records
    that fail to parse are skipped (a torn PUT must not poison the
    fleet view); feed the values straight to `pick_replica` (each
    carries its ``replica`` id)."""
    if isinstance(kv, str):
        from ..distributed.launch.master import KVClient
        kv = KVClient(kv)
    out: Dict[int, dict] = {}
    got = kv.prefix(f"{job_id}/serve")
    dead = set()
    for key in got:
        if key.endswith("/tombstone"):
            try:
                dead.add(int(key.split("/")[-2]))
            except ValueError:
                continue
    for key, raw in got.items():
        if not key.endswith("/latest"):
            continue
        try:
            rec = json.loads(raw)
            rid = int(rec["replica"])
        except (ValueError, KeyError, TypeError):
            continue
        if rid in dead:     # retired (ISSUE 19): the stale last view
            continue        # must not read as a live replica
        out[rid] = rec
    return out
