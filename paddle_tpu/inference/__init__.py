"""Inference serving API — Config / create_predictor / Predictor.

Reference: `paddle/fluid/inference/api/analysis_predictor.h:105`
(AnalysisPredictor), `paddle_inference_api.h` (Config, PaddleTensor,
copy_from_cpu/copy_to_cpu handle protocol) and
`python/paddle/inference/wrapper.py`.

TPU-native: the "analysis + optimization passes" stage IS XLA — the
artifact produced by `paddle.jit.save` is a serialized StableHLO function
(jax.export) that XLA re-compiles (and re-optimises) for whatever device
serves it.  The Predictor keeps the handle-based API so reference serving
code ports 1:1:

    config = Config("model.pdmodel", "model.pdiparams")
    predictor = create_predictor(config)
    h = predictor.get_input_handle(predictor.get_input_names()[0])
    h.copy_from_cpu(batch_np)
    predictor.run()
    out = predictor.get_output_handle(
        predictor.get_output_names()[0]).copy_to_cpu()
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["Config", "Predictor", "create_predictor", "Tensor",
           "PrecisionType", "PlaceType", "get_version",
           "ContinuousBatcher", "Request", "SLO_CLASSES",
           "ServeRouter", "pick_replica", "fleet_serve",
           "pack_handoff", "unpack_handoff"]

from .serving import (ContinuousBatcher, Request, SLO_CLASSES,  # noqa: E402
                      pack_handoff, unpack_handoff)
from .router import ServeRouter, pick_replica  # noqa: E402


def fleet_serve(model=None, replicas=None, **kw) -> ServeRouter:
    """Serve-fleet entry point (ISSUE 15): a `ServeRouter` fronting N
    `ContinuousBatcher` replicas — N from `replicas`, else
    FLAGS_serve_replicas (0 -> 2).  Keyword args are split between the
    router (kv=, job_id=, batchers=) and the batchers (everything
    else: max_batch_size, max_len, chunk, kv_layout, ...).

        router = paddle.inference.fleet_serve(model, replicas=4,
                                              max_batch_size=8)
        gid = router.submit(ids, 128, slo="interactive")
        outs = router.run()
    """
    return ServeRouter(model, replicas=replicas, **kw)


def get_version() -> str:
    from .. import __version__
    return __version__


class PrecisionType:
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


class PlaceType:
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM = 3  # TPU serves through the default jax device


class Config:
    """Reference: AnalysisConfig (`analysis_config.cc`).  GPU/IR-pass
    toggles are accepted for API parity; device placement follows the
    jax backend (TPU when present)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._path = prog_file
        self._params_file = params_file
        self._enable_memory_optim = True
        self._ir_optim = True  # XLA always optimises; kept for parity

    def set_prog_file(self, p):
        self._path = p[: -len(".pdmodel")] if p.endswith(".pdmodel") else p

    def prog_file(self):
        return self._path

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        pass  # device selection is jax's; accepted for parity

    def disable_gpu(self):
        pass

    def enable_memory_optim(self, flag=True):
        self._enable_memory_optim = flag

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def enable_mkldnn(self):
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass

    def enable_low_precision_io(self, flag=True):
        """Cast floating inputs to bfloat16 at the predictor boundary
        (reference: enable_low_precision_io / mixed-precision inference).
        Compute precision itself is baked at export time by the saved
        program's dtypes."""
        self._low_precision_io = flag

    @property
    def low_precision_io(self):
        return getattr(self, "_low_precision_io", False)

    def summary(self):
        import jax
        return ("Config(path={}, device={}, memory_optim={}, "
                "low_precision_io={})".format(
                    self._path, jax.default_backend(),
                    self._enable_memory_optim, self.low_precision_io))


class Tensor:
    """Handle protocol (reference: ZeroCopyTensor / paddle_infer.Tensor):
    copy_from_cpu / copy_to_cpu move data host<->device."""

    def __init__(self, name: str):
        self.name = name
        self._value = None

    def copy_from_cpu(self, arr: np.ndarray):
        self._value = jnp.asarray(np.asarray(arr))

    def reshape(self, shape):
        if self._value is not None:
            self._value = self._value.reshape(shape)

    def copy_to_cpu(self) -> np.ndarray:
        if self._value is None:
            raise RuntimeError(f"tensor '{self.name}' has no data; "
                               "run() the predictor first")
        return np.asarray(self._value)

    def shape(self):
        return list(self._value.shape) if self._value is not None else []

    def type(self):
        return str(self._value.dtype) if self._value is not None else None


class Predictor:
    """Reference: AnalysisPredictor — loads the artifact, owns
    input/output handles, `run()` executes the compiled function."""

    @classmethod
    def from_model(cls, model) -> "Predictor":
        """Serve a live Layer (no artifact round-trip).  Decode-capable
        models (forward_cached/init_cache — e.g. LlamaForCausalLM) gain
        `generate()` with the KV-cached path."""
        self = cls.__new__(cls)
        self._config = None
        self._layer = None
        self._model = model
        self._inputs = {}
        self._outputs = {}
        return self

    def generate(self, input_ids, max_new_tokens=32, **kw):
        """KV-cached autoregressive decode (inference.generation).
        Requires a Predictor built with from_model() on a model with a
        cached decode path."""
        model = getattr(self, "_model", None)
        if model is None or not hasattr(model, "forward_cached"):
            raise NotImplementedError(
                "generate() needs Predictor.from_model(model) with a "
                "decode-capable model (forward_cached/init_cache)")
        from .generation import generate as _gen
        return _gen(model, input_ids, max_new_tokens, **kw)

    def __init__(self, config: Config, _shared_layer=None):
        from ..jit import load as jit_load
        if config._path is None:
            raise ValueError("Config needs the model path")
        self._config = config
        self._model = None
        self._layer = _shared_layer if _shared_layer is not None \
            else jit_load(config._path)
        if self._layer._exported is None:
            raise ValueError(
                f"'{config._path}.pdmodel' holds no compiled function; "
                "export with paddle.jit.save(layer, path, input_spec=...)")
        n_inputs = (len(self._layer._exported.in_avals)
                    - len(self._layer._param_names))
        names = self._layer.input_names or [
            f"x{i}" for i in range(max(n_inputs, 1))]
        self._inputs: Dict[str, Tensor] = {n: Tensor(n) for n in names}
        self._outputs: Dict[str, Tensor] = {}

    def _require_artifact(self, what):
        if self._layer is None:
            raise NotImplementedError(
                f"{what} needs an artifact-backed Predictor "
                "(create_predictor(Config(path))); this one wraps a "
                "live model via from_model() — use generate()")

    def get_input_names(self) -> List[str]:
        self._require_artifact("get_input_names()")
        return list(self._inputs)

    def get_input_handle(self, name: str) -> Tensor:
        self._require_artifact("get_input_handle()")
        return self._inputs[name]

    def run(self, inputs: Optional[list] = None):
        """Execute.  Either feed handles first (reference protocol) or
        pass arrays directly (paddle_infer.Predictor.run(list) style)."""
        self._require_artifact("run()")
        if inputs is not None:
            for h, a in zip(self._inputs.values(), inputs):
                h.copy_from_cpu(np.asarray(a))
        vals = []
        for n, h in self._inputs.items():
            if h._value is None:
                raise RuntimeError(f"input '{n}' not set")
            v = h._value
            if getattr(self._config, "low_precision_io", False) \
                    and jnp.issubdtype(v.dtype, jnp.floating):
                v = v.astype(jnp.bfloat16)
            vals.append(v)
        out = self._layer.forward(*vals)
        outs = out if isinstance(out, (tuple, list)) else [out]
        self._outputs = {}
        result = []
        for i, o in enumerate(outs):
            t = Tensor(f"out{i}")
            t._value = o._value if hasattr(o, "_value") else jnp.asarray(o)
            self._outputs[t.name] = t
            result.append(np.asarray(t._value))
        return result

    def get_output_names(self) -> List[str]:
        return list(self._outputs) or ["out0"]

    def get_output_handle(self, name: str) -> Tensor:
        if name not in self._outputs:
            raise RuntimeError("run() the predictor before reading "
                               f"output '{name}'")
        return self._outputs[name]

    def clear_intermediate_tensor(self):
        pass

    def try_shrink_memory(self):
        pass

    def clone(self):
        """Reference: AnalysisPredictor::Clone — a new predictor with
        its own IO handles SHARING the loaded weights/executable (no
        re-load, no extra HBM)."""
        if self._layer is None:
            return Predictor.from_model(self._model)
        return Predictor(self._config, _shared_layer=self._layer)


class PredictorPool:
    """Reference: paddle_infer.PredictorPool — one loaded model, `size`
    cloned predictors (per-thread handles over shared weights)."""

    def __init__(self, config: Config, size: int = 1):
        first = Predictor(config)
        self._preds = [first] + [first.clone() for _ in range(size - 1)]

    def retrieve(self, idx: int) -> Predictor:
        return self._preds[idx]


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
