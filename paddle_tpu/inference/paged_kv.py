"""Host-side bookkeeping for the serving paged KV cache (ISSUE 7).

The device side (ops.paged_attention / ops.paged_kv_update +
models.llama.init_paged_cache) is pure data plane: a page pool, page
tables, position-masked reads.  Everything stateful lives HERE, on the
host, at chunk boundaries — the vLLM/PagedAttention split, adapted to
the batcher's statically-shaped XLA programs:

  PageAllocator   free-list allocator over the pool (page 0 reserved
                  as the null page), per-page refcounts (number of
                  slots currently mapping the page), and a token-exact
                  prefix TRIE over page-sized prompt chunks.

Prefix sharing: a prompt's full pages are registered in the trie as it
prefills; a later admission whose prompt starts with the same chunks
maps those pages directly (refcount++) and SKIPS their prefill chunks
entirely — pos starts at the shared depth.  K/V for a token depends
only on the preceding tokens, the weights and the rope position, so a
shared page is bit-identical to what the new request would have
written (the serving parity tests pin this).

Copy-on-write at the divergence boundary: when the next chunk matches
a cached page only PARTIALLY (common prefix of m < page_size tokens),
the shared page cannot be mapped read-only — the new request must
write rows m.. of that logical page.  The batcher copies the cached
page into a freshly allocated private page (one device-side page copy)
and the request prefills only from row m, so the matched tokens still
skip recompute.

Lifecycle: pages mapped by live slots have refcount > 0 and are never
reclaimed.  When a request finishes, its trie-registered pages stay
RESIDENT as refcount-0 "cached" pages (the prefix cache); its
decode-area pages free immediately.  Allocation under pressure evicts
cached pages LRU-first (leaf-first, so the trie never dangles) and
counts each reclaimed page in `evictions`; if pressure persists after
the cache is empty, alloc() fails and the batcher defers the
admission — the eviction-under-pressure contract: a pool smaller than
total demand still completes every request, just with fewer resident
at a time.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

__all__ = ["PageAllocator", "AdmitPlan", "probe_digest"]

# FNV-1a over token streams — the cross-replica digest hash.  Chosen
# because it is deterministic across processes (unlike salted hash()),
# dependency-free, and cheap on the short page-sized chunks it sees.
_FNV_SEED = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_FNV_MASK = 0xFFFFFFFFFFFFFFFF


def _fnv(h: int, tokens) -> int:
    for t in tokens:
        v = int(t) & _FNV_MASK
        for _ in range(8):          # 8 LE bytes per token id
            h = ((h ^ (v & 0xFF)) * _FNV_PRIME) & _FNV_MASK
            v >>= 8
    return h


def probe_digest(digest, tokens, page_size: int) -> int:
    """Estimated resident-prefix depth (in tokens) of `tokens` against
    a replica's published trie digest — the advisory cross-replica twin
    of `prefix_match_len`.  A digest entry is ``[depth, chain_hash]``
    where chain_hash is the cumulative FNV-1a of the root→node chunk
    chain; the probe hashes the prompt's own chunk chain and returns
    the deepest published depth it reproduces.  Advisory only: a hash
    collision over-estimates and a bounded digest under-estimates, and
    either way the router just scores the replica slightly wrong —
    admission re-matches token-exactly on arrival."""
    if not digest:
        return 0
    have: Dict[int, set] = {}
    for ent in digest:
        try:
            d, h = int(ent[0]), str(ent[1])
        except (TypeError, ValueError, IndexError):
            continue
        have.setdefault(d, set()).add(h)
    ps = int(page_size)
    cap = len(tokens) - 1        # last prompt token always prefills
    h = _FNV_SEED
    best = 0
    i = 0
    while i + ps <= len(tokens) and i + ps <= cap:
        h = _fnv(h, tokens[i:i + ps])
        i += ps
        if "%016x" % h in have.get(i, ()):
            best = i
    return best


class _Node:
    """One page-sized prompt chunk in the prefix trie."""
    __slots__ = ("tokens", "page", "children", "parent", "complete",
                 "lru", "imported")

    def __init__(self, tokens, page, parent):
        self.tokens = tokens          # tuple of page_size ints
        self.page = page
        self.children: Dict[tuple, "_Node"] = {}
        self.parent = parent          # _Node or None (root child)
        self.complete = False         # all rows written on device
        self.lru = 0
        self.imported = False         # KV arrived from another replica


class AdmitPlan:
    """What one admission decided: the covered page ids (shared prefix
    first, then privates), how many prompt tokens were skipped, an
    optional page copy for a mid-page divergence, and the trie nodes
    registered for the prompt's own chunks (completed as prefill
    advances, removed if the request dies before finishing them).
    `cow`'s SOURCE page arrives pinned (refcounted by admit) so
    pressure cannot reclaim it first — the caller must
    release_page(src) once the device copy is done."""
    __slots__ = ("pages", "shared_tokens", "cow", "nodes",
                 "n_shared_pages")

    def __init__(self, pages, shared_tokens, cow, nodes,
                 n_shared_pages):
        self.pages: List[int] = pages
        self.shared_tokens = shared_tokens
        self.cow: Optional[Tuple[int, int]] = cow   # (src, dst) pages
        self.nodes: List[_Node] = nodes
        self.n_shared_pages = n_shared_pages


class PageAllocator:
    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError("pool needs >= 2 pages (page 0 is the "
                             "reserved null page)")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.NULL = 0
        self._free = list(range(self.num_pages - 1, 0, -1))
        self._ref: Dict[int, int] = {}
        self._node_of: Dict[int, _Node] = {}   # page -> trie node
        self._root: Dict[tuple, _Node] = {}
        self._clock = 0
        self.evictions = 0
        self.prefix_hit_tokens = 0
        self.cow_copies = 0
        # fleet-tier prefix cache: tokens matched against chunks whose
        # KV was imported from another replica (hand-off graft or hot-
        # prefix replication) — the cross-replica hit counter
        self.import_hit_tokens = 0
        self.grafted_pages = 0

    # -- introspection -----------------------------------------------------
    @property
    def pages_free(self) -> int:
        return len(self._free)

    @property
    def pages_used(self) -> int:
        return self.num_pages - 1 - len(self._free)

    @property
    def pages_cached(self) -> int:
        """Refcount-0 pages held resident only by the prefix cache."""
        return sum(1 for p, n in self._node_of.items()
                   if n.complete and self._ref.get(p, 0) == 0)

    # -- allocation --------------------------------------------------------
    def _touch(self, node: _Node):
        self._clock += 1
        node.lru = self._clock

    def _reclaimable(self) -> List[_Node]:
        """Cached LEAF pages, LRU order — leaf-first keeps every
        resident node reachable from the root."""
        out = [n for n in self._node_of.values()
               if n.complete and not n.children
               and self._ref.get(n.page, 0) == 0]
        out.sort(key=lambda n: n.lru)
        return out

    def alloc(self, n: int) -> Optional[List[int]]:
        """n fresh pages (refcount 1 each), evicting cached prefix
        pages LRU-leaf-first under pressure; None if the pool cannot
        serve n even with an empty prefix cache (caller defers).
        Pages the caller has already refcounted (an in-flight
        admission's matched prefix) are never reclaimable.  The victim
        list is computed once and refreshed only when it runs dry
        (dropping a leaf can turn its parent into the next leaf) —
        not re-scanned per evicted page."""
        victims: List[_Node] = []
        vi = 0
        while len(self._free) < n:
            if vi >= len(victims):
                victims, vi = self._reclaimable(), 0
                if not victims:
                    return None
            node = victims[vi]
            vi += 1
            # defensive staleness guard: skip entries invalidated by
            # our own earlier drops this call
            if self._node_of.get(node.page) is not node \
                    or node.children or self._ref.get(node.page, 0):
                continue
            self._drop_node(node)
            self._free.append(node.page)
            self.evictions += 1
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self._ref[p] = 1
        return out

    def _drop_node(self, node: _Node):
        parent_children = node.parent.children if node.parent \
            else self._root
        for key, ch in list(parent_children.items()):
            if ch is node:
                del parent_children[key]
        self._node_of.pop(node.page, None)

    def ref_inc(self, page: int):
        self._ref[page] = self._ref.get(page, 0) + 1

    def release_page(self, page: int):
        """One slot unmaps `page`.  At refcount 0 the page either stays
        resident as a cached prefix page (complete trie node) or goes
        straight back to the free list."""
        r = self._ref.get(page, 0) - 1
        if r > 0:
            self._ref[page] = r
            return
        self._ref.pop(page, None)
        node = self._node_of.get(page)
        if node is None:
            self._free.append(page)
        elif not node.complete:
            # the owning request died before the page filled — the
            # chunk content is not trustworthy, drop it
            self._drop_node(node)
            self._free.append(page)
        else:
            self._touch(node)       # newly cached: most-recent end

    # -- prefix trie -------------------------------------------------------
    def prefix_match_len(self, tokens) -> int:
        """READ-ONLY probe: how many leading tokens of `tokens` are
        already resident as shareable prefix pages (complete full-page
        chunks plus the best mid-page partial match), capped at
        len(tokens)-1 exactly like admit() — the answer is the prefill
        work an admission here would SKIP.

        Pure trie walk: no refcount change, no LRU touch, no CoW, no
        allocation — the serve-fleet router calls this against every
        replica per routed request, so probing must never pin a page
        or perturb the eviction order (regression-pinned)."""
        plen = len(tokens)
        if plen <= 1:
            return 0
        full, partial = self.match_prefix(tokens, max_share=plen - 1)
        return len(full) * self.page_size \
            + (partial[1] if partial is not None else 0)

    def match_prefix(self, tokens, max_share: int):
        """(full_nodes, partial) for `tokens`: full_nodes are complete
        trie nodes matching whole page_size chunks (walk stops at the
        first miss or incomplete node, and at max_share tokens);
        partial is (node, m) for the best mid-page divergence match
        among the next level's children (m < page_size common-prefix
        tokens), or None."""
        ps = self.page_size
        children = self._root
        full: List[_Node] = []
        i = 0
        while i + ps <= len(tokens) and (i + ps) <= max_share:
            child = children.get(tuple(int(t) for t in tokens[i:i + ps]))
            if child is None or not child.complete:
                break
            full.append(child)
            i += ps
            children = child.children
        partial = None
        best = 0
        rest = [int(t) for t in tokens[i:]]
        for chunk, child in children.items():
            if not child.complete:
                continue
            m = 0
            for a, b in zip(rest, chunk):
                if a != b:
                    break
                m += 1
            m = min(m, max_share - i)
            if m > best:
                best, partial = m, (child, m)
        return full, partial

    def register_chunk(self, parent: Optional[_Node], tokens,
                       page: int, imported: bool = False) \
            -> Optional[_Node]:
        """Register `page` as the (pending) trie node for one full
        prompt chunk under `parent`; returns the node, or None when the
        chunk is already registered (a concurrent admission got there
        first — the duplicate page simply stays trie-less)."""
        children = parent.children if parent is not None else self._root
        key = tuple(int(t) for t in tokens)
        if key in children:
            return None
        node = _Node(key, page, parent)
        node.imported = imported
        children[key] = node
        self._node_of[page] = node
        self._touch(node)
        return node

    def complete_node(self, node: _Node):
        node.complete = True
        self._touch(node)

    def remove_node(self, node: _Node):
        """Un-register a pending node (request died mid-prefill)."""
        if self._node_of.get(node.page) is node:
            self._drop_node(node)

    # -- admission ---------------------------------------------------------
    def admit(self, prompt, covered_pages: int,
              imported: bool = False) -> Optional[AdmitPlan]:
        """Plan one admission: match the prompt against the prefix
        cache (capped at len(prompt)-1 so the final prompt token always
        prefills — its logit seeds the first sampled token), allocate
        the private pages, and register pending trie nodes for the
        prompt's own full chunks.  Returns None (nothing allocated or
        registered) when the pool cannot back the request."""
        ps = self.page_size
        plen = len(prompt)
        full, partial = self.match_prefix(prompt, max_share=plen - 1)
        n_shared = len(full)
        shared_tokens = n_shared * ps
        cow_src = None
        if partial is not None and partial[1] > 0:
            cow_src = partial[0]
        n_priv = covered_pages - n_shared
        if n_priv <= 0 and cow_src is not None:
            cow_src = None          # no private page to copy into
        if n_priv < 0:
            # degenerate tiny-prompt corner: more shared pages than
            # coverage — trim the match instead of over-mapping
            full = full[:covered_pages]
            n_shared = len(full)
            shared_tokens = n_shared * ps
            cow_src = None
            n_priv = 0
        # pin the matched pages BEFORE allocating: under pressure the
        # eviction loop must never reclaim the very pages this plan is
        # about to map as shared (or copy from) and recycle them as
        # its own privates — a silent shared/private alias
        for node in full:
            self.ref_inc(node.page)
            self._touch(node)
        if cow_src is not None:
            self.ref_inc(cow_src.page)
            self._touch(cow_src)
        priv = self.alloc(n_priv)
        if priv is None:
            # roll the pins back: complete nodes return to the cached
            # state (release_page re-touches them to the recent end)
            for node in full:
                self.release_page(node.page)
            if cow_src is not None:
                self.release_page(cow_src.page)
            return None
        if cow_src is not None:
            shared_tokens += partial[1]
        self.prefix_hit_tokens += shared_tokens
        # attribute hits on grafted chunks: KV computed on ANOTHER
        # replica, reused here — the fleet-tier cache working
        imp = sum(ps for node in full if node.imported)
        if cow_src is not None and cow_src.imported:
            imp += partial[1]
        self.import_hit_tokens += imp
        pages = [n.page for n in full] + priv
        # pending nodes for the prompt's own full chunks (content is
        # prompt-determined, so future admissions can share them);
        # chunks already shared are existing nodes — walk continues
        # under the LAST matched node
        nodes: List[_Node] = []
        parent = full[-1] if full else None
        for ci in range(n_shared, plen // ps):
            chunk = prompt[ci * ps:(ci + 1) * ps]
            node = self.register_chunk(parent, chunk, pages[ci],
                                       imported=imported)
            if node is None:
                break   # a concurrent admission owns this subtree
            nodes.append(node)
            parent = node
        cow = (cow_src.page, priv[0]) if cow_src is not None else None
        if cow is not None:
            self.cow_copies += 1
        return AdmitPlan(pages, shared_tokens, cow, nodes, n_shared)

    def release_plan(self, plan: AdmitPlan):
        """Request finished (or was aborted): drop its pending nodes
        that never completed, then unmap every covered page."""
        for node in plan.nodes:
            if not node.complete:
                self.remove_node(node)
        for page in plan.pages:
            self.release_page(page)

    def mark_progress(self, plan: AdmitPlan, pos: int):
        """Prefill advanced to `pos` rows: pending nodes whose page is
        now fully written become shareable."""
        ps = self.page_size
        for node in plan.nodes:
            if node.complete:
                continue
            # node i covers logical rows [i*ps, (i+1)*ps) — find its
            # index from the plan's page list
            idx = plan.pages.index(node.page)
            if pos >= (idx + 1) * ps:
                self.complete_node(node)

    # -- fleet-tier prefix cache (ISSUE 20) --------------------------------
    def export_chain(self, tokens) -> Tuple[int, List[int]]:
        """Resident complete full-chunk chain for `tokens`: the page
        list a holder replica would ship when replicating this prefix.
        Read-only (no pins, no LRU touch) — the caller gathers the page
        data synchronously at the same chunk boundary, before any
        allocation can evict."""
        ps = self.page_size
        children = self._root
        pages: List[int] = []
        i = 0
        while i + ps <= len(tokens):
            child = children.get(tuple(int(t) for t in tokens[i:i + ps]))
            if child is None or not child.complete:
                break
            pages.append(child.page)
            i += ps
            children = child.children
        return i, pages

    def graft(self, tokens, max_pages: int) \
            -> Optional[List[Tuple[int, int]]]:
        """Slot-less trie graft for hot-prefix replication: register
        the leading full chunks of `tokens` (up to `max_pages` pages)
        as COMPLETE cached nodes, allocating pages for chunks not
        already resident.  Returns [(chunk_idx, page)] the caller must
        fill with the holder's exported page data before the next
        admission can match them — complete-on-register is safe because
        the device write happens at this same chunk boundary.  Chunks
        already resident are skipped (dedup).  None under pool
        pressure (nothing registered — placement is best-effort and
        must never starve serving)."""
        ps = self.page_size
        n_chunks = min(len(tokens) // ps, max_pages)
        if n_chunks <= 0:
            return []
        # walk the existing chain; count the missing tail
        children = self._root
        parent: Optional[_Node] = None
        i = 0
        while i < n_chunks:
            child = children.get(
                tuple(int(t) for t in tokens[i * ps:(i + 1) * ps]))
            if child is None or not child.complete:
                break
            parent = child
            children = child.children
            i += 1
        missing = n_chunks - i
        if missing <= 0:
            return []
        # pin the deepest matched node: it is a cached LEAF until the
        # new children are registered, and alloc()'s eviction loop must
        # not reclaim the very chain we are extending
        pin = parent
        if pin is not None:
            self.ref_inc(pin.page)
        pages = self.alloc(missing)
        if pages is None:
            if pin is not None:
                self.release_page(pin.page)
            return None
        out: List[Tuple[int, int]] = []
        for k in range(missing):
            ci = i + k
            chunk = tokens[ci * ps:(ci + 1) * ps]
            node = self.register_chunk(parent, chunk, pages[k],
                                       imported=True)
            if node is None:        # raced: subtree already owned
                for page in pages[k:]:
                    self.release_page(page)
                break
            self.complete_node(node)
            out.append((ci, pages[k]))
            parent = node
        # grafted pages are cache-resident, not slot-mapped: drop the
        # alloc refcount so they live as refcount-0 cached pages
        for _, page in out:
            self.release_page(page)
        if pin is not None:
            self.release_page(pin.page)
        self.grafted_pages += len(out)
        return out

    def trie_digest(self, max_entries: int = 32) -> List[list]:
        """Bounded published view of the prefix cache: up to
        `max_entries` ``[depth_tokens, chain_hash]`` entries for
        complete trie nodes, most-recently-used first — what a replica
        ships in its `router_view` so peers can score cross-replica
        prefix affinity with `probe_digest` without a token-level RPC.
        Pure walk: no pins, no LRU touch."""
        if max_entries <= 0:
            return []
        ps = self.page_size
        entries: List[Tuple[int, int, int]] = []   # (lru, depth, hash)
        stack = [(node, _fnv(_FNV_SEED, node.tokens), ps)
                 for node in self._root.values()]
        while stack:
            node, h, depth = stack.pop()
            if node.complete:
                entries.append((node.lru, depth, h))
            for child in node.children.values():
                stack.append((child, _fnv(h, child.tokens), depth + ps))
        entries.sort(key=lambda e: -e[0])
        return [[depth, "%016x" % h]
                for _, depth, h in entries[:max_entries]]
