"""KV-cached autoregressive generation — the serving decode path.

Reference: `python/paddle/incubate/nn/functional/
block_multihead_attention.py` (paged-KV decode attention) and
paddlenlp's GenerationMixin.generate.

TPU-native design: the ENTIRE generation — prefill over the prompt plus
a `lax.scan` over max_new_tokens decode steps — is ONE jitted program.
On a tunneled/remote accelerator a per-token host loop would pay
~10 ms dispatch per token (the measured relay latency that motivated
TrainStep.run_steps); the scanned program pays it once.  The KV cache
is a static-shape fixed-size buffer per layer sized to
prompt+max_new_tokens (XLA requires static shapes; "paged" blocks buy
nothing on TPU where the compiler owns layout), and
decode attention is one batched masked GEMV (ops.cached_attention — a
Pallas q_len==1 kernel would be grid-overhead-bound, see
ops/pallas/flash_attention.py packed-path notes).
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..framework import random as prandom

__all__ = ["generate"]


def _sample(logits, key, temperature, top_p, top_k):
    """Next-token sampling on [b, V] fp32 logits."""
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:                       # greedy
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k is not None:
        kth = jnp.sort(logits, axis=-1)[:, -int(top_k)][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if top_p is not None:
        sort_idx = jnp.argsort(-logits, axis=-1)
        sorted_l = jnp.take_along_axis(logits, sort_idx, axis=-1)
        probs = jax.nn.softmax(sorted_l, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = cum - probs <= top_p               # always keeps top-1
        sorted_l = jnp.where(keep, sorted_l, -1e30)
        inv = jnp.argsort(sort_idx, axis=-1)
        logits = jnp.take_along_axis(sorted_l, inv, axis=-1)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def _kv_layout_fingerprint():
    """The process-global KV-layout + decode-precision config a
    compiled program may have baked in: (kv_cache_dtype, kv_page_size,
    kv_pool_pages, weight_only_dtype, weight_only_group_size).
    Appended to every _model_program_cache key so toggling
    FLAGS_kv_cache_dtype, the pool geometry or
    FLAGS_weight_only_dtype mid-process can never replay a stale
    program built against the previous layout (a paged-pool program
    quantizing into a pool that no longer exists — or an fp program
    fed packed int8 weights — would silently corrupt serving).
    Deliberately blanket (the ISSUE 7/11 contract): programs that do
    not bake the KV layout pay a spurious rebuild on a flag flip —
    rare, and strictly safer than whitelisting which key tags are
    layout-dependent and forgetting one later."""
    from ..framework.flags import get_flag
    return ("kvcfg", str(get_flag("kv_cache_dtype", "auto")),
            int(get_flag("kv_page_size", 16)),
            int(get_flag("kv_pool_pages", 0)),
            str(get_flag("weight_only_dtype", "none")),
            int(get_flag("weight_only_group_size", 64)))


def _model_quant_fingerprint(model):
    """The MODEL-side half of the weight-only fingerprint: whether
    quantization.weight_only.quantize_model has packed this model's
    weights (and at what config).  Per-model state, not a flag — an
    explicitly quantized model under flags-off defaults must still
    miss every program traced against its fp weights (the packed
    state_dict carries extra scale entries, so a stale replay would
    zip-misalign the swapped parameters)."""
    wo = getattr(model, "_weight_only", None)
    if wo is None:
        return ("wo", "none")
    return ("wo", wo["dtype"], wo["group_size"])


def _store_key(model, key):
    """The key _model_program_cache actually stores under: the
    caller's key plus the KV-layout/flag fingerprint plus the model's
    quantization fingerprint.  The SINGLE place the composition
    lives — membership probes go through _program_cache_contains,
    never hand-built keys."""
    return (tuple(key) if isinstance(key, (tuple, list)) else (key,)) \
        + (_kv_layout_fingerprint(), _model_quant_fingerprint(model))


def _program_cache_contains(model, key) -> bool:
    """Would _model_program_cache(model, key, ...) hit, under the
    CURRENT KV-layout flags and the model's quantization state?
    (The serving batcher's first-use probe.)"""
    return _store_key(model, key) in model.__dict__.get("_gen_compiled",
                                                        {})


def _model_program_cache(model, key, build, cap=16):
    """Compiled-program cache living ON the model object, so its
    lifetime (and the closed-over weights) ends with the model —
    a global registry would pin every served model's HBM forever.
    Shared by generate() and the serving ContinuousBatcher (whose two
    step programs thereby survive across batcher instances).  Capped
    LRU (hits refresh recency): the batcher's step programs run every
    chunk, so generate() shape churn evicts cold generate entries
    rather than the serving hot path — FIFO would evict the
    earliest-inserted (hottest) programs first.  Keys carry the
    KV-layout fingerprint (see _kv_layout_fingerprint); callers keep
    their key[0] tag — the fingerprint is appended, not prepended."""
    key = _store_key(model, key)
    store = model.__dict__.setdefault("_gen_compiled", {})
    fn = store.pop(key, None)
    if fn is None:
        # announce the cache miss to the analysis layer: an active
        # recompile_guard records it in .cache_builds, so tests bound
        # program-cache growth the same way they bound XLA compiles
        from ..analysis.lints import note_program_build
        note_program_build(key)
        # a cold compile is ahead: arm jax's persistent compilation
        # cache if FLAGS_compile_cache_dir asks for it — serving-only
        # processes (no trainer) reach the cold-start killer through
        # here (one flag lookup when unset; idempotent when armed)
        from ..telemetry.compile_cache import maybe_enable_persistent_cache
        maybe_enable_persistent_cache()
        fn = build()
        if len(store) >= cap:
            store.pop(next(iter(store)))
    store[key] = fn                    # (re)insert at the recent end
    return fn


def _compiled_gen(model, b, s_prompt, max_new, temperature, top_p,
                  top_k, eos_token_id, max_len):
    cache_key = (b, s_prompt, max_new, temperature, top_p, top_k,
                 eos_token_id, max_len)

    def build():
        # closure construction (state_dict walk included) only happens
        # on a cache MISS — the warm-path cost is the dict lookup
        from ..jit import _swapped_state
        sd = model.state_dict()
        names = list(sd.keys())

        def gen(param_vals, ids, key):
            with _swapped_state(model, names, list(param_vals)):
                cache = model.init_cache(b, max_len)
                logits, cache = model.forward_cached(
                    ids, cache, jnp.asarray(0, jnp.int32))
                key, sub = jax.random.split(key)
                first = _sample(logits[:, -1], sub, temperature, top_p,
                                top_k)
                done0 = jnp.zeros((b,), bool) if eos_token_id is None \
                    else (first == eos_token_id)

                def body(carry, _):
                    cache, tok, pos, key, done = carry
                    lg, cache = model.forward_cached(tok[:, None],
                                                     cache, pos)
                    key, sub = jax.random.split(key)
                    nxt = _sample(lg[:, 0], sub, temperature, top_p,
                                  top_k)
                    if eos_token_id is not None:
                        nxt = jnp.where(done, eos_token_id, nxt)
                        done = done | (nxt == eos_token_id)
                    return (cache, nxt, pos + 1, key, done), nxt

                init = (cache, first, jnp.asarray(s_prompt, jnp.int32),
                        key, done0)
                _, rest = jax.lax.scan(body, init, None,
                                       length=max_new - 1)
            return jnp.concatenate([first[:, None], rest.T], axis=1)

        return jax.jit(gen)

    return _model_program_cache(model, cache_key, build)


def generate(model, input_ids, max_new_tokens: int = 32,
             temperature: float = 0.0, top_p: Optional[float] = None,
             top_k: Optional[int] = None,
             eos_token_id: Optional[int] = None,
             max_length: Optional[int] = None, seed: Optional[int] = None
             ) -> Tensor:
    """Generate [b, max_new_tokens] token ids.  temperature=0 → greedy.

    The compiled program is cached per (model, shape, sampling config);
    repeat calls with the same prompt shape reuse it."""
    ids = input_ids.value if isinstance(input_ids, Tensor) \
        else jnp.asarray(np.asarray(input_ids))
    ids = ids.astype(jnp.int32)
    b, s = int(ids.shape[0]), int(ids.shape[1])
    max_len = int(max_length or (s + max_new_tokens))
    if s + int(max_new_tokens) > max_len:
        raise ValueError(
            f"max_length={max_len} cannot hold prompt ({s}) + "
            f"{max_new_tokens} new tokens — the cache is a fixed-size "
            "buffer (no wraparound); raise max_length")
    fn = _compiled_gen(model, b, s, int(max_new_tokens),
                       float(temperature),
                       None if top_p is None else float(top_p),
                       None if top_k is None else int(top_k),
                       eos_token_id, max_len)
    sd = model.state_dict()
    param_vals = [sd[n]._value for n in sd.keys()]
    key = jax.random.PRNGKey(seed) if seed is not None \
        else prandom.next_key()
    out = fn(param_vals, ids, key)
    return Tensor(out, stop_gradient=True)
