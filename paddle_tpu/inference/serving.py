"""Continuous batching with CHUNKED PREFILL over the fixed-slot KV
cache — the serving scheduler (round-5 verdict item 8; round-6 perf
rework: admission no longer stops the world).

Reference: `python/paddle/incubate/nn/functional/
block_multihead_attention.py` — the reference's paged-KV block tables
exist to admit/evict sequences mid-flight.  TPU-native redesign: XLA
owns layout and needs static shapes, so instead of paged blocks the
engine keeps a FIXED batch of `max_batch_size` slots, each a deep KV
ring buffer with its OWN write depth (`pos[b]`).

The r5 design prefilled each admitted prompt through a separate
batch-1 program (one compile per prompt-length bucket) while every
live decode slot sat idle — BENCH_r05 measured the cost at 0.25x of
the decode roofline on the staggered mixed-length workload.  The r6
design runs ONE scan body for both phases:

  * every scan step feeds a [B, C] token block through the batched
    model (`forward_cached` with per-slot `pos[b]` vectors riding
    through `ops.cached_attention` and the rope tables);
  * a DECODE slot contributes 1 valid token per step (its last sampled
    token; the C-1 pad lanes write throwaway KV that the next step
    overwrites before any masked query can see it);
  * a slot being ADMITTED contributes up to C prompt tokens per step,
    read from a device-side prompt buffer at `pos[b]` — a per-slot
    mode mask selects prefill vs decode lanes, so admission rides the
    SAME compiled program as live decode instead of stalling it;
  * greedy argmax sampling is fused into the scan body; the logit of
    each slot's last VALID lane is the one sampled, so the step that
    consumes a prompt's final chunk also emits its first token;
  * exactly TWO programs compile per (batcher shape): the C=1 pure
    decode scan and the C=prefill_chunk admission scan — prompt length
    never reaches a shape, so distinct lengths cannot recompile;
  * all carry buffers (KV cache, token/pos/mode state, the prompt
    buffer) are donated into the jitted scan (`donate_argnums`), so a
    chunk no longer pays a cache-sized HBM copy;
  * at CHUNK BOUNDARIES the host evicts finished sequences and admits
    queued requests into freed slots (insert/evict at step boundaries
    — the block-table analog).

Compiled programs are cached ON THE MODEL (inference.generation's
compile-cache idiom), so successive batchers over one model reuse them.
`stats()` reports slot occupancy, the prefill-vs-decode token split and
per-chunk wall times so the serve bench can report reps+spread.

Greedy decoding (temperature 0) — the deterministic serving mode whose
per-sequence outputs are testable against isolated `generate()` runs.
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor

__all__ = ["ContinuousBatcher", "Request"]


@dataclass
class Request:
    req_id: int
    prompt: np.ndarray              # [L] int32
    max_new_tokens: int
    tokens: List[int] = field(default_factory=list)
    finished: bool = False

    def output(self) -> np.ndarray:
        return np.asarray(self.tokens[: self.max_new_tokens], np.int32)


class ContinuousBatcher:
    """One model, `max_batch_size` sequence slots, insert/evict at
    chunk boundaries, chunked prefill through the decode program.

    chunk: decode steps per host round trip (a per-token host loop
    would pay the ~10ms relay dispatch per token).
    prefill_chunk: prompt tokens a slot being admitted consumes per
    step of the admission-mode scan (the decode-shaped chunk width).
    admit_steps: scan length of the admission-mode program (defaults
    to chunk//4 — admission rounds are short; decode rounds are long).
    """

    def __init__(self, model, max_batch_size: int = 4,
                 max_len: int = 256, chunk: int = 16,
                 prefill_chunk: int = 32,
                 admit_steps: Optional[int] = None,
                 eos_token_id: Optional[int] = None):
        if not hasattr(model, "forward_cached"):
            raise TypeError("ContinuousBatcher needs a decode-capable "
                            "model (forward_cached/init_cache)")
        self.model = model
        self.B = int(max_batch_size)
        self.max_len = int(max_len)
        self.chunk = int(chunk)
        self.prefill_chunk = max(1, min(int(prefill_chunk),
                                        self.max_len))
        self.admit_steps = max(1, int(admit_steps)
                               if admit_steps is not None
                               else self.chunk // 4)
        self.eos = eos_token_id
        self._queue: deque = deque()
        self._slots: List[Optional[Request]] = [None] * self.B
        self._finished: Dict[int, Request] = {}
        self._next_id = 0

        sd = model.state_dict()
        self._names = list(sd.keys())
        # the cache is prefill_chunk-1 rows DEEPER than max_len: a
        # [B, C] step's pad lanes write up to C-1 rows past a slot's
        # valid depth, and dynamic_update_slice clamps the write start
        # — without the margin a near-capacity write would slide back
        # over valid rows
        self._cache_len = self.max_len + self.prefill_chunk - 1
        self._cache = model.init_cache(self.B, self._cache_len)
        self._pos = jnp.zeros((self.B,), jnp.int32)
        self._tok = jnp.zeros((self.B,), jnp.int32)
        self._mode = jnp.zeros((self.B,), bool)  # True = prefilling
        self._plen = jnp.zeros((self.B,), jnp.int32)
        self._prompts = jnp.zeros((self.B, self.max_len), jnp.int32)
        self._done = jnp.ones((self.B,), bool)   # free slots are "done"
        self._mode_host = np.zeros((self.B,), bool)
        self._done_host = np.ones((self.B,), bool)
        # stats() accumulators — running aggregates plus a BOUNDED
        # window of recent chunk times (a long-lived server would
        # otherwise grow per-chunk lists forever); p50 is over the
        # window, max/counts/occupancy over the whole lifetime
        self._chunk_times: deque = deque(maxlen=1024)
        self._chunk_count = 0
        self._chunk_kind_counts = {"admit": 0, "decode": 0}
        self._chunk_time_max = 0.0
        self._occupancy_total = 0
        self._prefill_tok_total = 0
        self._decode_tok_total = 0
        self._programs_used: set = set()
        self._first_use = False

    # -- public API --------------------------------------------------------
    def submit(self, input_ids, max_new_tokens: int = 32) -> int:
        """Queue one request; returns its id.  Admission happens at the
        next chunk boundary."""
        ids = np.asarray(input_ids.value if isinstance(input_ids, Tensor)
                         else input_ids, np.int32).reshape(-1)
        if len(ids) == 0:
            raise ValueError("empty prompt: a request needs at least "
                             "one token to condition on")
        if len(ids) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(ids)}) + {max_new_tokens} new tokens "
                f"exceeds the slot depth max_len={self.max_len}")
        rid = self._next_id
        self._next_id += 1
        self._queue.append(Request(rid, ids, int(max_new_tokens)))
        return rid

    def step(self) -> List[Request]:
        """One scheduling round: evict finished slots, admit queued
        requests into free slots, run one scan chunk (admission-mode
        while any slot is still consuming its prompt, pure decode
        otherwise).  Returns requests finished this round."""
        newly = self._evict()
        self._admit()
        if any(r is not None for r in self._slots):
            self._run_chunk(mixed=bool(self._mode_host.any()))
            # pre-chunk evictions cleared their slots, so the two
            # harvests are disjoint
            newly += self._evict()
        return newly

    def run(self) -> Dict[int, np.ndarray]:
        """Drive until queue and slots drain; returns {req_id: tokens}."""
        while self._queue or any(r is not None for r in self._slots):
            self.step()
        return {rid: r.output() for rid, r in self._finished.items()}

    @property
    def active(self) -> int:
        return sum(r is not None for r in self._slots)

    @property
    def tokens_produced(self) -> int:
        """USEFUL tokens produced so far: per request, only tokens that
        survive to its output() (capped at max_new_tokens; EOS-trimmed
        at eviction).  The junk lanes a slot decodes between finishing
        and the next chunk boundary are NOT counted — they would
        overstate serve throughput on chunk-misaligned workloads."""
        live = sum(min(len(r.tokens), r.max_new_tokens)
                   for r in self._slots if r is not None)
        done = sum(min(len(r.tokens), r.max_new_tokens)
                   for r in self._finished.values())
        return live + done

    @property
    def compiled_programs(self) -> int:
        """Distinct compiled step programs this batcher has used — at
        most 2 (the C=1 decode scan + the admission scan) regardless
        of how many prompt lengths it served (the
        no-recompile-per-length contract, pinned by tests); 1 if every
        chunk it ever ran had an admission in flight."""
        return len(self._programs_used)

    def stats(self) -> Dict[str, object]:
        """Scheduler counters for the serve bench: slot occupancy,
        prefill-vs-decode token split, per-chunk wall times (p50 over
        the last 1024 chunks; max/counts lifetime-wide; each program's
        first call is excluded from the time stats — it may include
        the one-time XLA compile).
        prefill_tokens/decode_tokens count scan-level WORK (every lane
        the programs advanced); tokens_produced counts only tokens that
        survive to request outputs."""
        n = self._chunk_count
        occ = (self._occupancy_total / (n * self.B)) if n else 0.0
        times = sorted(self._chunk_times)
        return {
            "chunks": n,
            "decode_chunks": self._chunk_kind_counts["decode"],
            "admit_chunks": self._chunk_kind_counts["admit"],
            "slots": self.B,
            "avg_occupancy": occ,
            "prefill_tokens": self._prefill_tok_total,
            "decode_tokens": self._decode_tok_total,
            "tokens_produced": self.tokens_produced,
            "chunk_time_p50": times[len(times) // 2] if times else 0.0,
            "chunk_time_max": self._chunk_time_max,
            "compiled_programs": self.compiled_programs,
        }

    # -- scheduling --------------------------------------------------------
    def _evict(self) -> List[Request]:
        out = []
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            hit_eos = self.eos is not None and self.eos in req.tokens
            if hit_eos:
                req.tokens = req.tokens[: req.tokens.index(self.eos)
                                        + 1]
            # capacity clamp: a slot whose ring buffer filled stops
            # emitting — finish it short rather than spin forever
            # (unreachable while submit() enforces prompt+new<=max_len)
            capped = (self._done_host[i] and not self._mode_host[i]
                      and req.tokens)
            if hit_eos or capped \
                    or len(req.tokens) >= req.max_new_tokens:
                req.finished = True
                self._finished[req.req_id] = req
                self._slots[i] = None
                self._done = self._done.at[i].set(True)
                self._mode = self._mode.at[i].set(False)
                self._mode_host[i] = False
                self._done_host[i] = True
                out.append(req)
        return out

    def _admit(self):
        """Stage queued requests into free slots: write the prompt into
        the device-side buffer and flip the slot to prefill mode.  No
        forward pass happens here — the prompt is consumed chunk by
        chunk inside the next admission-mode scan, overlapped with
        every live slot's decode."""
        for i in range(self.B):
            if self._slots[i] is not None or not self._queue:
                continue
            req = self._queue.popleft()
            self._slots[i] = req
            buf = np.zeros((self.max_len,), np.int32)
            buf[: len(req.prompt)] = req.prompt
            self._prompts = self._prompts.at[i].set(jnp.asarray(buf))
            self._pos = self._pos.at[i].set(0)
            self._plen = self._plen.at[i].set(len(req.prompt))
            self._tok = self._tok.at[i].set(0)
            self._mode = self._mode.at[i].set(True)
            self._done = self._done.at[i].set(False)
            self._mode_host[i] = True
            self._done_host[i] = False

    # -- compiled pieces ---------------------------------------------------
    def _param_vals(self):
        sd = self.model.state_dict()
        return [sd[n]._value for n in self._names]

    def _step_fn(self, width: int, length: int):
        """The unified scan program: `length` steps, each feeding a
        [B, width] token block.  Per slot b and step:

          prefilling?  consume n=min(width, plen-pos) prompt tokens
                       from prompts[b, pos:pos+width]
          decoding?    feed [tok[b], pad...] (n=1)
          free/done?   n=0 (lanes run but nothing advances)

        Lanes past n write throwaway KV at pos+n..pos+width-1; queries
        only see cache rows j <= pos+lane (ops.cached_attention per-slot
        mask) and the next step's valid lanes overwrite those rows
        before its queries can reach them, so the garbage is never
        observable.  The logit at lane n-1 is argmax-sampled; a slot
        emits iff it decoded or consumed its FINAL prompt chunk (the
        emitted token then being the prompt's greedy first token —
        bit-identical to what a monolithic prefill would sample).
        """
        key = ("serve_step", self.B, self._cache_len, self.max_len,
               width, length)
        # first_use consults the MODEL-level store, not this batcher's
        # key set: an LRU-evicted program that recompiles mid-life is
        # excluded from timing again, and a second batcher reusing a
        # warm program keeps its first chunks in the timing window
        self._first_use = key not in self.model.__dict__.get(
            "_gen_compiled", {})
        if self._first_use and key in self._programs_used:
            # mid-life re-trace of a program this batcher already ran
            # (LRU eviction / cleared model cache): snapshot stats()
            # into the telemetry plane BEFORE the rebuild — the counters
            # themselves must survive the recompile (regression-pinned),
            # and the snapshot timestamps exactly which chunks predate
            # the new program (its timing stats restart via _first_use)
            from .. import telemetry as _tel
            if _tel.active():
                _tel.emit("serve.recompile",
                          dict(self.stats(), program=str(key)))
            _tel.counter("serve.recompiles").inc()
        self._programs_used.add(key)
        model = self.model
        names = self._names
        C, K = int(width), int(length)
        max_len = self.max_len
        from ..jit import _swapped_state
        from .generation import _model_program_cache

        def build():
            def serve_step(param_vals, cache, tok, pos, mode, plen,
                           prompts, done):
                with _swapped_state(model, names, list(param_vals)):
                    def body(carry, _):
                        cache, tok, pos, mode, plen, prompts, done = \
                            carry
                        prefilling = mode & ~done
                        lanes = jnp.arange(C, dtype=jnp.int32)
                        idx = jnp.clip(pos[:, None] + lanes[None], 0,
                                       max_len - 1)
                        pref_x = jnp.take_along_axis(prompts, idx,
                                                     axis=1)
                        dec_x = jnp.concatenate(
                            [tok[:, None],
                             jnp.zeros((tok.shape[0], C - 1),
                                       jnp.int32)], axis=1)
                        x = jnp.where(prefilling[:, None], pref_x,
                                      dec_x)
                        n_valid = jnp.where(
                            prefilling,
                            jnp.minimum(C, plen - pos),
                            jnp.where(done, 0, 1)).astype(jnp.int32)
                        lg, cache = model.forward_cached(x, cache, pos)
                        last = jnp.clip(n_valid - 1, 0, C - 1)
                        lg_last = jnp.take_along_axis(
                            lg, last[:, None, None], axis=1)[:, 0]
                        nxt = jnp.argmax(lg_last.astype(jnp.float32),
                                         axis=-1).astype(jnp.int32)
                        finishing = prefilling & (pos + n_valid >= plen)
                        emit = finishing | (~prefilling & ~done)
                        pos = pos + n_valid
                        mode = mode & ~finishing
                        tok = jnp.where(emit, nxt, tok)
                        # clamp: a slot at capacity stops advancing
                        done = done | (pos >= max_len - 1)
                        out_tok = jnp.where(emit, nxt,
                                            jnp.full_like(nxt, -1))
                        n_pref = jnp.sum(
                            jnp.where(prefilling, n_valid, 0))
                        n_dec = jnp.sum(
                            (~prefilling
                             & (n_valid > 0)).astype(jnp.int32))
                        carry = (cache, tok, pos, mode, plen, prompts,
                                 done)
                        return carry, (out_tok, n_pref, n_dec)

                    carry = (cache, tok, pos, mode, plen, prompts,
                             done)
                    carry, (toks, n_pref, n_dec) = jax.lax.scan(
                        body, carry, None, length=K)
                (cache, tok, pos, mode, plen, prompts, done) = carry
                return (cache, tok, pos, mode, plen, prompts, done,
                        toks.T, jnp.sum(n_pref), jnp.sum(n_dec))
            # donate every carry buffer: the KV cache dominates — a
            # non-donated chunk pays a cache-sized HBM copy per call
            return jax.jit(serve_step,
                           donate_argnums=(1, 2, 3, 4, 5, 6, 7))
        return _model_program_cache(model, key, build)

    def _run_chunk(self, mixed: bool):
        if mixed:
            fn = self._step_fn(self.prefill_chunk, self.admit_steps)
        else:
            fn = self._step_fn(1, self.chunk)
        t0 = time.perf_counter()
        (self._cache, self._tok, self._pos, self._mode, self._plen,
         self._prompts, self._done, toks, n_pref, n_dec) = fn(
            self._param_vals(), self._cache, self._tok, self._pos,
            self._mode, self._plen, self._prompts, self._done)
        # ONE batched host transfer per chunk — each device_get is a
        # blocking round trip (~10ms on the tunneled relay), so
        # fetching tokens/mode/done/counters separately would pay it
        # five times per boundary
        toks, mode_h, done_h, n_pref, n_dec = jax.device_get(
            (toks, self._mode, self._done, n_pref, n_dec))
        toks = np.asarray(toks)                       # [B, K]
        self._mode_host = np.array(mode_h)
        self._done_host = np.array(done_h)
        dt = time.perf_counter() - t0
        # a program's FIRST call may include its XLA compile — keep it
        # out of the wall-time stats so chunk_time_max/p50 describe
        # steady-state chunks, not a one-time multi-second compile
        if not self._first_use:
            self._chunk_times.append(dt)
            self._chunk_time_max = max(self._chunk_time_max, dt)
        self._chunk_count += 1
        self._chunk_kind_counts["admit" if mixed else "decode"] += 1
        self._occupancy_total += self.active
        self._prefill_tok_total += int(n_pref)
        self._decode_tok_total += int(n_dec)
        from .. import telemetry as _tel
        _tel.counter("serve.chunks").inc()       # sink or not
        if _tel.active():
            _tel.emit("serve.chunk",
                      kind="admit" if mixed else "decode",
                      wall_ms=round(dt * 1e3, 3),
                      occupancy=self.active, slots=self.B,
                      prefill_tokens=int(n_pref),
                      decode_tokens=int(n_dec),
                      first_use=self._first_use)
            _tel.histogram("serve.chunk_ms").observe(dt * 1e3)
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            req.tokens.extend(int(t) for t in toks[i] if t >= 0)
