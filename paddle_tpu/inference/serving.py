"""Continuous batching over the fixed-slot KV cache — the serving
scheduler (round-5 verdict item 8).

Reference: `python/paddle/incubate/nn/functional/
block_multihead_attention.py` — the reference's paged-KV block tables
exist to admit/evict sequences mid-flight.  TPU-native redesign: XLA
owns layout and needs static shapes, so instead of paged blocks the
engine keeps a FIXED batch of `max_batch_size` slots, each a
`max_len`-deep KV ring buffer with its OWN write depth (`pos[b]`):

  * decode advances every live slot one token per step, as one batched
    program (per-slot positions ride a [b] vector through
    `ops.cached_attention` and the rope tables);
  * `chunk` decode steps run as one `lax.scan` program per host round
    trip (a per-token host loop would pay the ~10ms relay dispatch per
    token);
  * at CHUNK BOUNDARIES the host evicts finished sequences and
    prefills queued requests into the freed slots (insert/evict at
    step boundaries — the block-table analog);
  * prefill writes one request's prompt KV into its slot via a
    batch-1 sub-cache slice + write-back, compiled once per prompt
    length.

Greedy decoding (temperature 0) — the deterministic serving mode whose
per-sequence outputs are testable against isolated `generate()` runs.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor

__all__ = ["ContinuousBatcher", "Request"]


@dataclass
class Request:
    req_id: int
    prompt: np.ndarray              # [L] int32
    max_new_tokens: int
    tokens: List[int] = field(default_factory=list)
    finished: bool = False

    def output(self) -> np.ndarray:
        return np.asarray(self.tokens[: self.max_new_tokens], np.int32)


class ContinuousBatcher:
    """One model, `max_batch_size` sequence slots, insert/evict at
    chunk boundaries."""

    def __init__(self, model, max_batch_size: int = 4,
                 max_len: int = 256, chunk: int = 16,
                 eos_token_id: Optional[int] = None):
        if not hasattr(model, "forward_cached"):
            raise TypeError("ContinuousBatcher needs a decode-capable "
                            "model (forward_cached/init_cache)")
        self.model = model
        self.B = int(max_batch_size)
        self.max_len = int(max_len)
        self.chunk = int(chunk)
        self.eos = eos_token_id
        self._queue: deque = deque()
        self._slots: List[Optional[Request]] = [None] * self.B
        self._finished: Dict[int, Request] = {}
        self._next_id = 0

        sd = model.state_dict()
        self._names = list(sd.keys())
        self._cache = model.init_cache(self.B, self.max_len)
        self._pos = jnp.zeros((self.B,), jnp.int32)
        self._tok = jnp.zeros((self.B,), jnp.int32)
        self._done = jnp.ones((self.B,), bool)   # free slots are "done"
        self._prefill_fns: dict = {}
        self._decode_fn = None
        # raw decoded tokens appended across all slots (prefill firsts
        # + chunk tokens) — the throughput accounting counter
        self.tokens_produced = 0

    # -- public API --------------------------------------------------------
    def submit(self, input_ids, max_new_tokens: int = 32) -> int:
        """Queue one request; returns its id.  Admission happens at the
        next chunk boundary."""
        ids = np.asarray(input_ids.value if isinstance(input_ids, Tensor)
                         else input_ids, np.int32).reshape(-1)
        if len(ids) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(ids)}) + {max_new_tokens} new tokens "
                f"exceeds the slot depth max_len={self.max_len}")
        rid = self._next_id
        self._next_id += 1
        self._queue.append(Request(rid, ids, int(max_new_tokens)))
        return rid

    def step(self) -> List[Request]:
        """One scheduling round: evict finished slots, admit queued
        requests into free slots (prefill), run `chunk` decode steps
        for every live slot.  Returns requests finished this round."""
        newly = self._evict()
        self._admit()
        if any(r is not None for r in self._slots):
            self._decode_chunk()
            newly += self._evict()
            newly = list({r.req_id: r for r in newly}.values())
        return newly

    def run(self) -> Dict[int, np.ndarray]:
        """Drive until queue and slots drain; returns {req_id: tokens}."""
        while self._queue or any(r is not None for r in self._slots):
            self.step()
        return {rid: r.output() for rid, r in self._finished.items()}

    @property
    def active(self) -> int:
        return sum(r is not None for r in self._slots)

    # -- scheduling --------------------------------------------------------
    def _evict(self) -> List[Request]:
        out = []
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            hit_eos = self.eos is not None and self.eos in req.tokens
            if hit_eos:
                req.tokens = req.tokens[: req.tokens.index(self.eos)
                                        + 1]
            if hit_eos or len(req.tokens) >= req.max_new_tokens:
                req.finished = True
                self._finished[req.req_id] = req
                self._slots[i] = None
                self._done = self._done.at[i].set(True)
                out.append(req)
        return out

    def _admit(self):
        for i in range(self.B):
            if self._slots[i] is not None or not self._queue:
                continue
            req = self._queue.popleft()
            self._slots[i] = req
            first = self._prefill(i, req.prompt)
            req.tokens.append(int(first))
            self.tokens_produced += 1
            self._tok = self._tok.at[i].set(int(first))
            self._pos = self._pos.at[i].set(len(req.prompt))
            self._done = self._done.at[i].set(False)

    # -- compiled pieces ---------------------------------------------------
    def _param_vals(self):
        sd = self.model.state_dict()
        return [sd[n]._value for n in self._names]

    def _prefill(self, slot: int, prompt: np.ndarray) -> int:
        """Write the prompt's KV into `slot` (batch-1 sub-cache slice +
        write-back) and return the greedy first token.  Prompts pad up
        to power-of-two BUCKETS so one compiled program serves a range
        of lengths (arbitrary lengths would compile per length); the
        padded rows' garbage KV stays invisible — attention masks
        positions > pos, and decode overwrites each row before reading
        it.  The program cache is capped like generation.py's."""
        L = len(prompt)
        bucket = 8
        while bucket < L:
            bucket *= 2
        bucket = min(bucket, self.max_len)
        fn = self._prefill_fns.get(bucket)
        if fn is None:
            model = self.model
            names = self._names
            from ..jit import _swapped_state

            def prefill(param_vals, cache, ids, slot_i, real_len):
                with _swapped_state(model, names, list(param_vals)):
                    sub = [tuple(jax.lax.dynamic_slice_in_dim(
                        c, slot_i, 1, axis=0) for c in lc)
                        for lc in cache]
                    logits, sub = model.forward_cached(
                        ids, sub, jnp.asarray(0, jnp.int32))
                    cache = [tuple(
                        jax.lax.dynamic_update_slice_in_dim(
                            c, cs, slot_i, axis=0)
                        for c, cs in zip(lc, lcs))
                        for lc, lcs in zip(cache, sub)]
                    last = jax.lax.dynamic_index_in_dim(
                        logits[0], real_len - 1, axis=0,
                        keepdims=False)
                    first = jnp.argmax(last.astype(jnp.float32),
                                       axis=-1).astype(jnp.int32)
                return cache, first
            fn = jax.jit(prefill, donate_argnums=(1,))
            if len(self._prefill_fns) >= 16:
                self._prefill_fns.pop(next(iter(self._prefill_fns)))
            self._prefill_fns[bucket] = fn
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :L] = prompt
        self._cache, first = fn(self._param_vals(), self._cache,
                                jnp.asarray(padded),
                                jnp.asarray(slot, jnp.int32),
                                jnp.asarray(L, jnp.int32))
        return int(jax.device_get(first))

    def _decode_chunk(self):
        if self._decode_fn is None:
            model = self.model
            names = self._names
            K = self.chunk
            from ..jit import _swapped_state

            def decode(param_vals, cache, tok, pos, done):
                with _swapped_state(model, names, list(param_vals)):
                    def body(carry, _):
                        cache, tok, pos, done = carry
                        lg, cache = model.forward_cached(
                            tok[:, None], cache, pos)
                        nxt = jnp.argmax(
                            lg[:, 0].astype(jnp.float32),
                            axis=-1).astype(jnp.int32)
                        nxt = jnp.where(done, tok, nxt)
                        pos = pos + jnp.where(done, 0, 1)
                        # clamp: a slot at capacity stops advancing
                        done = done | (pos >= self.max_len - 1)
                        return (cache, nxt, pos, done), nxt

                    (cache, tok, pos, done), toks = jax.lax.scan(
                        body, (cache, tok, pos, done), None, length=K)
                return cache, tok, pos, done, toks.T   # [B, K]
            self._decode_fn = jax.jit(decode, donate_argnums=(1,))

        self._cache, self._tok, self._pos, self._done, toks = \
            self._decode_fn(self._param_vals(), self._cache, self._tok,
                            self._pos, self._done)
        toks = np.asarray(jax.device_get(toks))
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            req.tokens.extend(int(t) for t in toks[i])
            self.tokens_produced += toks.shape[1]
