"""Continuous batching with CHUNKED PREFILL over a PAGED KV cache —
the serving scheduler (round-5 verdict item 8; round-6 perf rework:
admission no longer stops the world; round-12 perf rework: the KV
cache is a shared page pool with prefix sharing and optional int8).

Reference: `python/paddle/incubate/nn/functional/
block_multihead_attention.py` — the reference's paged-KV block tables
exist to admit/evict sequences mid-flight.  The r6 design kept a FIXED
batch of `max_batch_size` slots, each a dense per-slot KV ring buffer
sized for the worst case — HBM (the binding resource in decode) went
to padding and to duplicated system prompts.  The r12 design keeps the
r6 scan untouched in shape but rebuilds its KV storage around pages
(the PagedAttention/vLLM design point, adapted to a statically-shaped
XLA program):

  * ONE device page pool `[num_pages, page_size, layers, kv_heads,
    head_dim]` per K and V (models.llama.init_paged_cache) backs every
    slot, addressed through a per-slot page table `[B, pages_per_slot]`
    carried through the scan; page 0 is a reserved null page;
  * attention gathers by page table INSIDE the kernel
    (ops.paged_attention: Pallas scalar-prefetch kernel on TPU, a
    `take`-gather jnp twin elsewhere — bit-identical to the dense path
    off-TPU); writes touch only the page window overlapping the step's
    rows (ops.paged_kv_update);
  * PREFIX SHARING (inference/paged_kv.py): a host-side token-exact
    trie over page-sized prompt chunks maps admissions onto already-
    resident pages with refcounts — matched tokens SKIP their prefill
    chunks entirely (pos starts at the shared depth), and a mid-page
    divergence copies the matched page once (copy-on-write) before
    private prefill continues from the divergence row;
  * int8 KV (`FLAGS_kv_cache_dtype=int8` or kv_dtype="int8"): the pool
    stores 1 byte/element with per-page per-head scales, dequant fused
    into the paged-attention kernel — roughly double the resident
    batch/context in the same KV HBM;
  * a pool smaller than total demand EVICTS cached prefix pages
    LRU-first and, beyond that, defers admissions until live requests
    finish — every request still completes (eviction-under-pressure
    contract).

The r6 serving contracts are preserved and regression-pinned WITH the
paged path: one `[B, C]` step body serves both phases, exactly TWO
compiled programs per batcher shape (prompt length never reaches a
shape), and every carry buffer — the page pool, the page tables, the
token/pos/mode state, the prompt buffer — is donated into the jitted
scan.  `kv_layout="dense"` keeps the r6 per-slot ring buffers (the
parity baseline the paged tests compare against).

Compiled programs are cached ON THE MODEL (inference.generation's
compile-cache idiom, keys fingerprinted with the KV-layout flags), so
successive batchers over one model reuse them.  `stats()` reports slot
occupancy, the prefill-vs-decode token split, per-chunk wall times and
the KV-pool counters (pages used/free, prefix-hit tokens, evictions,
pool bytes) that feed `serve.kv` telemetry and the serve bench.

The r9 training plane got fault tolerance (atomic checkpoints, fault
injection, SIGTERM drain); this file carries the SERVE-plane half of
that contract (ISSUE 9) — all host-plane control flow, so the compiled
step programs and their cache keys stay byte-identical with the
robustness flags off (bench-asserted):

  * SLO classes: every request is `interactive` / `batch` /
    `best_effort` with an optional arrival DEADLINE.  Admission is a
    priority queue — classes in priority order, strict FIFO by arrival
    within a class, and a class head deferred by KV-pool pressure
    blocks its own and lower classes (no head-of-line bypass, so a
    stream of short prompts can never starve a deferred long one);
  * load shedding: a bounded queue (`FLAGS_serve_queue_depth`) sheds
    the lowest-SLO newest-arrival QUEUED request on overflow
    (best_effort first), and a request still queued past its deadline
    is shed as a deadline miss — an in-flight decode is NEVER shed;
  * fault injection (`distributed/fault.py` points `serve.admit`,
    `serve.kv_alloc`, `serve.chunk`, `serve.decode`) + recovery: a
    faulted admission retries FIFO-in-place (bounded by
    `FLAGS_serve_retry_budget`), a faulted chunk fires BEFORE the
    donated carries are touched and simply retries, and a poisoned
    SLOT is evicted — pages released, request requeued at its arrival
    position for a from-scratch re-decode (greedy decode is
    deterministic, so the re-decode is bit-exact vs a fault-free run;
    `tools/chaos_check.py --serve` pins this) — while the rest of the
    batch keeps decoding;
  * a serve watchdog riding `distributed/watchdog.py`: every chunk
    dispatch runs under `watched("serve.chunk")`
    (FLAGS_stop_check_timeout), and a chunk that aged past the
    deadline while in flight is counted/published as hung;
  * SIGTERM drain mirroring the r9 training contract: once
    `guard.drain_requested()` is set, admissions stop (queued requests
    shed with reason "drain"), in-flight decodes finish within
    PADDLE_DRAIN_GRACE, and on grace expiry partial results are
    flushed — the caller exits ELASTIC_EXIT_CODE
    (`chaos_check --serve --selftest` runs the e2e).

Greedy decoding (temperature 0) — the deterministic serving mode whose
per-sequence outputs are testable against isolated `generate()` runs.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.flags import get_flag
from ..framework.tensor import Tensor

__all__ = ["ContinuousBatcher", "Request", "SLO_CLASSES",
           "pack_handoff", "unpack_handoff"]


def pack_handoff(meta, data) -> bytes:
    """Serialize one hand-off (meta + gathered KV pages) for the KV
    launch plane: multi-process fleets move prefill->decode hand-offs
    as a single value under ``<job>/serve/handoff/<gid>`` (host plane
    over the r14 KV plane); in-process fleets skip this entirely and
    pass the device arrays straight into import_handoff."""
    import io
    import json
    m = dict(meta)
    m["prompt"] = [int(t) for t in np.asarray(meta["prompt"]).tolist()]
    arrays = {k: np.asarray(v) for k, v in data.items()}
    # npz has no bfloat16: ship raw bytes (uint16 view) and record the
    # real dtype in the header for the view-cast on unpack
    m["_dtypes"] = {k: str(a.dtype) for k, a in arrays.items()}
    header = json.dumps(m).encode("utf-8")
    buf = io.BytesIO()
    np.savez(buf, **{k: a.view(np.dtype(f"uint{8 * a.dtype.itemsize}"))
                     if a.dtype.kind not in "iufb" else a
                     for k, a in arrays.items()})
    return len(header).to_bytes(8, "big") + header + buf.getvalue()


def unpack_handoff(blob: bytes):
    """Inverse of pack_handoff: (meta, data) with device arrays, ready
    for import_handoff().  Byte-identical round trip (pinned by
    tests/test_serve_disagg.py)."""
    import io
    import json
    n = int.from_bytes(blob[:8], "big")
    meta = json.loads(blob[8:8 + n].decode("utf-8"))
    meta["prompt"] = np.asarray(meta["prompt"], np.int32)
    dtypes = meta.pop("_dtypes", {})
    npz = np.load(io.BytesIO(blob[8 + n:]))
    data = {}
    for k in npz.files:
        a = npz[k]
        want = dtypes.get(k)
        if want and str(a.dtype) != want:
            a = a.view(np.dtype(want))
        data[k] = jnp.asarray(a)
    return meta, data

# admission priority order, highest first; shedding walks it in reverse
SLO_CLASSES = ("interactive", "batch", "best_effort")


@dataclass
class Request:
    req_id: int
    prompt: np.ndarray              # [L] int32
    max_new_tokens: int
    tokens: List[int] = field(default_factory=list)
    finished: bool = False
    # -- SLO / robustness state (ISSUE 9) --
    slo: str = "batch"
    deadline: Optional[float] = None   # absolute monotonic seconds
    arrival: int = 0                   # global arrival sequence number
    shed: bool = False
    shed_reason: Optional[str] = None
    requeues: int = 0                  # faulted-slot re-admissions
    admit_faults: int = 0              # injected admission-fault retries
    partial: bool = False              # drain-flushed mid-generation
    # -- per-request latency spans (ISSUE 10): monotonic stamps at the
    # queue -> admit -> first-token -> finish boundaries; TTFT/e2e are
    # measured from SUBMIT (a requeue resets admit/first, so the spans
    # describe the decode that actually served the user)
    t_submit: float = 0.0
    t_admit: Optional[float] = None
    t_first: Optional[float] = None
    t_done: Optional[float] = None
    # -- streaming (ISSUE 11 satellite): per-request token callback,
    # fired as chunks complete with each NEW burst of output-surviving
    # tokens (speculation delivers a whole accepted run in one burst);
    # `delivered` is the count already handed out — it survives a
    # faulted-slot requeue, so the bit-exact re-decode never re-sends
    # the prefix the caller already has
    on_token: Optional[object] = None
    # authoritative copy of every token actually handed to on_token —
    # a shed after repeated faults restores it as the partial output,
    # so the final result can never disown a streamed token even when
    # intermediate requeues discarded (and re-decoded) `tokens`
    delivered_tokens: List[int] = field(default_factory=list)

    @property
    def delivered(self) -> int:
        """Tokens already streamed — DERIVED from the authoritative
        delivered_tokens copy, so no second counter can drift out of
        sync with what the consumer actually holds."""
        return len(self.delivered_tokens)

    def output(self) -> np.ndarray:
        return np.asarray(self.tokens[: self.max_new_tokens], np.int32)


class ContinuousBatcher:
    """One model, `max_batch_size` sequence slots, insert/evict at
    chunk boundaries, chunked prefill through the decode program, KV
    in a shared page pool.

    chunk: decode steps per host round trip (a per-token host loop
    would pay the ~10ms relay dispatch per token).
    prefill_chunk: prompt tokens a slot being admitted consumes per
    step of the admission-mode scan (the decode-shaped chunk width).
    admit_steps: scan length of the admission-mode program (defaults
    to chunk//4 — admission rounds are short; decode rounds are long).
    kv_layout: "paged" (default when the model has a paged decode
    path) or "dense" (the r6 per-slot ring buffers).
    page_size/num_pages/kv_dtype: paged-pool geometry and precision;
    None reads FLAGS_kv_page_size / FLAGS_kv_pool_pages /
    FLAGS_kv_cache_dtype (num_pages 0 = dense-equivalent capacity).
    prefix_sharing: admissions whose prompt prefix matches resident
    pages map them instead of re-prefilling (paged only).  None =
    True, except under speculative decoding where it defaults False
    (skipped prefill chunks starve the draft cache and collapse the
    accept rate; explicit True keeps both and warns).
    """

    def __init__(self, model, max_batch_size: int = 4,
                 max_len: int = 256, chunk: int = 16,
                 prefill_chunk: int = 32,
                 admit_steps: Optional[int] = None,
                 eos_token_id: Optional[int] = None,
                 kv_layout: Optional[str] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 kv_dtype: Optional[str] = None,
                 prefix_sharing: Optional[bool] = None,
                 weight_only_dtype: Optional[str] = None,
                 spec_tokens: Optional[int] = None,
                 draft_model=None,
                 draft_layers: Optional[int] = None,
                 role: str = "unified"):
        if not hasattr(model, "forward_cached"):
            raise TypeError("ContinuousBatcher needs a decode-capable "
                            "model (forward_cached/init_cache)")
        # -- weight-only quantization (ISSUE 11): pack the model's
        # decode weights in place BEFORE the state_dict walk below, so
        # the packed params + scales ride the compiled scan.  None
        # reads FLAGS_weight_only_dtype; "none" leaves the model (and
        # therefore every compiled program) untouched.
        wo = weight_only_dtype if weight_only_dtype is not None \
            else get_flag("weight_only_dtype", "none")
        if str(wo) not in ("none", "", "None"):
            from ..quantization.weight_only import quantize_model
            quantize_model(model, wo)
        if kv_layout is None:
            kv_layout = "paged" if hasattr(model, "forward_cached_paged") \
                else "dense"
        if kv_layout not in ("paged", "dense"):
            raise ValueError(f"kv_layout {kv_layout!r}: paged|dense")
        if kv_layout == "paged" and not hasattr(model,
                                               "forward_cached_paged"):
            raise TypeError("kv_layout='paged' needs a paged-decode "
                            "model (forward_cached_paged/"
                            "init_paged_cache)")
        self.model = model
        self.B = int(max_batch_size)
        self.max_len = int(max_len)
        self.chunk = int(chunk)
        self.prefill_chunk = max(1, min(int(prefill_chunk),
                                        self.max_len))
        self.admit_steps = max(1, int(admit_steps)
                               if admit_steps is not None
                               else self.chunk // 4)
        self.eos = eos_token_id
        self.kv_layout = kv_layout
        # -- speculative decoding (ISSUE 11): K>0 swaps the pure-decode
        # program for a draft/verify body — draft K tokens with the
        # (small) draft model, verify them in ONE target pass of width
        # K+1 through the same chunked scan, accept the longest
        # matching prefix plus the target's bonus token.  Greedy output
        # is bit-exact vs non-speculative decode (the verify lanes ARE
        # the non-speculative logits), and with K=0 nothing below
        # exists — carries, programs and keys stay byte-identical.
        k = spec_tokens if spec_tokens is not None \
            else get_flag("serve_spec_tokens", 0)
        self.spec_k = max(0, int(k or 0))
        self._spec_w = self.spec_k + 1          # verify width
        self._draft = None
        self._draft_names: List[str] = []
        self._draft_key = ()
        if self.spec_k:
            if draft_model is None:
                n = draft_layers if draft_layers is not None \
                    else get_flag("serve_draft_layers", 0)
                n = int(n or 0)
                if n <= 0:
                    raise ValueError(
                        "speculative decoding needs a draft: pass "
                        "draft_model= or draft_layers= (or set "
                        "FLAGS_serve_draft_layers) for early-exit "
                        "self-drafting")
                if not hasattr(model, "early_exit_draft"):
                    raise TypeError(
                        f"{type(model).__name__} has no "
                        "early_exit_draft(); pass an explicit "
                        "draft_model instead")
                draft_model = model.early_exit_draft(n)
                self._draft_key = ("selfdraft", n)
            else:
                if not hasattr(draft_model, "forward_cached"):
                    raise TypeError("draft_model needs a cached decode "
                                    "path (forward_cached/init_cache)")
                # the compiled program closes over the draft OBJECT
                # (its params are swapped in per call), so the program
                # key carries the draft's identity — two batchers with
                # different drafts can never share a program
                # (satellite 2: draft identity in the program keys)
                self._draft_key = ("draft", id(draft_model))
                # self-speculation (draft IS the target) needs no
                # second parameter list: the target's _swapped_state
                # already covers every weight the draft reads —
                # shipping state_dict twice per chunk would double the
                # parameter traffic for nothing
                if draft_model is not model \
                        and hasattr(draft_model, "state_dict"):
                    self._draft_names = list(
                        draft_model.state_dict().keys())
            self._draft = draft_model
        # speculation accounting (host plane)
        self._spec_drafted = 0
        self._spec_accepted = 0
        self._spec_steps = 0
        self._spec_emit_window: deque = deque(maxlen=4096)
        # one FIFO per SLO class (admission walks SLO_CLASSES in
        # priority order; within a class strictly by arrival).  The
        # lock makes queue STRUCTURE atomic against a submit() racing
        # the run()/step() thread (and the router's balance reads):
        # without it stats()["queued"] / the per-class snapshot could
        # see a torn count mid-append (ISSUE 15 satellite).  Reentrant
        # because a shed inside submit() fires the user's on_token
        # callback, which may itself submit()
        self._qlock = threading.RLock()
        self._queues: Dict[str, deque] = {c: deque()
                                          for c in SLO_CLASSES}
        self._slots: List[Optional[Request]] = [None] * self.B
        self._finished: Dict[int, Request] = {}
        self._next_id = 0
        # -- disaggregated serving (ISSUE 20): a prefill-role batcher
        # runs ONLY chunked-prefill (admit) programs; a slot that
        # finishes its prompt is FROZEN (done=True device-side, pages
        # pinned) until export_handoff() ships its KV pages +
        # page-table row to a decode-role batcher, which admits it at
        # pos >= prompt_len via import_handoff() — no prefill is ever
        # recomputed.  "unified" is the classic symmetric replica and
        # the default: with no prefill/decode batchers in the fleet,
        # every code path below is dormant and the serve-step programs
        # are byte-identical (zero-overhead pin in bench.py).
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(f"role {role!r}: unified|prefill|decode")
        if role != "unified" and kv_layout != "paged":
            raise TypeError("disaggregated roles need kv_layout="
                            "'paged' (the hand-off ships pages)")
        self.role = role
        self._handoff_ready: Dict[int, int] = {}   # rid -> slot index
        self._no_freeze: set = set()    # unfrozen rids: decode HERE
        self._handoffs_out = 0
        self._handoffs_in = 0
        self._handoff_bytes = 0
        self._arrival_seq = 0
        self._now = time.monotonic     # patchable time source (tests)
        self._has_deadlines = False    # sweep is skipped until a
        #                                deadline ever enters the queue
        self._draining = False
        self._drain_deadline = None
        # serve-robustness accounting (the chaos no-leak contract:
        # submitted == completed + shed once queue and slots drain)
        self._submitted = 0
        self._admissions = 0           # admission EVENTS (requeues
        #                                re-admit, so >= completed)
        self._completed = 0
        self._shed_count = 0
        self._shed_by_class = {c: 0 for c in SLO_CLASSES}
        # sliding-window shed signal (ISSUE 19 satellite): one 0/1
        # sample per TERMINAL request (shed=1, delivered=0) in a
        # bounded window — the rate the router/autoscaler policy reads
        # is CURRENT pressure, not lifetime history (an old shed burst
        # ages out as later terminals push it off the window).  Same
        # bounded-window discipline as the latency deques below
        self._terminal_window: deque = deque(maxlen=256)
        self._deadline_misses = 0
        self._requeue_count = 0
        self._chunk_retries = 0
        self._consecutive_chunk_faults = 0
        self._hung_chunks = 0
        self._cb_errors = 0
        from ..distributed.watchdog import watched
        self._watch = watched("serve.chunk")

        sd = model.state_dict()
        self._names = list(sd.keys())
        # the logical KV depth is C-1 rows DEEPER than max_len: a
        # [B, C] step's pad lanes write up to C-1 rows past a slot's
        # valid depth — without the margin a near-capacity write would
        # land on valid rows.  Under speculation the widest writer is
        # the verify pass, and a done slot's frozen pos can sit up to
        # spec_w-1 rows past the clamp with another spec_w junk rows
        # written beyond it — hence the 2*K+2 floor.
        self._eff_chunk = max(self.prefill_chunk,
                              2 * self.spec_k + 2) if self.spec_k \
            else self.prefill_chunk
        self._cache_len = self.max_len + self._eff_chunk - 1
        if kv_layout == "paged":
            from .paged_kv import PageAllocator
            (self.page_size, self.pages_per_slot,
             self.num_pages) = self._paged_geometry(
                self.B, self.max_len, self._eff_chunk, page_size,
                num_pages)
            # prefix sharing defaults OFF under speculation: a shared
            # prefix SKIPS its prefill chunks, so the draft's dense
            # cache never sees those rows — greedy output stays
            # bit-exact (acceptance is exact-match against the target)
            # but the accept rate silently collapses on every prefix
            # hit, making speculation a net slowdown exactly when
            # sharing works.  An explicit True keeps both and warns.
            if prefix_sharing is None:
                self.prefix_sharing = not self.spec_k
            else:
                self.prefix_sharing = bool(prefix_sharing)
                if self.prefix_sharing and self.spec_k:
                    import warnings
                    warnings.warn(
                        "prefix_sharing=True with speculative decoding:"
                        " shared-prefix admissions skip the prefill"
                        " chunks that would fill the DRAFT cache, so"
                        " accept_rate degrades on every prefix hit"
                        " (output stays bit-exact). Prefer one or the"
                        " other per workload.", stacklevel=2)
            # rows a slot can write past prompt+new before the host
            # evicts it: up to max(chunk, admit_steps)-1 junk decode
            # steps inside the finishing chunk (each advancing up to
            # spec_w rows under speculation), plus C-1 junk lanes
            self._overshoot = max(self.chunk * self._spec_w,
                                  self.admit_steps) + self._eff_chunk
            self._alloc = PageAllocator(self.num_pages, self.page_size)
            self._plans: List[Optional[object]] = [None] * self.B
            self._cache = model.init_paged_cache(self.num_pages,
                                                 self.page_size,
                                                 kv_dtype)
            self._kv_dtype = str(np.dtype(self._cache["k"].dtype))
            self._page_table = jnp.zeros((self.B, self.pages_per_slot),
                                         jnp.int32)
        else:
            self.prefix_sharing = False
            self._cache = model.init_cache(self.B, self._cache_len)
        # the draft's KV cache is DENSE per-slot ring buffers even over
        # a paged target pool: the draft is small (that is the point),
        # its rows are never shared, and a second page plane would buy
        # nothing — it rides the scan carry and is donated like every
        # other buffer
        self._dcache = self._draft.init_cache(self.B, self._cache_len) \
            if self.spec_k else None
        self._pos = jnp.zeros((self.B,), jnp.int32)
        self._tok = jnp.zeros((self.B,), jnp.int32)
        self._mode = jnp.zeros((self.B,), bool)  # True = prefilling
        self._plen = jnp.zeros((self.B,), jnp.int32)
        self._prompts = jnp.zeros((self.B, self.max_len), jnp.int32)
        self._done = jnp.ones((self.B,), bool)   # free slots are "done"
        self._mode_host = np.zeros((self.B,), bool)
        self._done_host = np.ones((self.B,), bool)
        self._pos_host = np.zeros((self.B,), np.int64)
        # stats() accumulators — running aggregates plus a BOUNDED
        # window of recent chunk times (a long-lived server would
        # otherwise grow per-chunk lists forever); p50 is over the
        # window, max/counts/occupancy over the whole lifetime
        self._chunk_times: deque = deque(maxlen=1024)
        # per-request latency windows (bounded, same discipline as the
        # chunk times) + per-SLO-class deadline attainment — host
        # aggregates that always accumulate so stats() answers sink-less
        self._lat: Dict[str, deque] = {
            k: deque(maxlen=1024)
            for k in ("queue_ms", "ttft_ms", "tpot_ms", "e2e_ms")}
        self._slo_lat = {c: {"completed": 0, "with_deadline": 0,
                             "deadline_met": 0} for c in SLO_CLASSES}
        self._chunk_count = 0
        self._chunk_kind_counts = {"admit": 0, "decode": 0}
        self._chunk_time_max = 0.0
        self._occupancy_total = 0
        self._prefill_tok_total = 0
        self._decode_tok_total = 0
        self._programs_used: set = set()
        self._first_use = False
        # HBM memory ledger (ISSUE 10): register both step programs as
        # lazy providers — lower_step is the side-effect-free probe, so
        # nothing compiles until telemetry.memory_report() asks; the
        # weakref keeps the ledger from pinning a dead batcher (and
        # its KV pool) alive
        import weakref
        from ..telemetry import memledger as _ml
        _ref = weakref.ref(self)
        _meta = {"kv_layout": self.kv_layout, "slots": self.B,
                 "max_len": self.max_len}

        def _provider(mixed):
            def provider():
                bat = _ref()
                if bat is None:
                    raise RuntimeError("batcher was garbage-collected")
                return bat.lower_step(mixed=mixed).compile()
            return provider
        _ml.register("serve_step.decode", _provider(False), meta=_meta)
        _ml.register("serve_step.admit", _provider(True), meta=_meta)
        # build-level static sentinel (analysis.passes): structural
        # passes over the serve build path.  The full catalog (donation
        # aliasing over the paged carries — costs a lower per program)
        # runs via .preflight() / tools/static_check.py.
        from ..analysis.passes import PassContext, sentinel_preflight
        sentinel_preflight(
            PassContext("serve", f"serve:B{self.B}", engine=self),
            level="build")

    def preflight(self, *, level: str = "full", manager=None):
        """Full static sentinel over the serve step programs: the
        donation lint proves every donated paged carry (KV pool,
        caches, cursors) is really aliased in both the decode and
        mixed admission programs — an unaliased carry silently doubles
        the KV pool's HBM.  Uses the side-effect-free lower_step probe;
        returns a SentinelReport (None when FLAGS_static_sentinel is
        off).  Error findings raise SentinelError."""
        from ..analysis.passes import PassContext, sentinel_preflight
        return sentinel_preflight(
            PassContext("serve", f"serve:B{self.B}", engine=self),
            level=level, manager=manager)

    # -- pool geometry -----------------------------------------------------
    @staticmethod
    def _paged_geometry(B, max_len, prefill_chunk, page_size=None,
                        num_pages=None):
        """(page_size, pages_per_slot, num_pages) for a paged batcher —
        the ONE place the geometry formulas live (init, and the
        allocation-free byte estimator below).  pages_per_slot covers
        the logical depth PLUS the write window (ceil(C/ps)+1 pages):
        the windowed page write (ops.paged_kv_update) must never clamp
        two window entries onto one page.  num_pages defaults to
        dense-equivalent capacity (every slot fully backed + the null
        page)."""
        from ..framework.flags import get_flag
        ps = int(page_size or get_flag("kv_page_size", 16))
        cache_len = max_len + prefill_chunk - 1
        pages_per_slot = max(
            (max_len - 1) // ps + (-(-prefill_chunk // ps)) + 1,
            -(-cache_len // ps))
        auto = 1 + B * pages_per_slot
        num_pages = int(num_pages or get_flag("kv_pool_pages", 0)
                        or auto)
        return ps, pages_per_slot, num_pages

    @classmethod
    def paged_kv_bytes(cls, model, max_batch_size, max_len,
                       prefill_chunk: int = 32, page_size=None,
                       num_pages=None, kv_dtype=None) -> int:
        """Device bytes a paged batcher of this geometry would hold
        (pool + scales + page table) — pure shape arithmetic, NO
        allocation (the bench's int8-vs-bf16 sizing comparison must
        not burn two throwaway pools of HBM).  Matches
        kv_cache_bytes() of a real instance (test-pinned)."""
        from ..models.llama import _resolve_kv_dtype
        cfg = model.config
        B = int(max_batch_size)
        prefill_chunk = max(1, min(int(prefill_chunk), int(max_len)))
        ps, p_slot, n_pages = cls._paged_geometry(
            B, int(max_len), prefill_chunk, page_size, num_pages)
        dt, quant = _resolve_kv_dtype(cfg, kv_dtype)
        pool = 2 * n_pages * ps * cfg.num_hidden_layers \
            * cfg.num_key_value_heads * cfg.head_dim \
            * jnp.dtype(dt).itemsize
        scales = (2 * n_pages * cfg.num_hidden_layers
                  * cfg.num_key_value_heads * 4) if quant else 0
        table = B * p_slot * 4
        return pool + scales + table

    # -- public API --------------------------------------------------------
    def submit(self, input_ids, max_new_tokens: int = 32,
               slo: str = "batch",
               deadline_ms: Optional[float] = None,
               on_token=None) -> int:
        """Queue one request; returns its id.  Admission happens at the
        next chunk boundary, in SLO-class priority order (FIFO by
        arrival within a class).

        slo: "interactive" | "batch" | "best_effort".
        deadline_ms: latest time (from now) by which the request must
        be ADMITTED; still queued past it = shed as a deadline miss
        (None reads FLAGS_serve_default_deadline_ms; 0/unset = none).
        on_token: streaming callback `on_token(req_id, tokens, done)`
        fired from run()/step() as chunks complete — `tokens` is the
        NEW burst of output-surviving token ids (EOS-trimmed, capped
        at max_new_tokens; speculation delivers whole accepted runs),
        `done=True` exactly once at the terminal delivery (finish,
        drain flush or shed).  Callback exceptions are swallowed and
        counted (`callback_errors`) — a broken consumer must not
        poison the batch.

        Every submitted id appears exactly once in run()'s results —
        a request shed by the bounded queue / a deadline / the drain
        protocol comes back with `shed=True` and an empty (or partial)
        output, never silently dropped (the chaos no-leak contract)."""
        ids = np.asarray(input_ids.value if isinstance(input_ids, Tensor)
                         else input_ids, np.int32).reshape(-1)
        if len(ids) == 0:
            raise ValueError("empty prompt: a request needs at least "
                             "one token to condition on")
        if len(ids) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(ids)}) + {max_new_tokens} new tokens "
                f"exceeds the slot depth max_len={self.max_len}")
        if slo not in SLO_CLASSES:
            raise ValueError(f"unknown SLO class {slo!r}; known: "
                             f"{SLO_CLASSES}")
        rid = self._next_id
        self._next_id += 1
        req = Request(rid, ids, int(max_new_tokens), slo=slo,
                      arrival=self._arrival_seq, on_token=on_token)
        req.t_submit = self._now()
        self._arrival_seq += 1
        if deadline_ms is None:
            deadline_ms = float(get_flag("serve_default_deadline_ms")
                                or 0.0)
        if deadline_ms <= 0:
            deadline_ms = None          # 0/unset = no deadline, same
            #                             convention as the flag
        if deadline_ms is not None:
            req.deadline = self._now() + float(deadline_ms) / 1e3
            self._has_deadlines = True
        self._submitted += 1
        if self._draining:
            # admissions are closed: the request is accounted, shed
            self._shed(req, "drain")
            return rid
        with self._qlock:
            depth = int(get_flag("serve_queue_depth") or 0)
            if depth > 0 and self._queued_count() >= depth:
                victim = self._shed_victim(req)
                if victim is req:
                    self._shed(req, "queue_full")
                    return rid
                self._queues[victim.slo].remove(victim)
                self._shed(victim, "queue_full")
            self._queues[slo].append(req)
        return rid

    def _queued_count(self) -> int:
        with self._qlock:
            return sum(len(q) for q in self._queues.values())

    def queue_snapshot(self) -> Dict[str, int]:
        """Atomic {slo_class: queued count} snapshot — one consistent
        view of every class queue (the lock orders it against a
        concurrent submit/admit), so a router balancing on per-class
        depth (or telemetry_report) can never see a torn count."""
        with self._qlock:
            return {c: len(q) for c, q in self._queues.items()}

    @property
    def queued(self) -> int:
        """Requests waiting for a slot (all SLO classes)."""
        return self._queued_count()

    def _shed_victim(self, incoming: Request) -> Request:
        """Queue-overflow victim: lowest SLO class first, newest
        arrival within it — the incoming request itself when nothing
        queued ranks below it.  Only QUEUED requests are candidates;
        in-flight slots are untouchable."""
        order = {c: i for i, c in enumerate(SLO_CLASSES)}

        def rank(r):
            return (order[r.slo], r.arrival)
        victim = incoming
        for q in self._queues.values():
            for r in q:
                if rank(r) > rank(victim):
                    victim = r
        return victim

    def step(self) -> List[Request]:
        """One scheduling round: evict finished slots, shed queued
        requests past their deadline, admit queued requests into free
        slots (SLO priority, FIFO within class), run one scan chunk
        (admission-mode while any slot is still consuming its prompt,
        pure decode otherwise).  Returns requests finished this round.

        Once `guard.drain_requested()` is set (SIGTERM), admissions
        close: queued requests are shed with reason "drain" and only
        the in-flight slots keep decoding."""
        from ..distributed import guard
        if not self._draining and guard.drain_requested():
            self._begin_drain()
        newly = self._evict()
        if not self._draining:
            self._shed_deadline_missed()
            self._admit()
        # frozen hand-off slots (prefill role, prompt consumed, waiting
        # for a decode worker) are done=True device-side and need no
        # chunks — a prefill batcher whose live slots are all frozen
        # parks until export_handoff() frees them
        if any(r is not None and r.req_id not in self._handoff_ready
               for r in self._slots):
            self._run_chunk(mixed=bool(self._mode_host.any()))
            # pre-chunk evictions cleared their slots, so the two
            # harvests are disjoint
            newly += self._evict()
        return newly

    def run(self) -> Dict[int, np.ndarray]:
        """Drive until queue and slots drain; returns {req_id: tokens}
        for EVERY submitted request (shed ones included — empty or
        partial outputs, `Request.shed` set).

        Drain contract (mirrors the r9 training drain): when SIGTERM
        sets the drain flag, admissions stop, in-flight decodes finish
        within PADDLE_DRAIN_GRACE seconds, and on grace expiry the
        still-running slots are flushed as PARTIAL results — run()
        then returns normally so the caller can deliver what exists
        and exit ELASTIC_EXIT_CODE."""
        while self._queued_count() or any(r is not None
                                          for r in self._slots):
            if self._draining and self._drain_deadline is not None \
                    and self._now() > self._drain_deadline:
                self._flush_partial()
                break
            if self._handoff_ready:
                # prefill-role batcher driven standalone: once every
                # live slot is frozen awaiting hand-off (and no queued
                # request can fill a free slot) step() can make no
                # progress — park and let the router export
                occ = [r for r in self._slots if r is not None]
                if occ and all(r.req_id in self._handoff_ready
                               for r in occ) \
                        and not (len(occ) < self.B
                                 and self._queued_count()):
                    break
            self.step()
        return {rid: r.output() for rid, r in self._finished.items()}

    @property
    def drained(self) -> bool:
        """True once the SIGTERM drain protocol engaged — the caller's
        cue to exit ELASTIC_EXIT_CODE after delivering run()'s
        results."""
        return self._draining

    # -- streaming delivery (ISSUE 11 satellite) ---------------------------
    def _deliver(self, req: Request, done: bool):
        """Hand the request's NEW output-surviving tokens to its
        on_token callback: the deliverable prefix is EOS-trimmed and
        capped at max_new_tokens (exactly what output() will return),
        so a streamed consumer never sees a token the final result
        drops.  `done=True` fires exactly once, at the terminal
        delivery.  Host-plane only — the compiled programs cannot
        tell a streaming request from a plain one."""
        if req.on_token is None:
            return
        cap = req.max_new_tokens
        if self.eos is not None and self.eos in req.tokens:
            cap = min(cap, req.tokens.index(self.eos) + 1)
        end = min(len(req.tokens), cap)
        burst = [int(t) for t in req.tokens[req.delivered:end]]
        if not burst and not done:
            return
        req.delivered_tokens.extend(burst)
        try:
            req.on_token(req.req_id, burst, done)
        except Exception:
            self._cb_errors += 1
            from .. import telemetry as _tel
            _tel.counter("serve.callback_errors").inc()

    # -- robustness plumbing (ISSUE 9) -------------------------------------
    def _shed(self, req: Request, reason: str):
        """Terminal no-service state: the request is accounted in
        `_finished` (so run() returns it and nothing leaks) but marked
        shed.  Callers remove it from queue/slot structures FIRST; an
        in-flight decode is never shed."""
        req.finished = True
        req.shed = True
        req.shed_reason = reason
        self._finished[req.req_id] = req
        self._deliver(req, done=True)
        self._shed_count += 1
        self._shed_by_class[req.slo] += 1
        self._terminal_window.append(1.0)
        from .. import telemetry as _tel
        _tel.counter("serve.shed").inc()         # sink or not
        if _tel.active():
            _tel.emit("serve.shed", req=req.req_id, slo=req.slo,
                      reason=reason, requeues=req.requeues,
                      tokens=len(req.tokens))

    def _shed_deadline_missed(self):
        """Shed every QUEUED request whose admission deadline passed
        (`serve.deadline_miss`).  Skipped entirely until a deadline
        ever enters the queue — the flags-off path stays one bool."""
        if not self._has_deadlines:
            return
        now = self._now()
        from .. import telemetry as _tel
        with self._qlock:
            for cls in SLO_CLASSES:
                q = self._queues[cls]
                survivors = deque()
                while q:
                    req = q.popleft()
                    if req.deadline is not None and now > req.deadline:
                        self._deadline_misses += 1
                        _tel.counter("serve.deadline_miss").inc()
                        if _tel.active():
                            _tel.emit("serve.deadline_miss",
                                      req=req.req_id, slo=req.slo,
                                      late_ms=round(
                                          (now - req.deadline) * 1e3,
                                          3))
                        self._shed(req, "deadline")
                    else:
                        survivors.append(req)
                self._queues[cls] = survivors

    def _requeue(self, req: Request):
        """Put a faulted-slot request back into its class queue AT ITS
        ARRIVAL POSITION (strict FIFO by arrival survives requeues)."""
        with self._qlock:
            q = self._queues[req.slo]
            idx = 0
            while idx < len(q) and q[idx].arrival < req.arrival:
                idx += 1
            q.insert(idx, req)
        self._requeue_count += 1
        from .. import telemetry as _tel
        _tel.counter("serve.requeue").inc()
        if _tel.active():
            _tel.emit("serve.requeue", req=req.req_id, slo=req.slo,
                      requeues=req.requeues)

    def _clear_slot(self, i: int):
        """Free slot i's device-side state: done/mode flags, and for
        the paged layout the slot's page mapping (prompt pages stay
        resident as cached prefix pages; the freed slot's junk lanes
        write the null page)."""
        if self._slots[i] is not None:
            self._no_freeze.discard(self._slots[i].req_id)
        self._slots[i] = None
        self._done = self._done.at[i].set(True)
        self._mode = self._mode.at[i].set(False)
        self._mode_host[i] = False
        self._done_host[i] = True
        if self.kv_layout == "paged" and self._plans[i] is not None:
            self._alloc.release_plan(self._plans[i])
            self._plans[i] = None
            self._page_table = self._page_table.at[i].set(
                jnp.zeros((self.pages_per_slot,), jnp.int32))

    def _fault_slot(self, i: int, reason: str = "decode_fault"):
        """Slot i's decode came back poisoned: evict the slot (pages
        released, pending trie nodes dropped — nothing the faulted
        chunk wrote is ever shareable), discard every token the
        request produced (satellite: the re-decode re-emits them, so
        keeping them would double-count `tokens_produced`), and
        requeue the request at its arrival position for a from-scratch
        re-decode — or shed it when its deadline passed or its retry
        budget (FLAGS_serve_retry_budget) is spent.  The rest of the
        batch keeps decoding untouched."""
        req = self._slots[i]
        self._clear_slot(i)
        req.requeues += 1
        budget = int(get_flag("serve_retry_budget") or 3)
        shedding = (req.deadline is not None
                    and self._now() > req.deadline) \
            or req.requeues > budget or self._draining
        if shedding and req.delivered_tokens:
            # a streaming consumer already HOLDS the delivered prefix —
            # with no re-decode coming, disowning it would break the
            # "never see a token the final result drops" contract.
            # The final output becomes exactly what was streamed (a
            # partial result); the undelivered tail is dropped.  The
            # authoritative copy matters: an intermediate requeue may
            # have discarded `tokens` and the re-decode may not have
            # caught back up to the delivered frontier
            req.tokens[:] = req.delivered_tokens
            req.partial = True
        else:
            # the re-decode re-emits every token bit-exactly (greedy),
            # so discarding them keeps tokens_produced honest
            req.tokens.clear()
        # the re-decode re-serves the request from scratch: its spans
        # must describe the decode the user actually received
        req.t_admit = None
        req.t_first = None
        if shedding:
            self._shed(req, reason)
        else:
            self._requeue(req)

    def _finish_spans(self, req: Request):
        """Close a DELIVERED request's latency spans: stamp t_done,
        fold queue/TTFT/TPOT/e2e into the bounded stats windows and
        the per-SLO attainment counters, and publish one
        `serve.request` event (sink-gated; the host aggregates always
        accumulate so stats() answers sink-less).  Shed requests never
        come through here — no service, no latency sample."""
        now = self._now()
        req.t_done = now
        self._terminal_window.append(0.0)
        queue_ms = ((req.t_admit if req.t_admit is not None else now)
                    - req.t_submit) * 1e3
        e2e_ms = (now - req.t_submit) * 1e3
        n = min(len(req.tokens), req.max_new_tokens)
        # TTFT/TPOT only exist once a first token did: a drain-flushed
        # request that never produced one must not shift the TTFT
        # percentiles with a no-token wait
        ttft_ms = None
        tpot_ms = None
        if req.t_first is not None:
            ttft_ms = (req.t_first - req.t_submit) * 1e3
            if n > 1:
                # chunked decode emits tokens in bursts, so per-request
                # TPOT is the honest average over the decode window,
                # not a per-token measurement
                tpot_ms = (now - req.t_first) * 1e3 / (n - 1)
        self._lat["queue_ms"].append(queue_ms)
        self._lat["e2e_ms"].append(e2e_ms)
        if ttft_ms is not None:
            self._lat["ttft_ms"].append(ttft_ms)
        if tpot_ms is not None:
            self._lat["tpot_ms"].append(tpot_ms)
        slo = self._slo_lat[req.slo]
        slo["completed"] += 1
        met = None
        if req.deadline is not None:
            slo["with_deadline"] += 1
            met = (req.t_admit is not None
                   and req.t_admit <= req.deadline)
            if met:
                slo["deadline_met"] += 1
        from .. import telemetry as _tel
        if _tel.active():
            fields = dict(req=req.req_id, slo=req.slo, tokens=n,
                          queue_ms=round(queue_ms, 3),
                          e2e_ms=round(e2e_ms, 3),
                          requeues=req.requeues, partial=req.partial)
            if ttft_ms is not None:
                fields["ttft_ms"] = round(ttft_ms, 3)
            if tpot_ms is not None:
                fields["tpot_ms"] = round(tpot_ms, 3)
            if met is not None:
                fields["deadline_met"] = met
            _tel.emit("serve.request", fields)
            _tel.histogram("serve.e2e_ms").observe(e2e_ms)
            if ttft_ms is not None:
                _tel.histogram("serve.ttft_ms").observe(ttft_ms)
            if tpot_ms is not None:
                _tel.histogram("serve.tpot_ms").observe(tpot_ms)

    def _begin_drain(self):
        """SIGTERM arrived: close admissions (queued requests shed with
        reason "drain"), start the PADDLE_DRAIN_GRACE window for the
        in-flight decodes."""
        self._draining = True
        grace = float(os.environ.get("PADDLE_DRAIN_GRACE", "60"))
        self._drain_deadline = self._now() + grace
        n_shed = 0
        with self._qlock:
            for q in self._queues.values():
                while q:
                    self._shed(q.popleft(), "drain")
                    n_shed += 1
        from .. import telemetry as _tel
        _tel.counter("serve.drains").inc()
        if _tel.active():
            _tel.emit("serve.drain", phase="begin", shed=n_shed,
                      in_flight=self.active, grace_s=grace)

    def _flush_partial(self):
        """Grace expired: flush every still-running slot as a PARTIAL
        result (tokens so far, `Request.partial` set) — delivered, not
        shed; the chunk that was in flight completed at the last
        boundary, so the tokens are real."""
        flushed = 0
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            self._clear_slot(i)
            req.finished = True
            req.partial = True
            self._finished[req.req_id] = req
            self._completed += 1
            self._finish_spans(req)
            self._deliver(req, done=True)
            flushed += 1
        from .. import telemetry as _tel
        if _tel.active():
            _tel.emit("serve.drain", phase="flush", flushed=flushed)

    @property
    def active(self) -> int:
        return sum(r is not None for r in self._slots)

    @property
    def tokens_produced(self) -> int:
        """USEFUL tokens produced so far: per request, only tokens that
        survive to its output() (capped at max_new_tokens; EOS-trimmed
        at eviction).  The junk lanes a slot decodes between finishing
        and the next chunk boundary are NOT counted — they would
        overstate serve throughput on chunk-misaligned workloads."""
        live = sum(min(len(r.tokens), r.max_new_tokens)
                   for r in self._slots if r is not None)
        done = sum(min(len(r.tokens), r.max_new_tokens)
                   for r in self._finished.values())
        return live + done

    @property
    def compiled_programs(self) -> int:
        """Distinct compiled step programs this batcher has used — at
        most 2 (the C=1 decode scan + the admission scan) regardless
        of how many prompt lengths it served (the
        no-recompile-per-length contract, pinned by tests); 1 if every
        chunk it ever ran had an admission in flight."""
        return len(self._programs_used)

    def kv_cache_bytes(self) -> int:
        """Device bytes held by the KV cache (pool + scales + page
        tables for the paged layout; the dense ring buffers
        otherwise) — the serve bench's KV HBM metric."""
        leaves = jax.tree_util.tree_leaves(self._cache)
        if self.kv_layout == "paged":
            leaves = leaves + [self._page_table]
        return int(sum(int(np.prod(a.shape)) * a.dtype.itemsize
                       for a in leaves))

    def _attainment_of(self, cls: str) -> Optional[float]:
        """Per-SLO-class attainment, THE derivation stats() and the
        router's balance view share: deadline-bearing traffic reports
        admitted-in-time / deadlined; deadline-free traffic reports
        the served fraction; None with no signal yet (a fresh replica
        is 'headroom', not 'failing')."""
        rec = self._slo_lat[cls]
        shed = self._shed_by_class[cls]
        if rec["with_deadline"]:
            return rec["deadline_met"] / rec["with_deadline"]
        if rec["completed"] or shed:
            return rec["completed"] / (rec["completed"] + shed)
        return None

    @property
    def shed_rate_window(self) -> float:
        """Shed fraction over the last 256 TERMINAL requests (ISSUE 19
        satellite) — the sliding-window twin of the cumulative
        shed_rate: an old shed burst ages out of this one as later
        requests deliver, so a routing/autoscaling policy reading it
        sees CURRENT pressure.  0.0 with no terminal signal yet."""
        w = self._terminal_window
        return round(sum(w) / len(w), 4) if w else 0.0

    def prefix_match_len(self, input_ids) -> int:
        """Prompt tokens of `input_ids` already resident in THIS
        batcher's prefix cache — the prefill work an admission here
        would skip (ISSUE 15 satellite).  A pure read-only trie probe
        (PageAllocator.prefix_match_len): no page is pinned, no LRU
        order perturbed, nothing admitted.  0 for the dense layout or
        with prefix sharing off."""
        if self.kv_layout != "paged" or not self.prefix_sharing:
            return 0
        ids = np.asarray(input_ids.value
                         if isinstance(input_ids, Tensor)
                         else input_ids, np.int32).reshape(-1)
        return self._alloc.prefix_match_len(ids)

    def router_view(self, prompt=None, digest: bool = False) \
            -> Dict[str, object]:
        """Compact host-plane policy view for the serve-fleet router
        (inference/router.py) — everything pick_replica() weighs, and
        the record a replica-per-rank worker publishes to the KV plane
        (router.ReplicaPublisher, the r14 FleetSink key schema).  Much
        cheaper than stats(): no latency summaries, no device reads.
        With `prompt` the view carries this replica's
        prefix_hit_tokens for it (read-only probe).  With `digest` the
        view also carries the bounded trie digest
        (FLAGS_serve_digest_entries) — only the PUBLISHED view pays
        the trie walk; per-submit probes never do."""
        qbc = self.queue_snapshot()
        view: Dict[str, object] = {
            "queued": sum(qbc.values()),
            "queued_by_class": qbc,
            "active": self.active,
            "slots": self.B,
            "role": self.role,
            "handoff_ready": len(self._handoff_ready),
            "draining": self._draining,
            "shed_rate": round(self._shed_count / self._submitted, 4)
            if self._submitted else 0.0,
            "shed_rate_window": self.shed_rate_window,
            "attainment": {c: self._attainment_of(c)
                           for c in SLO_CLASSES},
        }
        if self.kv_layout == "paged":
            view["kv_pages_free"] = self._alloc.pages_free
            view["kv_pages_cached"] = self._alloc.pages_cached
            if digest and self.prefix_sharing:
                n = int(get_flag("serve_digest_entries", 32) or 0)
                view["trie_digest"] = self._alloc.trie_digest(n)
                view["page_size"] = self.page_size
        if prompt is not None:
            view["prefix_hit_tokens"] = self.prefix_match_len(prompt)
        return view

    def stats(self) -> Dict[str, object]:
        """Scheduler counters for the serve bench: slot occupancy,
        prefill-vs-decode token split, per-chunk wall times (p50 over
        the last 1024 chunks; max/counts lifetime-wide; each program's
        first call is excluded from the time stats — it may include
        the one-time XLA compile), and the KV-pool block (pages
        used/free/cached, prefix-hit tokens, evictions, pool bytes).
        prefill_tokens/decode_tokens count scan-level WORK (every lane
        the programs advanced); tokens_produced counts only tokens that
        survive to request outputs."""
        n = self._chunk_count
        occ = (self._occupancy_total / (n * self.B)) if n else 0.0
        times = sorted(self._chunk_times)
        qbc = self.queue_snapshot()     # ONE atomic view: "queued"
        #                                 and the per-class counts can
        #                                 never disagree (ISSUE 15)
        out = {
            "chunks": n,
            "decode_chunks": self._chunk_kind_counts["decode"],
            "admit_chunks": self._chunk_kind_counts["admit"],
            "slots": self.B,
            "avg_occupancy": occ,
            "prefill_tokens": self._prefill_tok_total,
            "decode_tokens": self._decode_tok_total,
            "tokens_produced": self.tokens_produced,
            "chunk_time_p50": times[len(times) // 2] if times else 0.0,
            "chunk_time_max": self._chunk_time_max,
            "compiled_programs": self.compiled_programs,
            "kv_layout": self.kv_layout,
            "kv_bytes": self.kv_cache_bytes(),
            # serve-robustness counters (ISSUE 9).  The no-leak
            # contract chaos_check --serve asserts: once queue and
            # slots drain, requests_submitted == requests_completed +
            # requests_shed, with requeued requests completing exactly
            # once (their discarded pre-fault tokens never reach
            # tokens_produced)
            "requests_submitted": self._submitted,
            "requests_admitted": self._admissions,
            "requests_completed": self._completed,
            "requests_shed": self._shed_count,
            "requests_requeued": self._requeue_count,
            "shed_by_class": dict(self._shed_by_class),
            "shed_rate_window": self.shed_rate_window,
            "deadline_misses": self._deadline_misses,
            "chunk_retries": self._chunk_retries,
            "hung_chunks": self._hung_chunks,
            "callback_errors": self._cb_errors,
            "queued": sum(qbc.values()),
            "queued_by_class": qbc,
            "drained": self._draining,
            # disaggregated serving (ISSUE 20): hand-off terminals.
            # Per-batcher no-leak partition becomes submitted ==
            # completed + shed + handoffs_out (imports count as
            # submissions on the decode side)
            "role": self.role,
            "handoffs_out": self._handoffs_out,
            "handoffs_in": self._handoffs_in,
            "handoff_bytes": self._handoff_bytes,
            "handoff_ready": len(self._handoff_ready),
        }
        wo = getattr(self.model, "_weight_only", None)
        out["weight_only"] = wo["dtype"] if wo else "none"
        if self.spec_k:
            # speculation block (ISSUE 11): accept_rate over drafted
            # tokens, accepted_per_step (= n_emit, drafts + bonus) over
            # a bounded window of active slot-steps
            from ..telemetry import percentiles_of
            window = list(self._spec_emit_window)
            pct = percentiles_of(window)
            out.update(
                spec_tokens=self.spec_k,
                spec_drafted=self._spec_drafted,
                spec_accepted=self._spec_accepted,
                spec_accept_rate=round(
                    self._spec_accepted / self._spec_drafted, 4)
                if self._spec_drafted else 0.0,
                spec_accepted_per_step={
                    "mean": round(sum(window) / len(window), 3)
                    if window else 0.0,
                    "p50": round(pct["p50"], 3),
                    "p99": round(pct["p99"], 3)},
            )
        # per-request latency spans (ISSUE 10): queue->admit->first-
        # token->finish percentiles over the last 1024 delivered
        # requests, and per-SLO-class deadline attainment.  The shared
        # summary derivation (ISSUE 14) adds TRUE window min/max —
        # percentile reservoirs sample away exactly the extreme
        # straggler/TTFT outliers an incident investigation needs
        from ..telemetry import summary_of
        latency = {}
        for k, window in self._lat.items():
            s = summary_of(list(window))
            latency[k] = {"count": s["count"],
                          "min": round(s["min"], 3),
                          "max": round(s["max"], 3),
                          "p50": round(s["p50"], 3),
                          "p90": round(s["p90"], 3),
                          "p99": round(s["p99"], 3)}
        out["latency"] = latency
        attain = {}
        for cls in SLO_CLASSES:
            rec = dict(self._slo_lat[cls])
            rec["shed"] = self._shed_by_class[cls]
            att = self._attainment_of(cls)
            if att is not None:
                rec["attainment"] = round(att, 4)
            attain[cls] = rec
        out["slo_attainment"] = attain
        if self.kv_layout == "paged":
            out.update(
                kv_page_size=self.page_size,
                kv_pages=self.num_pages,
                kv_pages_used=self._alloc.pages_used,
                kv_pages_free=self._alloc.pages_free,
                kv_pages_cached=self._alloc.pages_cached,
                kv_dtype=self._kv_dtype,
                prefix_hit_tokens=self._alloc.prefix_hit_tokens,
                import_hit_tokens=self._alloc.import_hit_tokens,
                grafted_pages=self._alloc.grafted_pages,
                evictions=self._alloc.evictions,
                cow_copies=self._alloc.cow_copies,
            )
        else:
            out.update(prefix_hit_tokens=0, import_hit_tokens=0,
                       grafted_pages=0, evictions=0, cow_copies=0)
        return out

    # -- scheduling --------------------------------------------------------
    def _evict(self) -> List[Request]:
        out = []
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            if req.req_id in self._handoff_ready:
                # frozen awaiting hand-off: done=True device-side is
                # the freeze, not a finish — never evict, never treat
                # as capped; export_handoff() clears the slot
                continue
            hit_eos = self.eos is not None and self.eos in req.tokens
            if hit_eos:
                req.tokens = req.tokens[: req.tokens.index(self.eos)
                                        + 1]
            if self.role == "prefill" and not self._mode_host[i] \
                    and req.tokens and not hit_eos \
                    and not self._done_host[i] \
                    and req.req_id not in self._no_freeze \
                    and len(req.tokens) < req.max_new_tokens:
                # prefill worker finished this slot's prompt (pos >=
                # prompt_len, first token(s) emitted inside the admit
                # scan): FREEZE it — done=True parks the lanes (done
                # lanes advance nothing; their junk writes land past
                # pos, never on valid rows) with pages pinned until a
                # decode worker imports the KV.  Also reached when a
                # role flip strands mid-decode slots: they hand off
                # at pos = prompt_len + k and resume elsewhere.
                self._handoff_ready[req.req_id] = i
                self._done = self._done.at[i].set(True)
                self._done_host[i] = True
                continue
            # capacity clamp: a slot whose ring buffer filled stops
            # emitting — finish it short rather than spin forever
            # (unreachable while submit() enforces prompt+new<=max_len)
            capped = (self._done_host[i] and not self._mode_host[i]
                      and req.tokens)
            if hit_eos or capped \
                    or len(req.tokens) >= req.max_new_tokens:
                req.finished = True
                self._finished[req.req_id] = req
                self._completed += 1
                self._finish_spans(req)
                self._deliver(req, done=True)
                # _clear_slot unmaps the slot's pages (prompt pages
                # stay resident as cached prefix pages) and points the
                # freed slot at the null page — a free slot's junk
                # lanes keep writing, and its old pages may be someone
                # else's now
                self._clear_slot(i)
                out.append(req)
        return out

    def _admit(self):
        """Stage queued requests into free slots: plan the slot's page
        mapping (prefix-shared pages + fresh privates, CoW copy at a
        mid-page divergence), write the prompt into the device-side
        buffer and flip the slot to prefill mode.  No forward pass
        happens here — the UNSHARED part of the prompt is consumed
        chunk by chunk inside the next admission-mode scan, overlapped
        with every live slot's decode.

        SLO order: classes in priority order, strict FIFO by arrival
        within a class.  Under pool pressure (alloc fails even after
        evicting cached prefix pages) the class HEAD defers to a later
        boundary and blocks its own and lower classes — no head-of-
        line bypass, so later short prompts can never starve a
        deferred long one (satellite regression) — unless nothing is
        running, which means the pool can never serve this request:
        that raises.  Injected faults (`serve.admit` /
        `serve.kv_alloc`) retry FIFO-in-place, bounded by
        FLAGS_serve_retry_budget.

        Runs under the queue lock: admission pops heads while a
        concurrent submit() may be appending — the router's balance
        snapshots must order against both."""
        with self._qlock:
            return self._admit_locked()

    def _admit_locked(self):
        from ..distributed import fault
        free = [i for i in range(self.B) if self._slots[i] is None]

        def retry_exhausted(q, req, reason):
            """Injected admission-path fault: bump the per-request
            retry count.  Past FLAGS_serve_retry_budget the request
            is shed (True — caller moves to the next one); otherwise
            it keeps its FIFO position for the next boundary (False —
            caller defers this class and lower)."""
            req.admit_faults += 1
            if req.admit_faults > int(
                    get_flag("serve_retry_budget") or 3):
                q.popleft()
                self._shed(req, reason)
                return True
            return False

        for cls in SLO_CLASSES:
            q = self._queues[cls]
            while q and free:
                req = q[0]
                # injected admission fault: error = transient (retry
                # this head at the next boundary, FIFO kept); skip =
                # admission rejected outright (shed)
                try:
                    f = fault.hit("serve.admit",
                                  key=f"req{req.req_id}:{cls}")
                except fault.FaultError:
                    if retry_exhausted(q, req, "admit_fault"):
                        continue
                    return          # blocked: same+lower classes wait
                if f is not None and f.mode == "skip":
                    q.popleft()
                    self._shed(req, "admit_fault")
                    continue
                plan = None
                if self.kv_layout == "paged":
                    ps = self.page_size
                    covered_rows = min(
                        len(req.prompt) + req.max_new_tokens
                        + self._overshoot, self._cache_len)
                    covered_pages = min(-(-covered_rows // ps),
                                        self.pages_per_slot)
                    try:
                        fk = fault.hit("serve.kv_alloc",
                                       key=f"req{req.req_id}")
                    except fault.FaultError:
                        # transient allocator fault == pool pressure:
                        # FIFO deferral, bounded like admit faults
                        if retry_exhausted(q, req, "kv_alloc_fault"):
                            continue
                        return
                    if fk is not None:
                        # data-mode kv_alloc fault: simulated pool
                        # exhaustion — defer exactly like pressure
                        # (bounded so times=* cannot spin run())
                        if retry_exhausted(q, req, "kv_alloc_fault"):
                            continue
                        return
                    plan = self._alloc.admit(
                        req.prompt if self.prefix_sharing
                        else req.prompt[:0], covered_pages)
                    if plan is None:
                        if self.active == 0:
                            # nothing is running, so no pages will
                            # ever free: deferring would spin forever
                            raise RuntimeError(
                                f"KV pool ({self.num_pages - 1} usable "
                                f"pages of {ps} rows) cannot ever hold "
                                f"this request ({covered_pages} pages); "
                                f"grow num_pages or shrink the request")
                        return      # pressure: defer same+lower classes
                q.popleft()
                i = free.pop(0)
                self._admissions += 1
                self._slots[i] = req
                req.t_admit = self._now()   # re-stamped on re-admission
                buf = np.zeros((self.max_len,), np.int32)
                buf[: len(req.prompt)] = req.prompt
                self._prompts = self._prompts.at[i].set(
                    jnp.asarray(buf))
                self._plen = self._plen.at[i].set(len(req.prompt))
                self._tok = self._tok.at[i].set(0)
                self._done = self._done.at[i].set(False)
                self._done_host[i] = False
                start = 0
                if plan is not None:
                    self._plans[i] = plan
                    row = np.zeros((self.pages_per_slot,), np.int32)
                    row[: len(plan.pages)] = plan.pages
                    self._page_table = self._page_table.at[i].set(
                        jnp.asarray(row))
                    if plan.cow is not None:
                        # copy-on-write at the divergence boundary:
                        # clone the partially-matched page into the
                        # slot's first private page, then prefill
                        # resumes mid-page.  admit() pinned the source
                        # so pressure could not reclaim it before this
                        # copy — unpin it now
                        src, dst = plan.cow
                        self._cache = self._page_copy_fn()(
                            self._cache, jnp.asarray(src, jnp.int32),
                            jnp.asarray(dst, jnp.int32))
                        self._alloc.release_page(src)
                    start = plan.shared_tokens
                # prefix-shared tokens are already resident: prefill
                # starts at the divergence, or straight to decode when
                # only the final prompt token remains
                self._pos = self._pos.at[i].set(start)
                self._pos_host[i] = start
                prefilling = start < len(req.prompt)
                self._mode = self._mode.at[i].set(prefilling)
                self._mode_host[i] = prefilling

    # -- compiled pieces ---------------------------------------------------
    def _param_vals(self):
        sd = self.model.state_dict()
        return [sd[n]._value for n in self._names]

    def _program_key(self, width: int, length: int):
        base = ("serve_step", self.B, self._cache_len, self.max_len,
                width, length)
        if self.kv_layout == "paged":
            base += ("paged", self.page_size, self.num_pages,
                     self.pages_per_slot, self._kv_dtype)
        if self.spec_k:
            # speculation changes BOTH programs (the draft cache rides
            # the admit carry too) and the compiled body closes over
            # the draft — K and the draft's identity are part of what
            # the program baked in (satellite 2)
            base += ("spec", self.spec_k) + self._draft_key
        return base

    def _page_copy_fn(self):
        """One-page device copy (pool rows + scales, all layers) for
        copy-on-write admissions; compiled once per pool shape and
        cached on the model beside the step programs."""
        from .generation import _model_program_cache
        key = ("serve_page_copy", self.num_pages, self.page_size,
               self._kv_dtype)

        def build():
            def serve_page_copy(cache, src, dst):
                out = dict(cache)
                for name in cache:
                    buf = cache[name]
                    out[name] = buf.at[dst].set(buf[src])
                return out
            return jax.jit(serve_page_copy, donate_argnums=(0,))
        return _model_program_cache(self.model, key, build)

    # -- disaggregated hand-off (ISSUE 20) ---------------------------------
    def set_role(self, role: str):
        """Host-plane role flip (the autoscaler's role-repair path).
        Flipping to 'prefill' strands nothing: slots mid-decode freeze
        at the next boundary and hand off their KV; flipping away from
        'prefill' simply reopens normal decode for future admissions
        (already-frozen slots still leave via export_handoff)."""
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(f"role {role!r}: unified|prefill|decode")
        if role != "unified" and self.kv_layout != "paged":
            raise TypeError("disaggregated roles need kv_layout="
                            "'paged' (the hand-off ships pages)")
        self.role = role

    def _page_export_fn(self):
        """Fixed-shape page gather for hand-off/replication export:
        [pages_per_slot] page ids -> per-buffer [pages_per_slot, ...]
        rows.  Pad entries point at the null page (junk by design), so
        ONE compiled program covers every export regardless of how
        many pages are valid.  Read-only: the pool is not donated."""
        from .generation import _model_program_cache
        key = ("serve_page_export", self.num_pages, self.page_size,
               self.pages_per_slot, self._kv_dtype)

        def build():
            def serve_page_export(cache, idx):
                return {name: cache[name][idx] for name in cache}
            return jax.jit(serve_page_export)
        return _model_program_cache(self.model, key, build)

    def _page_import_fn(self):
        """Fixed-shape page scatter for hand-off/replication import:
        rows land at the given page ids; entries the import does not
        need (already-resident shared chunks, pad rows) point at the
        null page, whose content is junk by contract — so duplicate
        null indices in the scatter are harmless.  The pool is donated
        exactly like the step carries."""
        from .generation import _model_program_cache
        key = ("serve_page_import", self.num_pages, self.page_size,
               self.pages_per_slot, self._kv_dtype)

        def build():
            def serve_page_import(cache, idx, data):
                out = dict(cache)
                for name in cache:
                    out[name] = cache[name].at[idx].set(data[name])
                return out
            return jax.jit(serve_page_import, donate_argnums=(0,))
        return _model_program_cache(self.model, key, build)

    def _handoff_page_bytes(self, data, n_pages: int) -> int:
        total = 0
        for a in data.values():
            total += (a.nbytes // self.pages_per_slot) * n_pages
        return int(total)

    def export_handoff(self, rid: int):
        """Detach a frozen hand-off-ready request: gather its valid KV
        pages (rows [0, pos)) plus everything a decode worker needs to
        resume at pos — prompt, emitted tokens, SLO state — and free
        the slot.  The prompt's full chunks stay RESIDENT here as
        cached prefix pages, so later prompts sharing them still skip
        their prefill chunks on this worker.  Accounting: the request
        leaves as a hand-off, not a completion — per batcher,
        submitted == completed + shed + handoffs_out."""
        i = self._handoff_ready.pop(rid, None)
        if i is None:
            raise KeyError(f"request {rid} is not hand-off ready")
        req = self._slots[i]
        pos = int(self._pos_host[i])
        ps = self.page_size
        n_pages = -(-pos // ps)
        plan = self._plans[i]
        idx = np.zeros((self.pages_per_slot,), np.int32)
        idx[:n_pages] = plan.pages[:n_pages]
        data = self._page_export_fn()(self._cache, jnp.asarray(idx))
        nbytes = self._handoff_page_bytes(data, n_pages)
        meta = {
            "rid": int(req.req_id),
            "prompt": np.asarray(req.prompt, np.int32),
            "pos": pos,
            "plen": int(len(req.prompt)),
            "tokens": [int(t) for t in req.tokens],
            "max_new_tokens": int(req.max_new_tokens),
            "slo": req.slo,
            "deadline": req.deadline,
            "t_submit": req.t_submit,
            "t_first": req.t_first,
            "n_pages": int(n_pages),
            "page_size": int(ps),
            "kv_dtype": self._kv_dtype,
            "nbytes": int(nbytes),
        }
        self._handoffs_out += 1
        self._handoff_bytes += nbytes
        self._clear_slot(i)
        from .. import telemetry as _tel
        if _tel.active():
            _tel.emit("serve.handoff", dir="export", req=int(rid),
                      pages=int(n_pages), bytes=int(nbytes), pos=pos)
        return meta, data

    def import_handoff(self, meta, data, on_token=None) -> Optional[int]:
        """Admit a handed-off request at ``pos = prompt_len + k``: no
        prefill chunk ever runs for it here (the zero-recompute
        contract — this batcher's prefill_tokens stat stays flat).
        Pages whose chunks are already resident in the local trie are
        NOT rewritten — their rows are bit-identical by the prefix-
        sharing determinism argument — and count as cross-replica
        prefix hits; the rest scatter into freshly allocated pages and
        the prompt chain grafts into the trie, so the fleet-tier cache
        grows where decode traffic lands.  Returns the local req_id,
        or None when no slot (or no pages) is free — the caller
        retries at the next boundary; nothing is allocated on None."""
        if self.role == "prefill":
            raise RuntimeError("prefill-role batcher cannot import a "
                               "hand-off")
        if self.kv_layout != "paged":
            raise TypeError("import_handoff needs the paged KV layout")
        if int(meta["page_size"]) != self.page_size \
                or str(meta["kv_dtype"]) != self._kv_dtype:
            raise ValueError(
                "hand-off geometry mismatch: got page_size=%s/%s, "
                "this pool is %d/%s" % (meta["page_size"],
                                        meta["kv_dtype"],
                                        self.page_size, self._kv_dtype))
        with self._qlock:
            free = [i for i in range(self.B)
                    if self._slots[i] is None]
            if not free:
                return None
            prompt = np.asarray(meta["prompt"], np.int32)
            pos = int(meta["pos"])
            ps = self.page_size
            covered_rows = min(
                len(prompt) + int(meta["max_new_tokens"])
                + self._overshoot, self._cache_len)
            covered_pages = min(-(-covered_rows // ps),
                                self.pages_per_slot)
            n_pages = int(meta["n_pages"])
            if n_pages > covered_pages:
                raise ValueError(
                    f"hand-off spans {n_pages} pages but this pool "
                    f"covers {covered_pages} per slot")
            plan = self._alloc.admit(
                prompt if self.prefix_sharing else prompt[:0],
                covered_pages, imported=True)
            if plan is None:
                return None
            if plan.cow is not None:
                # the imported data fully covers the divergence page —
                # skip the device copy, just unpin the CoW source
                self._alloc.release_page(plan.cow[0])
            # scatter only the NON-shared valid pages; shared chunks
            # already hold bit-identical rows (and may be mapped by
            # other live slots) — their data rows land on the null page
            idx = np.zeros((self.pages_per_slot,), np.int32)
            for j in range(plan.n_shared_pages, n_pages):
                idx[j] = plan.pages[j]
            self._cache = self._page_import_fn()(
                self._cache, jnp.asarray(idx), data)
            rid = self._next_id
            self._next_id += 1
            req = Request(rid, prompt, int(meta["max_new_tokens"]),
                          slo=str(meta.get("slo", "batch")),
                          deadline=meta.get("deadline"),
                          arrival=self._arrival_seq,
                          on_token=on_token)
            self._arrival_seq += 1
            if req.deadline is not None:
                self._has_deadlines = True
            req.tokens = [int(t) for t in meta.get("tokens", ())]
            req.t_submit = float(meta.get("t_submit")
                                 or self._now())
            req.t_first = meta.get("t_first")
            req.t_admit = self._now()
            i = free[0]
            self._slots[i] = req
            self._submitted += 1       # arrives as a hand-off, so the
            self._admissions += 1      # no-leak partition still closes
            self._handoffs_in += 1
            nbytes = int(meta.get("nbytes")
                         or self._handoff_page_bytes(data, n_pages))
            self._handoff_bytes += nbytes
            buf = np.zeros((self.max_len,), np.int32)
            buf[: len(prompt)] = prompt
            self._prompts = self._prompts.at[i].set(jnp.asarray(buf))
            self._plen = self._plen.at[i].set(len(prompt))
            self._tok = self._tok.at[i].set(
                int(req.tokens[-1]) if req.tokens else 0)
            self._done = self._done.at[i].set(False)
            self._done_host[i] = False
            self._plans[i] = plan
            row = np.zeros((self.pages_per_slot,), np.int32)
            row[: len(plan.pages)] = plan.pages
            self._page_table = self._page_table.at[i].set(
                jnp.asarray(row))
            self._pos = self._pos.at[i].set(pos)
            self._pos_host[i] = pos
            self._mode = self._mode.at[i].set(False)
            self._mode_host[i] = False
            # the prompt's full chunks are valid through pos: complete
            # them now — this is the trie GRAFT that makes the prefix
            # shareable on the decode side
            self._alloc.mark_progress(plan, pos)
            from .. import telemetry as _tel
            if _tel.active():
                _tel.emit("serve.handoff", dir="import", req=int(rid),
                          pages=int(n_pages), bytes=nbytes, pos=pos,
                          dedup_pages=int(plan.n_shared_pages))
            return rid

    def unfreeze_handoff(self, rid: int):
        """Degraded-fleet fallback: no decode-capable replica is left,
        so the frozen slot resumes decoding HERE — the prefill worker
        temporarily breaks its admit-only program diet rather than
        deadlock the request."""
        i = self._handoff_ready.pop(rid)
        # pin the exemption BEFORE clearing done: without it the next
        # _evict sweep would re-freeze this slot instantly (all freeze
        # conditions hold again) and the fleet livelocks on the
        # freeze/unfreeze ping-pong
        self._no_freeze.add(rid)
        self._done = self._done.at[i].set(False)
        self._done_host[i] = False

    # -- hot-prefix replication (fleet-tier cache placement) ---------------
    def export_prefix(self, tokens):
        """Holder side of cache placement: (n_tokens, data) covering
        the resident complete chain for `tokens`, or None when nothing
        is resident.  Read-only and synchronous — gathered at this
        chunk boundary, before any allocation could evict the chain."""
        if self.kv_layout != "paged" or not self.prefix_sharing:
            return None
        n_tok, pages = self._alloc.export_chain(tokens)
        pages = pages[: self.pages_per_slot]
        if not pages:
            return None
        idx = np.zeros((self.pages_per_slot,), np.int32)
        idx[: len(pages)] = pages
        data = self._page_export_fn()(self._cache, jnp.asarray(idx))
        return len(pages) * self.page_size, data

    def import_prefix(self, tokens, n_tokens: int, data) -> int:
        """Target side of cache placement: graft the chain's chunks
        into the local trie (skipping already-resident ones) and
        scatter the holder's page data.  Returns pages grafted; 0
        under pool pressure — placement is best-effort and must never
        starve serving."""
        if self.kv_layout != "paged" or not self.prefix_sharing:
            return 0
        n_chunks = min(int(n_tokens) // self.page_size,
                       self.pages_per_slot)
        pairs = self._alloc.graft(tokens, n_chunks)
        if not pairs:
            return 0
        idx = np.zeros((self.pages_per_slot,), np.int32)
        for ci, page in pairs:
            idx[ci] = page
        self._cache = self._page_import_fn()(
            self._cache, jnp.asarray(idx), data)
        return len(pairs)

    def _step_fn(self, width: int, length: int, record: bool = True):
        """The unified scan program: `length` steps, each feeding a
        [B, width] token block.  record=False (lower_step) builds or
        fetches the program WITHOUT touching the batcher's
        program/timing bookkeeping — an analysis probe must not
        inflate compiled_programs or defeat the first-use compile
        exclusion.  Per slot b and step:

          prefilling?  consume n=min(width, plen-pos) prompt tokens
                       from prompts[b, pos:pos+width]
          decoding?    feed [tok[b], pad...] (n=1)
          free/done?   n=0 (lanes run but nothing advances)

        Lanes past n write throwaway KV at pos+n..pos+width-1; queries
        only see cache rows j <= pos+lane (per-slot position mask in
        ops.cached_attention / ops.paged_attention) and the next step's
        valid lanes overwrite those rows before its queries can reach
        them, so the garbage is never observable (free slots write
        their junk into the null page).  The logit at lane n-1 is
        argmax-sampled; a slot emits iff it decoded or consumed its
        FINAL prompt chunk (the emitted token then being the prompt's
        greedy first token — bit-identical to what a monolithic
        prefill would sample).
        """
        key = self._program_key(width, length)
        # first_use consults the MODEL-level store, not this batcher's
        # key set: an LRU-evicted program that recompiles mid-life is
        # excluded from timing again, and a second batcher reusing a
        # warm program keeps its first chunks in the timing window
        from .generation import (_model_program_cache,
                                 _program_cache_contains)
        first_use = not _program_cache_contains(self.model, key)
        if record:
            self._first_use = first_use
        if record and first_use and key in self._programs_used:
            # mid-life re-trace of a program this batcher already ran
            # (LRU eviction / cleared model cache): snapshot stats()
            # into the telemetry plane BEFORE the rebuild — the counters
            # themselves must survive the recompile (regression-pinned),
            # and the snapshot timestamps exactly which chunks predate
            # the new program (its timing stats restart via _first_use)
            from .. import telemetry as _tel
            if _tel.active():
                _tel.emit("serve.recompile",
                          dict(self.stats(), program=str(key)))
            _tel.counter("serve.recompiles").inc()
        if record:
            self._programs_used.add(key)
        model = self.model
        names = self._names
        C, K = int(width), int(length)
        max_len = self.max_len
        paged = self.kv_layout == "paged"
        spec = self.spec_k > 0
        draft = self._draft
        draft_names = self._draft_names
        from ..jit import _swapped_state

        def build():
            def step_core(carry):
                """One [B, C] step over the shared carry layout; the
                draft (speculation on) consumes the SAME x at the same
                pos so its dense cache stays row-for-row in lockstep
                with the target's — prefill fills both, decode rounds
                in the admit program advance both by one."""
                (cache, dcache, page_table, tok, pos, mode, plen,
                 prompts, done) = carry
                prefilling = mode & ~done
                lanes = jnp.arange(C, dtype=jnp.int32)
                idx = jnp.clip(pos[:, None] + lanes[None], 0,
                               max_len - 1)
                pref_x = jnp.take_along_axis(prompts, idx, axis=1)
                dec_x = jnp.concatenate(
                    [tok[:, None],
                     jnp.zeros((tok.shape[0], C - 1),
                               jnp.int32)], axis=1)
                x = jnp.where(prefilling[:, None], pref_x, dec_x)
                n_valid = jnp.where(
                    prefilling,
                    jnp.minimum(C, plen - pos),
                    jnp.where(done, 0, 1)).astype(jnp.int32)
                if paged:
                    lg, cache = model.forward_cached_paged(
                        x, cache, page_table, pos)
                else:
                    lg, cache = model.forward_cached(x, cache, pos)
                if spec:
                    # draft prefill rides the admit chunk (logits
                    # discarded — XLA DCEs the draft's lm head here)
                    _, dcache = draft.forward_cached(x, dcache, pos)
                last = jnp.clip(n_valid - 1, 0, C - 1)
                lg_last = jnp.take_along_axis(
                    lg, last[:, None, None], axis=1)[:, 0]
                nxt = jnp.argmax(lg_last.astype(jnp.float32),
                                 axis=-1).astype(jnp.int32)
                finishing = prefilling & (pos + n_valid >= plen)
                emit = finishing | (~prefilling & ~done)
                pos = pos + n_valid
                mode = mode & ~finishing
                tok = jnp.where(emit, nxt, tok)
                # clamp: a slot at capacity stops advancing
                done = done | (pos >= max_len - 1)
                out_tok = jnp.where(emit, nxt,
                                    jnp.full_like(nxt, -1))
                n_pref = jnp.sum(
                    jnp.where(prefilling, n_valid, 0))
                n_dec = jnp.sum(
                    (~prefilling
                     & (n_valid > 0)).astype(jnp.int32))
                carry = (cache, dcache, page_table, tok, pos, mode,
                         plen, prompts, done)
                return carry, (out_tok, n_pref, n_dec)

            def run_scan(cache, dcache, page_table, tok, pos, mode,
                         plen, prompts, done):
                def body(carry, _):
                    return step_core(carry)
                carry = (cache, dcache, page_table, tok, pos, mode,
                         plen, prompts, done)
                carry, (toks, n_pref, n_dec) = jax.lax.scan(
                    body, carry, None, length=K)
                return carry, toks.T, jnp.sum(n_pref), jnp.sum(n_dec)

            if spec:
                def serve_step(param_vals, draft_vals, cache, dcache,
                               page_table, tok, pos, mode, plen,
                               prompts, done):
                    with _swapped_state(model, names,
                                        list(param_vals)):
                        if draft_names:
                            with _swapped_state(draft, draft_names,
                                                list(draft_vals)):
                                carry, toks, n_pref, n_dec = run_scan(
                                    cache, dcache, page_table, tok,
                                    pos, mode, plen, prompts, done)
                        else:
                            carry, toks, n_pref, n_dec = run_scan(
                                cache, dcache, page_table, tok, pos,
                                mode, plen, prompts, done)
                    (cache, dcache, page_table, tok, pos, mode, plen,
                     prompts, done) = carry
                    return (cache, dcache, page_table, tok, pos, mode,
                            plen, prompts, done, toks, n_pref, n_dec)
                return jax.jit(serve_step,
                               donate_argnums=(2, 3, 4, 5, 6, 7, 8, 9,
                                               10))

            def serve_step(param_vals, cache, page_table, tok, pos,
                           mode, plen, prompts, done):
                with _swapped_state(model, names, list(param_vals)):
                    carry, toks, n_pref, n_dec = run_scan(
                        cache, None, page_table, tok, pos, mode, plen,
                        prompts, done)
                (cache, _, page_table, tok, pos, mode, plen, prompts,
                 done) = carry
                return (cache, page_table, tok, pos, mode, plen,
                        prompts, done, toks, n_pref, n_dec)
            # donate every carry buffer: the KV pool dominates — a
            # non-donated chunk pays a pool-sized HBM copy per call
            return jax.jit(serve_step,
                           donate_argnums=(1, 2, 3, 4, 5, 6, 7, 8))
        if not record and first_use:
            # probe miss: build a throwaway jit WITHOUT inserting it
            # into the model cache — .lower() never compiles, so a
            # cached probe entry would make the first real chunk look
            # warm (first_use=False) while still paying the XLA
            # compile into the timing stats
            return build()
        return _model_program_cache(model, key, build)

    def _carry_args(self):
        if self.kv_layout == "paged":
            pt = self._page_table
        else:
            # a [B, 1] placeholder rides the dense carry so both
            # layouts share one program signature (and the donation
            # set); it is never read
            pt = jnp.zeros((self.B, 1), jnp.int32)
        if self.spec_k:
            # the draft cache is one more donated carry, slotted right
            # after the target cache; with K=0 the signature is the
            # pre-speculation one, byte for byte
            return (self._cache, self._dcache, pt, self._tok, self._pos,
                    self._mode, self._plen, self._prompts, self._done)
        return (self._cache, pt, self._tok, self._pos, self._mode,
                self._plen, self._prompts, self._done)

    def _draft_param_vals(self):
        if not self._draft_names:
            return []
        sd = self._draft.state_dict()
        return [sd[n]._value for n in self._draft_names]

    def _spec_step_fn(self, record: bool = True):
        """The speculative DECODE program (ISSUE 11): `chunk` scan
        steps, each drafting K tokens with the draft model (an inner
        K+1-step scan — the extra step exists only for its KV write,
        so an all-accepted round leaves no hole in the draft cache)
        and verifying them in ONE target pass of width K+1 — the
        verify width folded into the chunk axis, so the r6 2-programs
        contract holds.  Per slot and step:

          drafts d_1..d_K  = greedy draft continuations of tok
          verify x         = [tok, d_1..d_K] at pos (writes K+1 KV
                             rows, exactly the prefill-chunk lane
                             discipline)
          targets t_i      = argmax of verify lane i-1 — t_1 is
                             PRECISELY the non-speculative next token,
                             and each accepted d_i == t_i keeps the
                             chain exact
          accept a         = longest prefix with d_i == t_i; emit
                             t_1..t_{a+1} (a drafts + the bonus
                             token), advance pos by a+1

        Rejected rows (pos+a+1..pos+K) are never rolled back on
        device: they sit beyond the new frontier, and the next verify
        window overwrites them before any query can attend them (the
        scan's pad-lane discipline) — the HOST rolls back nothing but
        its own pos view, which arrives already-accepted.  Greedy
        output is therefore bit-exact vs non-speculative decode."""
        Kd = self.spec_k
        W = self._spec_w
        key = self._program_key(W, self.chunk)
        from .generation import (_model_program_cache,
                                 _program_cache_contains)
        first_use = not _program_cache_contains(self.model, key)
        if record:
            self._first_use = first_use
            if first_use and key in self._programs_used:
                # mid-life re-trace (LRU eviction / cleared model
                # cache): same snapshot contract as _step_fn
                from .. import telemetry as _tel
                if _tel.active():
                    _tel.emit("serve.recompile",
                              dict(self.stats(), program=str(key)))
                _tel.counter("serve.recompiles").inc()
            self._programs_used.add(key)
        model = self.model
        names = self._names
        draft = self._draft
        draft_names = self._draft_names
        K_steps = self.chunk
        max_len = self.max_len
        paged = self.kv_layout == "paged"
        from ..jit import _swapped_state

        def build():
            def spec_core(carry):
                (cache, dcache, page_table, tok, pos, mode, plen,
                 prompts, done) = carry

                # -- draft K (+1 for the cache write) greedy tokens --
                def dbody(dc, _):
                    dcache, dtok, dpos = dc
                    dlg, dcache = draft.forward_cached(
                        dtok[:, None], dcache, dpos)
                    nxt = jnp.argmax(dlg[:, 0].astype(jnp.float32),
                                     axis=-1).astype(jnp.int32)
                    return (dcache, nxt, dpos + 1), nxt
                (dcache, _, _), drafts = jax.lax.scan(
                    dbody, (dcache, tok, pos), None, length=Kd + 1)
                drafts = drafts.T                       # [B, K+1]

                # -- verify in one width-(K+1) target pass --
                x = jnp.concatenate([tok[:, None], drafts[:, :Kd]],
                                    axis=1)             # [B, K+1]
                if paged:
                    lg, cache = model.forward_cached_paged(
                        x, cache, page_table, pos)
                else:
                    lg, cache = model.forward_cached(x, cache, pos)
                tgt = jnp.argmax(lg.astype(jnp.float32),
                                 axis=-1).astype(jnp.int32)  # [B, K+1]

                # -- accept the longest matching prefix + bonus ------
                match = (drafts[:, :Kd] == tgt[:, :Kd]).astype(
                    jnp.int32)
                acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
                # capacity clamp mirrors the non-speculative one-token
                # steps: never emit past the max_len-1 frontier
                allowed = jnp.maximum(max_len - 1 - pos, 0)
                n_emit = jnp.where(done, 0,
                                   jnp.minimum(acc + 1, allowed)) \
                    .astype(jnp.int32)
                lanes = jnp.arange(W, dtype=jnp.int32)
                emit_mask = lanes[None, :] < n_emit[:, None]
                out_tok = jnp.where(emit_mask, tgt,
                                    jnp.full_like(tgt, -1))
                last = jnp.clip(n_emit - 1, 0, W - 1)
                new_tok = jnp.take_along_axis(
                    tgt, last[:, None], axis=1)[:, 0]
                tok = jnp.where(n_emit > 0, new_tok, tok)
                pos = pos + n_emit
                done = done | (pos >= max_len - 1)
                # true accepted-draft count for the accounting plane:
                # under the capacity clamp n_emit-1 would UNDERCOUNT
                # matches (drafted stays K, so the accepted+rejected==
                # drafted partition needs the unclamped acc)
                n_acc = jnp.where(n_emit > 0, acc, 0)
                carry = (cache, dcache, page_table, tok, pos, mode,
                         plen, prompts, done)
                return carry, (out_tok, n_emit, n_acc)

            def serve_step(param_vals, draft_vals, cache, dcache,
                           page_table, tok, pos, mode, plen, prompts,
                           done):
                def run_scan():
                    def body(carry, _):
                        return spec_core(carry)
                    carry = (cache, dcache, page_table, tok, pos,
                             mode, plen, prompts, done)
                    return jax.lax.scan(body, carry, None,
                                        length=K_steps)
                with _swapped_state(model, names, list(param_vals)):
                    if draft_names:
                        with _swapped_state(draft, draft_names,
                                            list(draft_vals)):
                            carry, (toks, n_emit, n_acc) = run_scan()
                    else:
                        carry, (toks, n_emit, n_acc) = run_scan()
                (cache, dcache, page_table, tok, pos, mode, plen,
                 prompts, done) = carry
                # [K_steps, B, W] -> [B, K_steps*W]: each slot's row is
                # its chunk-ordered emission stream (-1 = no token),
                # the same harvest contract as the plain decode program
                toks = toks.transpose(1, 0, 2).reshape(
                    toks.shape[1], K_steps * W)
                n_dec = jnp.sum(n_emit)
                return (cache, dcache, page_table, tok, pos, mode,
                        plen, prompts, done, toks, n_emit.T, n_acc.T,
                        jnp.asarray(0, jnp.int32), n_dec)
            return jax.jit(serve_step,
                           donate_argnums=(2, 3, 4, 5, 6, 7, 8, 9, 10))
        if not record and first_use:
            return build()
        return _model_program_cache(model, key, build)

    def lower_step(self, mixed: bool = False):
        """`jax.stages.Lowered` for the (admission if mixed else
        decode) step program with its donation set — the analysis
        suite's entry point for lint_donation over the paged carries.
        Under speculation the decode program is the draft/verify scan
        and both programs carry the (donated) draft cache.  A pure
        probe: it never touches the batcher's program or timing
        bookkeeping (record=False)."""
        if mixed:
            fn = self._step_fn(self.prefill_chunk, self.admit_steps,
                               record=False)
        elif self.spec_k:
            fn = self._spec_step_fn(record=False)
        else:
            fn = self._step_fn(1, self.chunk, record=False)
        if self.spec_k:
            return fn.lower(self._param_vals(),
                            self._draft_param_vals(),
                            *self._carry_args())
        return fn.lower(self._param_vals(), *self._carry_args())

    def _run_chunk(self, mixed: bool):
        from ..distributed import fault
        if mixed:
            fn = self._step_fn(self.prefill_chunk, self.admit_steps)
        elif self.spec_k:
            fn = self._spec_step_fn()
        else:
            fn = self._step_fn(1, self.chunk)
        t0 = time.perf_counter()
        kind = "admit" if mixed else "decode"
        n_emit = n_acc = None
        try:
            # the chunk dispatch runs under the serve watchdog
            # (FLAGS_stop_check_timeout): a hang dumps thread stacks /
            # aborts per the r9 contract, and a delay-injected chunk
            # that ages past the deadline is counted as hung below.
            # The serve.chunk fault fires INSIDE the watched window
            # but BEFORE fn touches the donated carries — an injected
            # chunk fault loses nothing; the chunk retries at the next
            # boundary (under speculation that includes a fault
            # mid-verify: no draft token ever leaks from a chunk that
            # never returned)
            with self._watch:
                fault.hit("serve.chunk", key=kind)
                if self.spec_k:
                    out = fn(self._param_vals(),
                             self._draft_param_vals(),
                             *self._carry_args())
                    if mixed:
                        (self._cache, self._dcache, page_table,
                         self._tok, self._pos, self._mode, self._plen,
                         self._prompts, self._done, toks, n_pref,
                         n_dec) = out
                    else:
                        (self._cache, self._dcache, page_table,
                         self._tok, self._pos, self._mode, self._plen,
                         self._prompts, self._done, toks, n_emit,
                         n_acc, n_pref, n_dec) = out
                else:
                    (self._cache, page_table, self._tok, self._pos,
                     self._mode, self._plen, self._prompts, self._done,
                     toks, n_pref, n_dec) = fn(self._param_vals(),
                                               *self._carry_args())
        except fault.FaultError:
            self._chunk_retries += 1
            self._consecutive_chunk_faults += 1
            from .. import telemetry as _tel
            _tel.counter("serve.chunk_retries").inc()
            if _tel.active():
                _tel.emit("serve.chunk_fault", kind=kind,
                          retries=self._chunk_retries)
            # a PERSISTENT chunk fault (times=*) would otherwise spin
            # run() forever — past the budget, surface it to the
            # caller like StepAnomalyGuard's bad-step budget
            if self._consecutive_chunk_faults > int(
                    get_flag("serve_retry_budget") or 3):
                raise
            return
        self._consecutive_chunk_faults = 0
        if self._watch.last_reported:
            self._hung_chunks += 1
            from .. import telemetry as _tel
            _tel.counter("serve.hung_chunks").inc()
            if _tel.active():
                _tel.emit("serve.hung", kind=kind,
                          wall_ms=round(
                              (time.perf_counter() - t0) * 1e3, 3))
        if self.kv_layout == "paged":
            self._page_table = page_table
        # ONE batched host transfer per chunk — each device_get is a
        # blocking round trip (~10ms on the tunneled relay), so
        # fetching tokens/mode/done/pos/counters separately would pay
        # it six times per boundary
        (toks, mode_h, done_h, pos_h, n_pref, n_dec, n_emit,
         n_acc) = jax.device_get(
            (toks, self._mode, self._done, self._pos, n_pref, n_dec,
             n_emit, n_acc))
        toks = np.asarray(toks)                 # [B, K] / [B, K*(k+1)]
        self._mode_host = np.array(mode_h)
        self._done_host = np.array(done_h)
        self._pos_host = np.array(pos_h)
        # serve.decode: per-live-slot fault sweep — a poisoned slot is
        # evicted and its request requeued/shed (_fault_slot) BEFORE
        # its pending trie nodes could be marked complete or its
        # chunk tokens harvested, while every other slot proceeds
        # untouched.  Unset, this whole block is one cached string
        # compare (fault.is_active)
        if fault.is_active():
            faulted = []
            for i, req in enumerate(self._slots):
                if req is None:
                    continue
                try:
                    f = fault.hit("serve.decode",
                                  key=f"slot{i}:req{req.req_id}")
                except fault.FaultError:
                    faulted.append(i)
                    continue
                if f is not None:   # data modes poison the slot too
                    faulted.append(i)
            for i in faulted:
                self._fault_slot(i)
        dt = time.perf_counter() - t0
        # a program's FIRST call may include its XLA compile — keep it
        # out of the wall-time stats so chunk_time_max/p50 describe
        # steady-state chunks, not a one-time multi-second compile
        if not self._first_use:
            self._chunk_times.append(dt)
            self._chunk_time_max = max(self._chunk_time_max, dt)
        self._chunk_count += 1
        self._chunk_kind_counts["admit" if mixed else "decode"] += 1
        self._occupancy_total += self.active
        self._prefill_tok_total += int(n_pref)
        self._decode_tok_total += int(n_dec)
        if n_emit is not None:
            # speculation accounting (ISSUE 11): n_emit [B, K_steps] is
            # tokens emitted per slot per scan step (0 = inactive);
            # n_acc carries the TRUE accepted-draft count per step —
            # n_emit-1 would undercount on a capacity-clamped step —
            # so accepted + rejected == drafted holds exactly
            ne = np.asarray(n_emit)
            active = ne > 0
            n_active = int(active.sum())
            drafted = n_active * self.spec_k
            accepted = int(np.asarray(n_acc)[active].sum())
            self._spec_drafted += drafted
            self._spec_accepted += accepted
            self._spec_steps += n_active
            self._spec_emit_window.extend(int(v) for v in ne[active])
            from .. import telemetry as _tel
            _tel.counter("serve.spec_drafted").inc(drafted)
            _tel.counter("serve.spec_accepted").inc(accepted)
            if _tel.active():
                _tel.emit("serve.spec", drafted=drafted,
                          accepted=accepted, steps=n_active,
                          accept_rate=round(accepted / drafted, 4)
                          if drafted else 0.0)
                for v in ne[active]:
                    _tel.histogram("serve.accepted_per_step") \
                        .observe(float(v))
        if self.kv_layout == "paged":
            # prompt pages that finished filling this chunk become
            # shareable for the NEXT admission
            for i, plan in enumerate(self._plans):
                if plan is not None and plan.nodes:
                    self._alloc.mark_progress(plan,
                                              int(self._pos_host[i]))
        from .. import telemetry as _tel
        _tel.counter("serve.chunks").inc()       # sink or not
        if _tel.active():
            _tel.emit("serve.chunk",
                      kind="admit" if mixed else "decode",
                      wall_ms=round(dt * 1e3, 3),
                      occupancy=self.active, slots=self.B,
                      prefill_tokens=int(n_pref),
                      decode_tokens=int(n_dec),
                      first_use=self._first_use)
            _tel.histogram("serve.chunk_ms").observe(dt * 1e3)
            # cost ledger measured-wall feed (ISSUE 12): the chunk
            # wall lands on the ledger label of the very program that
            # ran it; first_use walls (may include the compile) are
            # excluded like the chunk-time stats above
            _tel.costledger.observe(
                "serve_step.admit" if mixed else "serve_step.decode",
                dt * 1e3, cold=self._first_use)
            if self.kv_layout == "paged":
                _tel.emit("serve.kv",
                          pages=self.num_pages,
                          pages_used=self._alloc.pages_used,
                          pages_free=self._alloc.pages_free,
                          pages_cached=self._alloc.pages_cached,
                          prefix_hit_tokens=self._alloc
                          .prefix_hit_tokens,
                          evictions=self._alloc.evictions,
                          kv_bytes=self.kv_cache_bytes())
        t_harvest = self._now()
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            req.tokens.extend(int(t) for t in toks[i] if t >= 0)
            if req.t_first is None and req.tokens:
                req.t_first = t_harvest
            # streaming: hand out this chunk's burst now — TTFT for an
            # interactive caller is the FIRST chunk boundary, not
            # run()'s return (speculation lands accepted runs here in
            # one burst)
            self._deliver(req, done=False)

