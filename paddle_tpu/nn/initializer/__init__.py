"""Weight initializers.

Reference: `python/paddle/nn/initializer/` (Constant, Normal, Uniform,
XavierNormal/Uniform, KaimingNormal/Uniform, TruncatedNormal, Orthogonal,
Assign, Dirac, calculate_gain).

TPU-native: initializers produce jnp arrays directly from the global
deterministic PRNG (framework.random), so init is reproducible per seed and
identical across SPMD replicas (key is data, not device state).
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ...framework import dtypes
from ...framework.random import next_key

__all__ = ["Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
           "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
           "Assign", "Orthogonal", "Dirac", "calculate_gain",
           "set_global_initializer"]


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtypes.to_jax(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        jd = dtypes.to_jax(dtype)
        return (self.mean + self.std
                * jax.random.normal(next_key(), shape, jnp.float32)
                ).astype(jd)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0, name=None):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        jd = dtypes.to_jax(dtype)
        lo = (self.a - 0.0)
        hi = (self.b - 0.0)
        z = jax.random.truncated_normal(next_key(), lo, hi, shape,
                                        jnp.float32)
        return (self.mean + self.std * z).astype(jd)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        jd = dtypes.to_jax(dtype)
        return jax.random.uniform(next_key(), shape, jnp.float32,
                                  self.low, self.high).astype(jd)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return Normal(0.0, std)(shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return Uniform(-limit, limit)(shape, dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return Normal(0.0, std)(shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return Uniform(-limit, limit)(shape, dtype)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, shape, dtype):
        from ...framework.tensor import Tensor
        v = self.value
        if isinstance(v, Tensor):
            v = v.value
        arr = jnp.asarray(np.asarray(v)).astype(dtypes.to_jax(dtype))
        return arr.reshape(shape)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def __call__(self, shape, dtype):
        jd = dtypes.to_jax(dtype)
        rows = shape[0]
        cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        flat = jax.random.normal(next_key(), (max(rows, cols),
                                              min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(jd)


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, shape, dtype):
        arr = np.zeros(shape, np.float32)
        co, ci = shape[0], shape[1]
        per = co // self.groups
        for g in range(self.groups):
            for i in range(min(per, ci)):
                idx = (g * per + i, i) + tuple(s // 2 for s in shape[2:])
                arr[idx] = 1.0
        return jnp.asarray(arr).astype(dtypes.to_jax(dtype))


def calculate_gain(nonlinearity, param=None):
    if nonlinearity in ("sigmoid", "linear", "conv1d", "conv2d", "conv3d",
                       "conv_transpose1d", "conv_transpose2d",
                       "conv_transpose3d"):
        return 1.0
    if nonlinearity == "tanh":
        return 5.0 / 3.0
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = param if param is not None else 0.01
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity == "selu":
        return 3.0 / 4.0
    raise ValueError(f"unknown nonlinearity {nonlinearity}")


_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init
