"""Activation functionals.

Reference: `python/paddle/nn/functional/activation.py`.  All are jnp/jax.nn
one-liners — XLA fuses them into adjacent matmuls (HBM-bandwidth win), which
is why there are no hand-written kernels here.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.dispatch import run, to_tensor_args
from ...framework.tensor import Tensor


def _unary(jfn, opname):
    def op(x, name=None):
        (x,) = to_tensor_args(x)
        return run(jfn, x, name=opname)
    op.__name__ = opname
    return op


relu = _unary(jax.nn.relu, "relu")
relu6 = _unary(jax.nn.relu6, "relu6")
sigmoid = _unary(jax.nn.sigmoid, "sigmoid")
tanh = _unary(jnp.tanh, "tanh")
silu = _unary(jax.nn.silu, "silu")
swish = silu
mish = _unary(lambda v: v * jnp.tanh(jax.nn.softplus(v)), "mish")
softsign = _unary(jax.nn.soft_sign, "softsign")
tanhshrink = _unary(lambda v: v - jnp.tanh(v), "tanhshrink")
log_sigmoid = _unary(jax.nn.log_sigmoid, "log_sigmoid")


def relu_(x, name=None):
    out = relu(x)
    x._value = out._value
    x._set_ref(out._ref)
    x.stop_gradient = out.stop_gradient
    return x


def gelu(x, approximate=False, name=None):
    (x,) = to_tensor_args(x)
    return run(lambda v: jax.nn.gelu(v, approximate=approximate), x,
               name="gelu")


def leaky_relu(x, negative_slope=0.01, name=None):
    (x,) = to_tensor_args(x)
    return run(lambda v: jax.nn.leaky_relu(v, negative_slope), x,
               name="leaky_relu")


def prelu(x, weight, data_format="NCHW", name=None):
    x, weight = to_tensor_args(x, weight)

    def _fn(v, w):
        if w.size > 1 and v.ndim > 1:
            shape = [1] * v.ndim
            ch_axis = 1 if data_format[1] == "C" else v.ndim - 1
            shape[ch_axis] = w.size
            w = w.reshape(shape)
        return jnp.where(v >= 0, v, w * v)
    return run(_fn, x, weight, name="prelu")


def elu(x, alpha=1.0, name=None):
    (x,) = to_tensor_args(x)
    return run(lambda v: jax.nn.elu(v, alpha), x, name="elu")


def celu(x, alpha=1.0, name=None):
    (x,) = to_tensor_args(x)
    return run(lambda v: jax.nn.celu(v, alpha), x, name="celu")


def selu(x,
         scale=1.0507009873554804934193349852946,
         alpha=1.6732632423543772848170429916717, name=None):
    (x,) = to_tensor_args(x)
    return run(lambda v: scale * jnp.where(v > 0, v,
                                           alpha * jnp.expm1(v)), x,
               name="selu")


def hardshrink(x, threshold=0.5, name=None):
    (x,) = to_tensor_args(x)
    return run(lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0), x,
               name="hardshrink")


def softshrink(x, threshold=0.5, name=None):
    (x,) = to_tensor_args(x)
    return run(lambda v: jnp.where(v > threshold, v - threshold,
                                   jnp.where(v < -threshold, v + threshold,
                                             0.0)), x, name="softshrink")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    (x,) = to_tensor_args(x)
    return run(lambda v: jnp.clip(v, min, max), x, name="hardtanh")


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    (x,) = to_tensor_args(x)
    return run(lambda v: jnp.clip(slope * v + offset, 0.0, 1.0), x,
               name="hardsigmoid")


def hardswish(x, name=None):
    (x,) = to_tensor_args(x)
    return run(lambda v: v * jnp.clip(v + 3.0, 0.0, 6.0) / 6.0, x,
               name="hardswish")


def softplus(x, beta=1, threshold=20, name=None):
    (x,) = to_tensor_args(x)
    return run(lambda v: jnp.where(beta * v > threshold, v,
                                   jax.nn.softplus(beta * v) / beta), x,
               name="softplus")


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    (x,) = to_tensor_args(x)
    return run(lambda v: jnp.where(v > threshold, v, value), x,
               name="thresholded_relu")


def softmax(x, axis=-1, dtype=None, name=None):
    (x,) = to_tensor_args(x)
    if dtype is not None:
        from ...framework import dtypes
        x = run(lambda v: v.astype(dtypes.to_jax(dtype)), x)
    return run(lambda v: jax.nn.softmax(v, axis=axis), x, name="softmax")


softmax_ = softmax


def log_softmax(x, axis=-1, dtype=None, name=None):
    (x,) = to_tensor_args(x)
    if dtype is not None:
        from ...framework import dtypes
        x = run(lambda v: v.astype(dtypes.to_jax(dtype)), x)
    return run(lambda v: jax.nn.log_softmax(v, axis=axis), x,
               name="log_softmax")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...framework.random import next_key
    (x,) = to_tensor_args(x)
    g = jax.random.gumbel(next_key(), x.value.shape, x.value.dtype)

    def _fn(v):
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis,
                                        inplace=False)
            y = jax.lax.stop_gradient(y_hard - y) + y
        return y
    return run(_fn, x, name="gumbel_softmax")


def maxout(x, groups, axis=1, name=None):
    (x,) = to_tensor_args(x)

    def _fn(v):
        # reference formula (activation.py:873): out channel i = max
        # over the CONSECUTIVE group [g*i, g*i+g) → Co = Ci/groups
        ax = axis if axis >= 0 else axis + v.ndim   # NHWC uses axis=-1
        shp = list(v.shape)
        c = shp[ax]
        shp[ax:ax + 1] = [c // groups, groups]
        return jnp.max(v.reshape(shp), axis=ax + 1)
    return run(_fn, x, name="maxout")


def glu(x, axis=-1, name=None):
    (x,) = to_tensor_args(x)
    return run(lambda v: jax.nn.glu(v, axis=axis), x, name="glu")


def swiglu(x, y=None, name=None):
    """Reference: python/paddle/incubate/nn/functional/swiglu.py."""
    if y is None:
        (x,) = to_tensor_args(x)
        return run(lambda v: jax.nn.silu(v[..., : v.shape[-1] // 2])
                   * v[..., v.shape[-1] // 2:], x, name="swiglu")
    x, y = to_tensor_args(x, y)
    return run(lambda a, b: jax.nn.silu(a) * b, x, y, name="swiglu")


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, name=None):
    from ...framework.random import next_key
    (x,) = to_tensor_args(x)
    if training:
        a = jax.random.uniform(next_key(), x.value.shape, jnp.float32,
                               lower, upper).astype(x.value.dtype)
    else:
        a = jnp.asarray((lower + upper) / 2.0, x.value.dtype)
    return run(lambda v: jnp.where(v >= 0, v, a * v), x, name="rrelu")
