"""Convolution functionals.

Reference: `python/paddle/nn/functional/conv.py` → phi conv kernels (cuDNN).
TPU-native: `jax.lax.conv_general_dilated` — XLA maps convs onto the MXU
directly; NCHW layouts are accepted and internally transposed by XLA as
needed (TPU prefers NHWC; Conv layers expose data_format for users who want
the native layout end-to-end).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.dispatch import run, to_tensor_args


def _tuplize(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    if len(v) == 1:
        return v * n
    return v


def _padding(padding, n, strides, dilations, ksize):
    """Normalize paddle padding spec to lax padding list or 'SAME'/'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    # nested [[lo, hi], ...] possibly including batch/channel dims
    if all(isinstance(p, (list, tuple)) for p in padding):
        flat = [tuple(p) for p in padding]
        if len(flat) == n + 2:
            flat = flat[2:]
        return flat
    raise ValueError(f"bad padding {padding}")


def _conv(x, weight, bias, stride, padding, dilation, groups, nd,
          data_format, transpose=False, output_padding=0, output_size=None):
    strides = _tuplize(stride, nd)
    dilations = _tuplize(dilation, nd)
    chan_last = data_format[-1] == "C"
    if nd == 1:
        dn_in = "NCH" if not chan_last else "NHC"
        dn_out = dn_in
        dn_k = "OIH"
    elif nd == 2:
        dn_in = "NCHW" if not chan_last else "NHWC"
        dn_out = dn_in
        dn_k = "OIHW"
    else:
        dn_in = "NCDHW" if not chan_last else "NDHWC"
        dn_out = dn_in
        dn_k = "OIDHW"
    dnums = (dn_in, dn_k, dn_out)
    ksize = tuple(weight.shape[2:])
    pad = _padding(padding, nd, strides, dilations, ksize)

    def _fn(v, w, *b):
        if not transpose:
            out = jax.lax.conv_general_dilated(
                v, w, strides, pad, rhs_dilation=dilations,
                dimension_numbers=dnums, feature_group_count=groups,
                preferred_element_type=None)
        else:
            # conv_transpose: gradient of conv w.r.t. input.
            # weight layout in paddle is [in, out//groups, *k]
            opad = _tuplize(output_padding, nd)
            if isinstance(pad, str):
                pads = None
            else:
                pads = pad
            if pads is None:
                k_eff = [(k - 1) * d + 1 for k, d in zip(ksize, dilations)]
                if pad == "SAME":
                    pads = [((ke - 1) // 2, ke // 2) for ke in k_eff]
                else:
                    pads = [(0, 0)] * nd
            k_eff = [(k - 1) * d + 1 for k, d in zip(ksize, dilations)]
            tpads = [(ke - 1 - p[0], ke - 1 - p[1] + op)
                     for ke, p, op in zip(k_eff, pads, opad)]
            # flip spatial dims and swap in/out channels
            wt = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
            wt = jnp.swapaxes(wt, 0, 1)  # [out//g, in, *k] → lax OIHW with
            if groups > 1:
                ci = w.shape[0]
                co_g = w.shape[1]
                wt = w.reshape(groups, ci // groups, co_g, *ksize)
                wt = jnp.flip(wt, axis=tuple(range(3, 3 + nd)))
                wt = jnp.swapaxes(wt, 1, 2)
                wt = wt.reshape(groups * co_g, ci // groups, *ksize)
            out = jax.lax.conv_general_dilated(
                v, wt, (1,) * nd, tpads, lhs_dilation=strides,
                rhs_dilation=dilations, dimension_numbers=dnums,
                feature_group_count=groups)
        if b:
            bias_shape = [1] * out.ndim
            c_ax = 1 if not chan_last else out.ndim - 1
            bias_shape[c_ax] = b[0].shape[0]
            out = out + b[0].reshape(bias_shape)
        return out

    args = (x, weight) + ((bias,) if bias is not None else ())
    ts = to_tensor_args(*args)
    out = run(_fn, *ts, name="conv_transpose" if transpose else "conv")
    if transpose and output_size is not None:
        want = tuple(int(s) for s in
                     (output_size if isinstance(output_size, (list, tuple))
                      else [output_size] * nd))
        got = tuple(out.shape[2:]) if not chan_last else tuple(
            out.shape[1:-1])
        if want != got:
            from ...tensor.manipulation import pad as _pad
            extra = []
            for w_, g_ in zip(want[::-1], got[::-1]):
                extra += [0, w_ - g_]
            out = _pad(out, extra, data_format=data_format)
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 data_format, transpose=True, output_padding=output_padding,
                 output_size=output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format, transpose=True, output_padding=output_padding,
                 output_size=output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format, transpose=True, output_padding=output_padding,
                 output_size=output_size)
