"""paddle_tpu.nn.functional — reference: python/paddle/nn/functional/."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import (conv1d, conv2d, conv3d, conv1d_transpose,  # noqa: F401
                   conv2d_transpose, conv3d_transpose)
from .pooling import *  # noqa: F401,F403
from .norm import (layer_norm, batch_norm, instance_norm,  # noqa: F401
                   group_norm, local_response_norm, rms_norm)
from .loss import *  # noqa: F401,F403
from .flash_attention import (scaled_dot_product_attention,  # noqa: F401
                              flash_attention, flash_attn_qkvpacked,
                              flash_attn_unpadded, sdp_kernel)
