"""Pooling functionals.

Reference: `python/paddle/nn/functional/pooling.py` → phi pool kernels.
TPU-native: `jax.lax.reduce_window`.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.dispatch import run, to_tensor_args
from .conv import _tuplize


def _pool_pad(padding, nd):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * nd
    padding = list(padding)
    if all(isinstance(p, int) for p in padding):
        if len(padding) == nd:
            return [(p, p) for p in padding]
        if len(padding) == 2 * nd:
            return [(padding[2 * i], padding[2 * i + 1]) for i in range(nd)]
    flat = [tuple(p) for p in padding]
    if len(flat) == nd + 2:
        flat = flat[2:]
    return flat


def _reduce_window(v, init, op, window, strides, pads, chan_last, nd):
    if chan_last:
        full_window = (1,) + window + (1,)
        full_strides = (1,) + strides + (1,)
        full_pads = ((0, 0),) + tuple(pads) + ((0, 0),) \
            if not isinstance(pads, str) else pads
    else:
        full_window = (1, 1) + window
        full_strides = (1, 1) + strides
        full_pads = ((0, 0), (0, 0)) + tuple(pads) \
            if not isinstance(pads, str) else pads
    return jax.lax.reduce_window(v, init, op, full_window, full_strides,
                                 full_pads)


def _pool(x, kernel_size, stride, padding, nd, data_format, mode,
          ceil_mode=False, exclusive=True, count_include_pad=None):
    (x,) = to_tensor_args(x)
    window = _tuplize(kernel_size, nd)
    strides = _tuplize(stride if stride is not None else kernel_size, nd)
    pads = _pool_pad(padding, nd)
    chan_last = data_format[-1] == "C"
    if count_include_pad is not None:
        exclusive = not count_include_pad

    def _fn(v):
        if mode == "max":
            if jnp.issubdtype(v.dtype, jnp.integer):
                init = int(jnp.iinfo(v.dtype).min)
            else:
                init = -jnp.inf
            return _reduce_window(v, init, jax.lax.max, window, strides,
                                  pads, chan_last, nd)
        # avg
        summed = _reduce_window(v, 0.0, jax.lax.add, window, strides, pads,
                                chan_last, nd)
        if isinstance(pads, str) or not exclusive:
            denom = float(np.prod(window))
            return summed / jnp.asarray(denom, v.dtype)
        ones = jnp.ones(v.shape, v.dtype)
        counts = _reduce_window(ones, 0.0, jax.lax.add, window, strides,
                                pads, chan_last, nd)
        return summed / counts
    return run(_fn, x, name=f"{mode}_pool{nd}d")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, data_format, "max",
                 ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, "max",
                 ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, "max",
                 ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, data_format, "avg",
                 ceil_mode, exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, "avg",
                 ceil_mode, exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, "avg",
                 ceil_mode, exclusive)


def _adaptive_pool(x, output_size, nd, data_format, mode):
    (x,) = to_tensor_args(x)
    chan_last = data_format[-1] == "C"
    out_sizes = _tuplize(output_size, nd)

    def _fn(v):
        spatial_axes = list(range(1, 1 + nd)) if chan_last \
            else list(range(2, 2 + nd))
        out = v
        for ax_i, ax in enumerate(spatial_axes):
            osz = out_sizes[ax_i]
            if osz is None:
                continue
            isz = out.shape[ax]
            if isz % osz == 0:
                k = isz // osz
                shp = list(out.shape)
                shp[ax:ax + 1] = [osz, k]
                r = out.reshape(shp)
                out = (jnp.max(r, axis=ax + 1) if mode == "max"
                       else jnp.mean(r, axis=ax + 1))
            else:
                # general adaptive: variable windows via segment means
                starts = (np.arange(osz) * isz) // osz
                ends = ((np.arange(osz) + 1) * isz + osz - 1) // osz
                pieces = []
                for s, e in zip(starts, ends):
                    seg = jnp.take(out, jnp.arange(s, e), axis=ax)
                    red = (jnp.max(seg, axis=ax, keepdims=True)
                           if mode == "max"
                           else jnp.mean(seg, axis=ax, keepdims=True))
                    pieces.append(red)
                out = jnp.concatenate(pieces, axis=ax)
        return out
    return run(_fn, x, name=f"adaptive_{mode}_pool{nd}d")


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "NCL", "avg")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, data_format, "avg")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, data_format, "avg")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, "NCL", "max")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, "NCHW", "max")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, "NCDHW", "max")
