"""Normalization functionals.

Reference: `python/paddle/nn/functional/norm.py` → phi batch_norm/layer_norm
kernels; fused rms_norm in `python/paddle/incubate/nn/functional/`.
TPU-native: explicit jnp math — XLA fuses the whole normalization into one
pass; a Pallas fused rmsnorm (paddle_tpu/ops) covers the hot LLM path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.dispatch import run, to_tensor_args
from ...framework.tensor import Tensor


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05,
               name=None):
    (x,) = to_tensor_args(x)
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    nd = len(normalized_shape)
    axes = tuple(range(x.ndim - nd, x.ndim))

    args = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        args.append(weight)
    if has_b:
        args.append(bias)

    def _fn(v, *wb):
        # stats in fp32 for bf16 inputs (reference computes in fp32 too)
        vf = v.astype(jnp.float32) if v.dtype in (jnp.bfloat16, jnp.float16) \
            else v
        mean = jnp.mean(vf, axis=axes, keepdims=True)
        var = jnp.var(vf, axis=axes, keepdims=True)
        out = (vf - mean) * jax.lax.rsqrt(var + epsilon)
        out = out.astype(v.dtype)
        i = 0
        if has_w:
            out = out * wb[i]
            i += 1
        if has_b:
            out = out + wb[i]
        return out
    return run(_fn, *to_tensor_args(*args), name="layer_norm")


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (reference: incubate/nn/functional/fused_rms_norm.py).
    Dispatches to the Pallas kernel on TPU via paddle_tpu.ops."""
    from ...ops import rms_norm as _rms_impl
    (x,) = to_tensor_args(x)
    if weight is not None:
        (weight,) = to_tensor_args(weight)
        return run(lambda v, w: _rms_impl(v, w, epsilon), x, weight,
                   name="rms_norm")
    return run(lambda v: _rms_impl(v, None, epsilon), x, name="rms_norm")


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05,
               data_format="NCHW", use_global_stats=None, name=None):
    (x,) = to_tensor_args(x)
    chan_last = data_format[-1] == "C" and x.ndim > 2
    c_ax = x.ndim - 1 if chan_last else (1 if x.ndim > 1 else 0)
    red_axes = tuple(a for a in range(x.ndim) if a != c_ax)
    shape = [1] * x.ndim
    shape[c_ax] = x.shape[c_ax]

    use_batch = training and not use_global_stats

    if use_batch:
        vf = x.value.astype(jnp.float32)
        bm = jnp.mean(vf, axis=red_axes)
        bv = jnp.var(vf, axis=red_axes)
        # update running stats in place: eager mutation always; under
        # jit tracing ONLY inside _swapped_state (the jitted trainers
        # capture the traced buffer values and thread them out of the
        # step; anywhere else a traced write would leak a tracer into
        # the live buffer)
        from ...jit import in_swapped_state
        if running_mean is not None and (
                not isinstance(x.value, jax.core.Tracer)
                or in_swapped_state()):
            rm = running_mean.value.astype(jnp.float32)
            rv = running_var.value.astype(jnp.float32)
            running_mean._value = (momentum * rm + (1 - momentum) * bm
                                   ).astype(running_mean.value.dtype)
            n = 1
            for a in red_axes:
                n *= x.shape[a]
            unbiased = bv * n / max(n - 1, 1)
            running_var._value = (momentum * rv + (1 - momentum) * unbiased
                                  ).astype(running_var.value.dtype)
        mean_arr, var_arr = bm, bv
    else:
        mean_arr = running_mean.value.astype(jnp.float32)
        var_arr = running_var.value.astype(jnp.float32)

    args = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        args.append(weight)
    if has_b:
        args.append(bias)

    def _fn(v, *wb):
        vf = v.astype(jnp.float32)
        out = (vf - mean_arr.reshape(shape)) * jax.lax.rsqrt(
            var_arr.reshape(shape) + epsilon)
        out = out.astype(v.dtype)
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        return out
    return run(_fn, *to_tensor_args(*args), name="batch_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-05,
                  data_format="NCHW", name=None):
    (x,) = to_tensor_args(x)
    c_ax = 1
    red_axes = tuple(range(2, x.ndim))
    shape = [1] * x.ndim
    shape[c_ax] = x.shape[c_ax]

    args = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        args.append(weight)
    if has_b:
        args.append(bias)

    def _fn(v, *wb):
        vf = v.astype(jnp.float32)
        mean = jnp.mean(vf, axis=red_axes, keepdims=True)
        var = jnp.var(vf, axis=red_axes, keepdims=True)
        out = ((vf - mean) * jax.lax.rsqrt(var + eps)).astype(v.dtype)
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        return out
    return run(_fn, *to_tensor_args(*args), name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    (x,) = to_tensor_args(x)
    chan_last = data_format[-1] == "C" and x.ndim > 2
    c_ax = x.ndim - 1 if chan_last else 1
    c = x.shape[c_ax]
    shape = [1] * x.ndim
    shape[c_ax] = c

    args = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        args.append(weight)
    if has_b:
        args.append(bias)

    def _fn(v, *wb):
        vf = v.astype(jnp.float32)
        if chan_last:
            vm = jnp.moveaxis(vf, -1, 1)
        else:
            vm = vf
        n = vm.shape[0]
        g = vm.reshape(n, num_groups, c // num_groups, *vm.shape[2:])
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = (g - mean) * jax.lax.rsqrt(var + epsilon)
        out = out.reshape(vm.shape)
        if chan_last:
            out = jnp.moveaxis(out, 1, -1)
        out = out.astype(v.dtype)
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        return out
    return run(_fn, *to_tensor_args(*args), name="group_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    (x,) = to_tensor_args(x)

    def _fn(v):
        sq = v * v
        c_ax = 1 if data_format[1] == "C" else v.ndim - 1
        sqm = jnp.moveaxis(sq, c_ax, -1)
        pad_lo = (size - 1) // 2
        pad_hi = size - 1 - pad_lo
        padded = jnp.pad(sqm, [(0, 0)] * (sqm.ndim - 1) + [(pad_lo, pad_hi)])
        windows = jnp.stack([padded[..., i:i + sqm.shape[-1]]
                             for i in range(size)], axis=0)
        summed = jnp.sum(windows, axis=0)
        summed = jnp.moveaxis(summed, -1, c_ax)
        div = jnp.power(k + alpha * summed, beta)
        return v / div
    return run(_fn, x, name="local_response_norm")
