"""Attention functionals.

Reference: `python/paddle/nn/functional/flash_attention.py` (1608 LoC; sdp
kernel selection at :37, `flash_attn`, `flash_attn_unpadded:593`, qkvpacked
variants) wrapping the external flash-attn CUDA library via phi kernels.

TPU-native: `paddle_tpu.ops.flash_attention` — a Pallas splash/flash kernel
on TPU with an XLA reference path on CPU.  Layout follows the reference:
q/k/v are [batch, seq, num_heads, head_dim].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.dispatch import run, to_tensor_args
from ...framework.tensor import Tensor

__all__ = ["scaled_dot_product_attention", "flash_attention",
           "flash_attn_qkvpacked", "sdp_kernel", "flash_attn_unpadded"]


def _sdpa_raw(q, k, v, mask=None, is_causal=False, dropout_p=0.0,
              scale=None):
    from ...ops import attention as ops_attention
    return ops_attention(q, k, v, mask=mask, causal=is_causal,
                        scale=scale, dropout_p=dropout_p)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Reference signature: nn/functional/flash_attention.py:scaled_dot_
    product_attention.  Inputs [b, s, h, d]; returns [b, s, h, d]."""
    query, key, value = to_tensor_args(query, key, value)
    p = dropout_p if training else 0.0
    if attn_mask is not None:
        (attn_mask,) = to_tensor_args(attn_mask)
        return run(lambda q, k, v, m: _sdpa_raw(q, k, v, mask=m,
                                                is_causal=is_causal,
                                                dropout_p=p),
                   query, key, value, attn_mask, name="sdpa")
    return run(lambda q, k, v: _sdpa_raw(q, k, v, is_causal=is_causal,
                                         dropout_p=p),
               query, key, value, name="sdpa")


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """Reference: flash_attention.py flash_attn — returns (out, softmax)."""
    out = scaled_dot_product_attention(query, key, value, None, dropout,
                                       causal, training)
    return out, None


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False,
                         return_softmax=False, fixed_seed_offset=None,
                         rng_name="", training=True, name=None):
    """Reference: flash_attention.py:399 flash_attn_qkvpacked.
    qkv: [b, s, 3, h, d]."""
    (qkv,) = to_tensor_args(qkv)

    def _fn(x):
        q, k, v = x[:, :, 0], x[:, :, 1], x[:, :, 2]
        return _sdpa_raw(q, k, v, is_causal=causal,
                         dropout_p=dropout if training else 0.0)
    return run(_fn, qkv, name="flash_attn_qkvpacked"), None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale=None, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Varlen attention (reference :593).  TPU-native: segment-ids mask over
    the packed sequence (XLA-friendly static shapes)."""
    query, key, value = to_tensor_args(query, key, value)
    cu_q = cu_seqlens_q.value if isinstance(cu_seqlens_q, Tensor) \
        else jnp.asarray(cu_seqlens_q)
    cu_k = cu_seqlens_k.value if isinstance(cu_seqlens_k, Tensor) \
        else jnp.asarray(cu_seqlens_k)

    def _fn(q, k, v):
        # build segment ids from cumulative seqlens: token i belongs to the
        # segment whose [cu[j], cu[j+1]) contains i
        tq = q.shape[0]
        tk = k.shape[0]
        seg_q = jnp.searchsorted(cu_q[1:], jnp.arange(tq), side="right")
        seg_k = jnp.searchsorted(cu_k[1:], jnp.arange(tk), side="right")
        mask = seg_q[:, None] == seg_k[None, :]
        if causal:
            pos_q = jnp.arange(tq) - jnp.take(cu_q, seg_q)
            pos_k = jnp.arange(tk) - jnp.take(cu_k, seg_k)
            mask = mask & (pos_q[:, None] >= pos_k[None, :])
        s = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
        logits = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                            k.astype(jnp.float32)) * s
        logits = jnp.where(mask[None], logits, -1e30)
        w = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("hqk,khd->qhd", w, v.astype(jnp.float32)
                          ).astype(q.dtype)
    return run(_fn, query, key, value, name="flash_attn_unpadded"), None


class sdp_kernel:
    """Context manager to force a kernel choice (reference :37).  On TPU the
    choice is pallas-flash vs xla-reference; recorded for parity."""

    def __init__(self, enable_flash=True, enable_math=True,
                 enable_mem_efficient=True):
        self.enable_flash = enable_flash
        self.enable_math = enable_math

    def __enter__(self):
        from ... import ops
        self._prev = ops.get_attention_backend()
        ops.set_attention_backend(
            "pallas" if self.enable_flash else "xla")
        return self

    def __exit__(self, *exc):
        from ... import ops
        ops.set_attention_backend(self._prev)
        return False
