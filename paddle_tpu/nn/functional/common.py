"""Common functionals: linear, dropout, embedding, interpolate, etc.

Reference: `python/paddle/nn/functional/common.py` + `input.py`.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.dispatch import run, to_tensor_args
from ...framework.tensor import Tensor
from ...framework.random import next_key
from ...framework import dtypes


def linear(x, weight, bias=None, name=None, compute_dtype=None):
    """y = x @ W + b.  Weight layout [in, out] as in the reference
    (`python/paddle/nn/functional/common.py` linear → matmul kernel).
    Kept as one dot for MXU mapping; XLA fuses the bias add.
    compute_dtype: cast operands for the dot (fp32 master params, bf16
    MXU compute — see nn.Linear)."""
    from ...framework import dtypes as _dt
    cd = _dt.to_jax(compute_dtype) if compute_dtype is not None else None

    def _c(v):
        return v.astype(cd) if cd is not None and v.dtype != cd else v
    if bias is None:
        x, weight = to_tensor_args(x, weight)
        return run(lambda v, w: _c(v) @ _c(w), x, weight, name="linear")
    x, weight, bias = to_tensor_args(x, weight, bias)
    return run(lambda v, w, b: _c(v) @ _c(w) + _c(b), x, weight, bias,
               name="linear")


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    (x,) = to_tensor_args(x)
    if not training or p == 0.0:
        return run(lambda v: v, x, name="dropout_id")
    if p == 1.0:
        return run(lambda v: jnp.zeros_like(v), x, name="dropout")
    shape = list(x.value.shape)
    if axis is not None:
        axes = [axis] if isinstance(axis, int) else list(axis)
        shape = [s if i in axes else 1 for i, s in enumerate(shape)]
    keep = jax.random.bernoulli(next_key(), 1.0 - p, tuple(shape))

    def _fn(v):
        k = keep.astype(v.dtype)
        if mode == "upscale_in_train":
            return v * k / jnp.asarray(1.0 - p, v.dtype)
        return v * k  # downgrade_in_infer scales at infer time instead
    return run(_fn, x, name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    (x,) = to_tensor_args(x)
    if not training or p == 0.0:
        return run(lambda v: v, x)
    alpha = 1.6732632423543772848170429916717
    scale = 1.0507009873554804934193349852946
    alpha_p = -alpha * scale
    a = (1.0 / np.sqrt((alpha_p ** 2 * p + 1) * (1 - p)))
    b = -a * alpha_p * p
    keep = jax.random.bernoulli(next_key(), 1.0 - p, x.value.shape)

    def _fn(v):
        k = keep
        return (jnp.where(k, v, jnp.asarray(alpha_p, v.dtype))
                * jnp.asarray(a, v.dtype) + jnp.asarray(b, v.dtype))
    return run(_fn, x, name="alpha_dropout")


def embedding(x, weight, padding_idx=None, sparse=False, name=None,
              max_norm=None, norm_type=2.0, scale_grad_by_freq=False):
    """Reference: nn/functional/input.py embedding → phi embedding kernel.
    TPU-native: one-hot-free take(); padding_idx rows are masked so their
    grads vanish (XLA handles the scatter-add in the vjp)."""
    x, weight = to_tensor_args(x, weight)

    def _fn(w):
        tbl = w
        if padding_idx is not None:
            pid = padding_idx if padding_idx >= 0 else w.shape[0] + padding_idx
            tbl = w.at[pid].set(jnp.zeros_like(w[0]))
        return jnp.take(tbl, x.value.astype(jnp.int32), axis=0)
    return run(_fn, weight, name="embedding")


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    (label,) = to_tensor_args(label)
    k = label.shape[-1]

    def _fn(v):
        if prior_dist is not None:
            pd = prior_dist.value if isinstance(prior_dist, Tensor) \
                else jnp.asarray(prior_dist)
            return (1 - epsilon) * v + epsilon * pd
        return (1 - epsilon) * v + epsilon / k
    return run(_fn, label, name="label_smooth")


def one_hot(x, num_classes, name=None):
    (x,) = to_tensor_args(x)
    return Tensor(jax.nn.one_hot(x.value, num_classes, dtype=jnp.float32))


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    x1, x2 = to_tensor_args(x1, x2)

    def _fn(a, b):
        an = jnp.linalg.norm(a, axis=axis, keepdims=True)
        bn = jnp.linalg.norm(b, axis=axis, keepdims=True)
        denom = jnp.maximum(an * bn, eps)
        return jnp.sum(a * b, axis=axis) / jnp.squeeze(denom, axis)
    return run(_fn, x1, x2, name="cosine_similarity")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    (x,) = to_tensor_args(x)

    def _fn(v):
        if p == 2:
            n = jnp.sqrt(jnp.sum(v * v, axis=axis, keepdims=True))
        else:
            n = jnp.sum(jnp.abs(v) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return v / jnp.maximum(n, epsilon)
    return run(_fn, x, name="normalize")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from ...tensor.manipulation import pad as _pad
    return _pad(x, pad, mode, value, data_format)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0,
               data_format=data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    (x,) = to_tensor_args(x)

    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    dh, dw = _pair(dilations)
    pads = _pair(paddings)
    if len(pads) == 2:
        pt, pb, pl, pr = pads[0], pads[0], pads[1], pads[1]
    else:
        pt, pb, pl, pr = pads

    def _fn(v):
        n, c, h, w = v.shape
        v = jnp.pad(v, ((0, 0), (0, 0), (pt, pb), (pl, pr)))
        oh = (v.shape[2] - (dh * (kh - 1) + 1)) // sh + 1
        ow = (v.shape[3] - (dw * (kw - 1) + 1)) // sw + 1
        patches = jax.lax.conv_general_dilated_patches(
            v, (kh, kw), (sh, sw), "VALID", rhs_dilation=(dh, dw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return patches.reshape(n, c * kh * kw, oh * ow)
    return run(_fn, x, name="unfold")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    (x,) = to_tensor_args(x)

    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    oh, ow = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    dh, dw = _pair(dilations)
    pads = _pair(paddings)
    if len(pads) == 2:
        pt, pb, pl, pr = pads[0], pads[0], pads[1], pads[1]
    else:
        pt, pb, pl, pr = pads

    def _fn(v):
        n, ckk, L = v.shape
        c = ckk // (kh * kw)
        hh, ww = oh + pt + pb, ow + pl + pr
        lh = (hh - (dh * (kh - 1) + 1)) // sh + 1
        lw = (ww - (dw * (kw - 1) + 1)) // sw + 1
        out = jnp.zeros((n, c, hh, ww), v.dtype)
        v6 = v.reshape(n, c, kh, kw, lh, lw)
        for i in range(kh):
            for j in range(kw):
                hi = i * dh
                wi = j * dw
                out = out.at[:, :, hi:hi + sh * lh:sh,
                             wi:wi + sw * lw:sw].add(v6[:, :, i, j])
        return out[:, :, pt:pt + oh, pl:pl + ow]
    return run(_fn, x, name="fold")


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    (x,) = to_tensor_args(x)
    chan_last = data_format[-1] == "C"
    nd = x.ndim - 2
    spatial = x.shape[1:-1] if chan_last else x.shape[2:]
    if size is not None:
        if isinstance(size, Tensor):
            size = [int(s) for s in np.asarray(size.value)]
        out_size = [int(s.item()) if isinstance(s, Tensor) else int(s)
                    for s in (size if isinstance(size, (list, tuple))
                              else [size] * nd)]
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
            else [scale_factor] * nd
        out_size = [int(s * f) for s, f in zip(spatial, sf)]

    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear",
             "cubic": "cubic"}[mode]

    def _cubic_axis(v, ax, in_s, out_s):
        # reference bicubic kernel: cubic convolution with A=-0.75
        # (phi kernels/funcs/interpolate_function.h cubic_interp) —
        # jax.image's "cubic" is the Keys A=-0.5 kernel, which is NOT
        # what the reference (or torch/OpenCV) computes
        A = -0.75
        if align_corners:
            pos = jnp.arange(out_s) * ((in_s - 1) / max(out_s - 1, 1))
        else:
            pos = (jnp.arange(out_s) + 0.5) * (in_s / out_s) - 0.5
        lo = jnp.floor(pos).astype(jnp.int32)
        t = (pos - lo).astype(v.dtype)
        d = [1.0 + t, t, 1.0 - t, 2.0 - t]
        w = [A * d[0] ** 3 - 5 * A * d[0] ** 2 + 8 * A * d[0] - 4 * A,
             (A + 2) * d[1] ** 3 - (A + 3) * d[1] ** 2 + 1,
             (A + 2) * d[2] ** 3 - (A + 3) * d[2] ** 2 + 1,
             A * d[3] ** 3 - 5 * A * d[3] ** 2 + 8 * A * d[3] - 4 * A]
        shp = [1] * v.ndim
        shp[ax] = out_s
        out = 0.0
        for k in range(4):
            idx = jnp.clip(lo - 1 + k, 0, in_s - 1)
            out = out + jnp.take(v, idx, axis=ax) * w[k].reshape(shp)
        return out

    def _fn(v):
        if chan_last:
            shape = (v.shape[0],) + tuple(out_size) + (v.shape[-1],)
        else:
            shape = v.shape[:2] + tuple(out_size)
        if jmode == "nearest":
            return jax.image.resize(v, shape, method="nearest")
        sp_axes0 = list(range(1, 1 + nd)) if chan_last \
            else list(range(2, 2 + nd))
        if jmode == "cubic":
            out = v
            for ax_i, ax in enumerate(sp_axes0):
                out = _cubic_axis(out, ax, v.shape[ax], out_size[ax_i])
            return out
        # jax.image linear matches align_corners=False (half-pixel centers)
        if align_corners:
            # explicit gather for align_corners semantics
            idxs = []
            sp_axes = list(range(1, 1 + nd)) if chan_last \
                else list(range(2, 2 + nd))
            out = v
            for ax_i, ax in enumerate(sp_axes):
                in_s, out_s = v.shape[ax], out_size[ax_i]
                if out_s == 1:
                    pos = jnp.zeros((1,), v.dtype)
                else:
                    pos = jnp.arange(out_s) * ((in_s - 1) / (out_s - 1))
                lo = jnp.floor(pos).astype(jnp.int32)
                hi = jnp.minimum(lo + 1, in_s - 1)
                w = (pos - lo).astype(v.dtype)
                shp = [1] * out.ndim
                shp[ax] = out_s
                w = w.reshape(shp)
                out = (jnp.take(out, lo, axis=ax) * (1 - w)
                       + jnp.take(out, hi, axis=ax) * w)
            return out
        return jax.image.resize(v, shape, method=jmode)
    return run(_fn, x, name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW",
             name=None):
    return interpolate(x, size, scale_factor, mode, align_corners,
                       align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    (x,) = to_tensor_args(x)
    r = upscale_factor

    def _fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c // (r * r), r, r, h, w)
            v = v.transpose(0, 1, 4, 2, 5, 3)
            return v.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, r, r, c // (r * r))
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h * r, w * r, c // (r * r))
    return run(_fn, x, name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    (x,) = to_tensor_args(x)
    r = downscale_factor

    def _fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c, h // r, r, w // r, r)
            v = v.transpose(0, 1, 3, 5, 2, 4)
            return v.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = v.shape
        v = v.reshape(n, h // r, r, w // r, r, c)
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h // r, w // r, c * r * r)
    return run(_fn, x, name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    (x,) = to_tensor_args(x)

    def _fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, groups, c // groups, h, w)
            return v.transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, groups, c // groups)
        return v.transpose(0, 1, 2, 4, 3).reshape(n, h, w, c)
    return run(_fn, x, name="channel_shuffle")


def bilinear(x1, x2, weight, bias=None, name=None):
    ts = to_tensor_args(x1, x2, weight) + (to_tensor_args(bias)
                                           if bias is not None else ())

    def _fn(a, b, w, *bs):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bs:
            out = out + bs[0]
        return out
    return run(_fn, *ts, name="bilinear")
