"""Loss functionals.

Reference: `python/paddle/nn/functional/loss.py` (cross_entropy at :2458,
softmax_with_cross_entropy, mse_loss, ...).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.dispatch import run, to_tensor_args
from ...framework.tensor import Tensor


def _reduce(val, reduction):
    if reduction == "mean":
        return jnp.mean(val)
    if reduction == "sum":
        return jnp.sum(val)
    return val


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    """Reference: nn/functional/loss.py cross_entropy → phi
    softmax_with_cross_entropy kernel.  Computed as fused
    log_softmax + gather; fp32 accumulation for bf16 logits."""
    input, label = to_tensor_args(input, label)
    has_w = weight is not None
    if has_w:
        (weight,) = to_tensor_args(weight)

    lbl = label.value

    def _fn(logits, *w):
        x = logits.astype(jnp.float32) \
            if logits.dtype in (jnp.bfloat16, jnp.float16) else logits
        if use_softmax:
            logp = jax.nn.log_softmax(x, axis=axis)
        else:
            logp = jnp.log(jnp.clip(x, 1e-10))
        if soft_label or (lbl.ndim == logp.ndim and lbl.shape == logp.shape
                          and jnp.issubdtype(lbl.dtype, jnp.floating)):
            tgt = lbl.astype(logp.dtype)
            if label_smoothing > 0:
                k = logp.shape[axis]
                tgt = (1 - label_smoothing) * tgt + label_smoothing / k
            loss = -jnp.sum(tgt * logp, axis=axis)
            if w:
                # reference soft_label branch: per-sample weight is the
                # target-probability-weighted class weight (matmul(label,
                # weight)), multiplying the unweighted loss
                shape = [1] * logp.ndim
                shape[axis] = logp.shape[axis]
                wv = w[0].astype(logp.dtype).reshape(shape)
                wsample = jnp.sum(wv * tgt, axis=axis)
                loss = loss * wsample
                if reduction == "mean":
                    return jnp.sum(loss) / jnp.maximum(jnp.sum(wsample),
                                                       1e-12)
        else:
            idx = lbl.astype(jnp.int32)
            if idx.ndim == logp.ndim:
                idx = jnp.squeeze(idx, axis)
            safe_idx = jnp.where(idx == ignore_index, 0, idx)
            picked = jnp.take_along_axis(
                logp, jnp.expand_dims(safe_idx, axis), axis=axis)
            picked = jnp.squeeze(picked, axis)
            if label_smoothing > 0:
                k = logp.shape[axis]
                smooth = jnp.mean(logp, axis=axis)
                loss = -((1 - label_smoothing) * picked
                         + label_smoothing * smooth)
            else:
                loss = -picked
            mask = (idx != ignore_index)
            loss = jnp.where(mask, loss, 0.0)
            if w:
                wv = jnp.take(w[0].astype(logp.dtype), safe_idx)
                loss = loss * jnp.where(mask, wv, 0.0)
                if reduction == "mean":
                    denom = jnp.sum(jnp.where(mask, wv, 0.0))
                    return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
            if reduction == "mean":
                denom = jnp.sum(mask.astype(logp.dtype))
                return jnp.sum(loss) / jnp.maximum(denom, 1.0)
        return _reduce(loss, reduction)

    args = (input,) + ((weight,) if has_w else ())
    return run(_fn, *args, name="cross_entropy")


def fused_cross_entropy(input, label, weight=None, bias=None, *,
                        transpose_weight=False, ignore_index=None,
                        shift=False, chunk_rows=None, vocab_chunk=None,
                        axis_name=None, use_pallas=None, name=None):
    """Token-level LM cross entropy — the ONE implementation of the loss
    math llama, gpt and bert's MLM head used to hand-roll (PROFILE_r05:
    the fp32 logits/CE slice of the non-matmul MFU gap).

    Two modes:

      weight is None — `input` IS the logits [..., V].  Reference path:
        fp32 `logsumexp − picked logit`, masked mean over labels that
        are non-negative and != ignore_index.  Same values as the old
        per-model implementations (regression-pinned).

      weight given — `input` is the HIDDEN states [..., H] and the
        lm-head matmul folds INTO the loss: the chunked fused
        linear+cross-entropy (ops/pallas/fused_cross_entropy.py,
        Liger-style) computes per-row-chunk logits, loss and gradients
        in one sweep, so the [B, S, V] fp32 logits tensor — the single
        largest live buffer in the llama train step — never exists.
        `weight` is [H, V], or [V, H] with transpose_weight (the
        tied-embedding layout); optional `bias` [V].  axis_name: the
        vocab-sharded (ParallelCrossEntropy) mode for shard_map callers
        — per-shard max/denominator merged with one pmax + psum.

    shift=True drops the last input position and the first label column
    (next-token prediction) — kept here so both modes shift
    identically.  Models enable the fused mode via FLAGS_fused_ce (the
    training forward then returns hidden states).
    """
    (input,) = to_tensor_args(input)
    (label,) = to_tensor_args(label)
    lbl = label.value

    def _prep_labels(lg_or_h):
        tgt = lbl[:, 1:] if shift else lbl
        return lg_or_h[:, :-1] if shift else lg_or_h, tgt

    if weight is None:
        def _fn(lg):
            lgv, tgt = _prep_labels(lg)
            tgt = tgt.astype(jnp.int32)
            if ignore_index is not None:
                tgt = jnp.where(tgt == ignore_index, -1, tgt)
            safe = jnp.maximum(tgt, 0)
            # gather from the COMPUTE-dtype logits and upcast only the
            # picked column; the fp32 cast feeds just the logsumexp
            # reduction (XLA fuses it) — a full fp32 [tokens, vocab]
            # buffer never needs to materialize on this flags-off path
            picked = jnp.take_along_axis(lgv, safe[..., None],
                                         axis=-1)[..., 0] \
                .astype(jnp.float32)
            lse = jax.nn.logsumexp(lgv.astype(jnp.float32), axis=-1)
            mask = (tgt >= 0).astype(jnp.float32)
            return jnp.sum((lse - picked) * mask) \
                / jnp.maximum(jnp.sum(mask), 1.0)
        return run(_fn, input, name=name or "fused_cross_entropy")

    from ...ops.pallas.fused_cross_entropy import \
        fused_linear_cross_entropy
    (weight,) = to_tensor_args(weight)
    has_b = bias is not None
    if has_b:
        (bias,) = to_tensor_args(bias)

    def _fused(h, w, *b):
        hv, tgt = _prep_labels(h)
        # the matmul runs in the hidden states' compute dtype (what the
        # unfused lm-head did) with fp32 accumulation inside the kernel
        return fused_linear_cross_entropy(
            hv, w.astype(hv.dtype), tgt,
            bias=b[0].astype(jnp.float32) if b else None,
            transpose_weight=transpose_weight, ignore_index=ignore_index,
            chunk_rows=chunk_rows, vocab_chunk=vocab_chunk,
            axis_name=axis_name, use_pallas=use_pallas)

    args = (input, weight) + ((bias,) if has_b else ())
    return run(_fused, *args, name=name or "fused_linear_cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    from ...tensor.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        from .activation import softmax
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    input, label = to_tensor_args(input, label)
    lbl = label.value
    has_w = weight is not None
    if has_w:
        (weight,) = to_tensor_args(weight)

    def _fn(logp, *w):
        idx = lbl.astype(jnp.int32)
        safe_idx = jnp.where(idx == ignore_index, 0, idx)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe_idx, 1),
                                     axis=1)
        loss = -jnp.squeeze(picked, 1)
        mask = idx != ignore_index
        if w:
            wv = jnp.take(w[0], safe_idx)
            loss = loss * wv
            loss = jnp.where(mask, loss, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.sum(jnp.where(mask, wv, 0.0))
        loss = jnp.where(mask, loss, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(
                jnp.sum(mask.astype(logp.dtype)), 1.0)
        return _reduce(loss, reduction)
    args = (input,) + ((weight,) if has_w else ())
    return run(_fn, *args, name="nll_loss")


def mse_loss(input, label, reduction="mean", name=None):
    input, label = to_tensor_args(input, label)
    return run(lambda a, b: _reduce((a - b) ** 2, reduction), input, label,
               name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    input, label = to_tensor_args(input, label)
    return run(lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label,
               name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    input, label = to_tensor_args(input, label)

    def _fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        # paddle multiplies by delta
        return _reduce(loss * delta, reduction)
    return run(_fn, input, label, name="smooth_l1_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    args = to_tensor_args(*( (input, label) +
                             ((weight,) if weight is not None else ()) ))

    def _fn(p, t, *w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-7)
        loss = -(t * jnp.log(p) + (1 - t) * jnp.log(1 - p))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)
    return run(_fn, *args, name="bce")


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    extra = ()
    if weight is not None:
        extra += (weight,)
    if pos_weight is not None:
        extra += (pos_weight,)
    args = to_tensor_args(logit, label, *extra)

    def _fn(x, t, *rest):
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = rest[i]
            i += 1
        if pos_weight is not None:
            pw = rest[i]
        # numerically stable: max(x,0) - x*t + log(1+exp(-|x|))
        if pw is None:
            loss = jnp.maximum(x, 0) - x * t + jnp.log1p(jnp.exp(-jnp.abs(x)))
        else:
            logp = jax.nn.log_sigmoid(x)
            lognp = jax.nn.log_sigmoid(-x)
            loss = -(pw * t * logp + (1 - t) * lognp)
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    return run(_fn, *args, name="bce_with_logits")


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    input, label = to_tensor_args(input, label)

    def _fn(lp, t):
        if log_target:
            loss = jnp.exp(t) * (t - lp)
        else:
            loss = t * (jnp.log(jnp.clip(t, 1e-12)) - lp)
        if reduction == "batchmean":
            return jnp.sum(loss) / lp.shape[0]
        return _reduce(loss, reduction)
    return run(_fn, input, label, name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    input, other, label = to_tensor_args(input, other, label)
    return run(lambda a, b, y: _reduce(
        jnp.maximum(0.0, -y * (a - b) + margin), reduction), input, other,
        label, name="margin_ranking_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    input, label = to_tensor_args(input, label)
    return run(lambda x, y: _reduce(
        jnp.where(y == 1, x, jnp.maximum(0.0, margin - x)), reduction),
        input, label, name="hinge_embedding_loss")


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean",
                          name=None):
    input1, input2, label = to_tensor_args(input1, input2, label)

    def _fn(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / (
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1) + 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return run(_fn, input1, input2, label, name="cosine_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-06, swap=False, reduction="mean",
                        name=None):
    input, positive, negative = to_tensor_args(input, positive, negative)

    def _fn(a, pos, neg):
        def dist(u, v):
            return jnp.power(jnp.sum(jnp.power(jnp.abs(u - v) + epsilon, p),
                                     axis=-1), 1.0 / p)
        dp = dist(a, pos)
        dn = dist(a, neg)
        if swap:
            dn = jnp.minimum(dn, dist(pos, neg))
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)
    return run(_fn, input, positive, negative, name="triplet_margin_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    logit, label = to_tensor_args(logit, label)

    def _fn(x, t):
        p = jax.nn.sigmoid(x)
        ce = jnp.maximum(x, 0) - x * t + jnp.log1p(jnp.exp(-jnp.abs(x)))
        p_t = p * t + (1 - p) * (1 - t)
        a_t = alpha * t + (1 - alpha) * (1 - t)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if normalizer is not None:
            nv = normalizer.value if isinstance(normalizer, Tensor) \
                else normalizer
            loss = loss / nv
        return _reduce(loss, reduction)
    return run(_fn, logit, label, name="sigmoid_focal_loss")


def square_error_cost(input, label):
    input, label = to_tensor_args(input, label)
    return run(lambda a, b: (a - b) ** 2, input, label,
               name="square_error_cost")


def log_loss(input, label, epsilon=1e-4, name=None):
    input, label = to_tensor_args(input, label)
    return run(lambda p, t: -t * jnp.log(p + epsilon)
               - (1 - t) * jnp.log(1 - p + epsilon), input, label,
               name="log_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """Reference: nn/functional/loss.py:1908 (warpctc kernel) — takes
    UNSCALED logits [T, B, C] ("a native softmax activation is
    interlaced"), labels [B, L] padded, per-sample lengths.

    TPU-native: the forward-algorithm alpha recursion in log space as
    ONE lax.scan over time (static [B, 2L+1] state — no per-sample
    Python control flow), gradients via autodiff instead of the
    reference's hand-written warpctc backward.
    """
    log_probs, labels, input_lengths, label_lengths = to_tensor_args(
        log_probs, labels, input_lengths, label_lengths)

    def _fn(logits, lab, ilen, llen):
        t_max, b, _ = logits.shape
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        lab = lab.astype(jnp.int32)
        ilen = ilen.astype(jnp.int32)
        llen = llen.astype(jnp.int32)
        s = 2 * lab.shape[1] + 1
        ext = jnp.full((b, s), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab)
        neg = jnp.float32(-1e30)
        ext_lp = jnp.take_along_axis(
            lp, ext[None, :, :].repeat(t_max, 0), axis=-1)  # [T, B, S]
        alpha0 = jnp.full((b, s), neg)
        alpha0 = alpha0.at[:, 0].set(ext_lp[0, :, 0])
        if s > 1:
            alpha0 = alpha0.at[:, 1].set(
                jnp.where(llen > 0, ext_lp[0, :, 1], neg))
        can_skip = (ext != blank) & (ext != jnp.roll(ext, 2, axis=1))
        can_skip = can_skip.at[:, :2].set(False)

        def step(alpha, t):
            a1 = jnp.concatenate(
                [jnp.full((b, 1), neg), alpha[:, :-1]], 1)
            a2 = jnp.concatenate(
                [jnp.full((b, 2), neg), alpha[:, :-2]], 1)
            a2 = jnp.where(can_skip, a2, neg)
            new = jnp.logaddexp(jnp.logaddexp(alpha, a1), a2) \
                + ext_lp[t]
            # samples shorter than t keep their final alpha
            new = jnp.where((t < ilen)[:, None], new, alpha)
            return new, None
        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, t_max))
        rows = jnp.arange(b)
        end = 2 * llen
        last_blank = alpha[rows, end]
        last_label = jnp.where(llen > 0,
                               alpha[rows, jnp.maximum(end - 1, 0)],
                               neg)
        loss = -jnp.logaddexp(last_blank, last_label)
        if norm_by_times:
            loss = loss / jnp.maximum(ilen.astype(jnp.float32), 1.0)
        if reduction == "mean":
            # reference: divide by label_lengths, then batch mean
            return jnp.mean(loss / jnp.maximum(
                llen.astype(jnp.float32), 1.0))
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    return run(_fn, log_probs, labels, input_lengths, label_lengths,
               name="ctc_loss")
