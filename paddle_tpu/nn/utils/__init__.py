"""nn.utils — weight_norm / spectral_norm / parameters_to_vector.

Reference: `python/paddle/nn/utils/`.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...framework.tensor import Tensor, Parameter
from ... import tensor as pten

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters"]


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize weight = g * v/||v|| (reference: utils/weight_norm_hook.py)."""
    w = getattr(layer, name)
    if dim is None:
        norm = jnp.sqrt(jnp.sum(jnp.square(w.value)))
        g0 = norm.reshape(())
    else:
        axes = tuple(i for i in range(w.ndim) if i != dim)
        g0 = jnp.sqrt(jnp.sum(jnp.square(w.value), axis=axes))
    v = Parameter(w.value)
    g = Parameter(g0)
    layer.add_parameter(name + "_v", v)
    layer.add_parameter(name + "_g", g)
    del layer._parameters[name]

    def _compute():
        vv = layer._parameters[name + "_v"]
        gg = layer._parameters[name + "_g"]
        if dim is None:
            nrm = pten.norm(vv)
            return pten.multiply(pten.divide(vv, nrm), gg)
        axes = [i for i in range(vv.ndim) if i != dim]
        nrm = pten.sqrt(pten.sum(pten.multiply(vv, vv), axis=axes,
                                 keepdim=True))
        shape = [1] * vv.ndim
        shape[dim] = -1
        return pten.multiply(pten.divide(vv, nrm), pten.reshape(gg, shape))

    def pre_hook(l, inputs):
        object.__setattr__(l, name, _compute())
        return None
    handle = layer.register_forward_pre_hook(pre_hook)
    layer.__dict__["_weight_norm_handle_" + name] = handle
    object.__setattr__(layer, name, _compute())
    return layer


def remove_weight_norm(layer, name="weight"):
    handle = layer.__dict__.pop("_weight_norm_handle_" + name, None)
    if handle is not None:
        handle.remove()
    v = layer._parameters.pop(name + "_v")
    g = layer._parameters.pop(name + "_g")
    if g.ndim == 0:
        w = (v.value / jnp.sqrt(jnp.sum(jnp.square(v.value)))) * g.value
    else:
        dim = next(i for i, s in enumerate(v.shape)
                   if s == g.shape[0]) if g.ndim else 0
        axes = tuple(i for i in range(v.ndim) if i != dim)
        nrm = jnp.sqrt(jnp.sum(jnp.square(v.value), axis=axes,
                               keepdims=True))
        shape = [1] * v.ndim
        shape[dim] = -1
        w = v.value / nrm * g.value.reshape(shape)
    layer.__dict__.pop(name, None)
    layer.add_parameter(name, Parameter(w))
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    from ..layer.norm import SpectralNorm
    w = getattr(layer, name)
    if dim is None:
        dim = 0
    sn = SpectralNorm(w.shape, dim=dim, power_iters=n_power_iterations,
                      epsilon=eps)
    orig = Parameter(w.value)
    layer.add_parameter(name + "_orig", orig)
    del layer._parameters[name]
    layer.add_sublayer(name + "_spectral_norm", sn)

    def pre_hook(l, inputs):
        object.__setattr__(l, name,
                           sn(l._parameters[name + "_orig"]))
        return None
    layer.register_forward_pre_hook(pre_hook)
    object.__setattr__(layer, name, sn(orig))
    return layer


def parameters_to_vector(parameters, name=None):
    return pten.concat([pten.reshape(p, [-1]) for p in parameters])


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p.size
        p._value = vec.value[offset:offset + n].reshape(p.value.shape)
        offset += n
