"""paddle_tpu.nn — reference: python/paddle/nn/ (47.5K LoC)."""
from .layer.layers import Layer  # noqa: F401
from .layer.common import *  # noqa: F401,F403
from .layer.conv import *  # noqa: F401,F403
from .layer.norm import *  # noqa: F401,F403
from .layer.activation import *  # noqa: F401,F403
from .layer.pooling import *  # noqa: F401,F403
from .layer.loss import *  # noqa: F401,F403
from .layer.container import *  # noqa: F401,F403
from .layer.transformer import *  # noqa: F401,F403
from .layer.rnn import *  # noqa: F401,F403
from .clip import (ClipGradByValue, ClipGradByNorm,  # noqa: F401
                   ClipGradByGlobalNorm)
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .utils import weight_norm, remove_weight_norm, spectral_norm  # noqa: F401


def set_compute_dtype(layer, dtype):
    """Flax-style TPU mixed precision: parameters stay fp32 (the param
    IS the master weight) while supporting layers (Linear / LayerNorm /
    Embedding) compute in `dtype` — casts fuse into the matmuls, so the
    MXU runs at full bf16 rate with no separate master copy.  Returns
    the number of layers switched.  Contrast amp.decorate O2, which
    casts the PARAMS and keeps fp32 masters in the optimizer."""
    from ..framework import dtypes as _dt
    jd = _dt.to_jax(dtype)
    n = 0
    for sub in layer.sublayers(include_self=True):
        if hasattr(type(sub), "_compute_dtype"):
            sub._compute_dtype = jd
            n += 1
    return n
