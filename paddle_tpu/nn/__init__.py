"""paddle_tpu.nn — reference: python/paddle/nn/ (47.5K LoC)."""
from .layer.layers import Layer  # noqa: F401
from .layer.common import *  # noqa: F401,F403
from .layer.conv import *  # noqa: F401,F403
from .layer.norm import *  # noqa: F401,F403
from .layer.activation import *  # noqa: F401,F403
from .layer.pooling import *  # noqa: F401,F403
from .layer.loss import *  # noqa: F401,F403
from .layer.container import *  # noqa: F401,F403
from .layer.transformer import *  # noqa: F401,F403
from .layer.rnn import *  # noqa: F401,F403
from .clip import (ClipGradByValue, ClipGradByNorm,  # noqa: F401
                   ClipGradByGlobalNorm)
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .utils import weight_norm, remove_weight_norm, spectral_norm  # noqa: F401
