"""nn.Layer base class.

Reference: `python/paddle/nn/layer/layers.py:354` (class Layer — parameters,
buffers, sublayers, hooks, state_dict, train/eval).

TPU-native notes: parameters are Tensors over jax.Arrays, and Layer composes
with jax transforms through `paddle_tpu.jit.functional_call` (parameters are
swapped for traced values during jit).  No static-graph interplay is needed —
tracing IS the static mode.
"""
from __future__ import annotations

import collections
from typing import Callable, Iterator, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from ...framework.tensor import Tensor, Parameter
from ...framework import dtypes
from ...framework.param_attr import ParamAttr

__all__ = ["Layer"]

_layer_name_counters: dict = collections.defaultdict(int)


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names_set = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0
        cls_name = name_scope or self.__class__.__name__.lower()
        _layer_name_counters[cls_name] += 1
        self._full_name = f"{cls_name}_{_layer_name_counters[cls_name] - 1}"

    # -- naming ------------------------------------------------------------
    def full_name(self):
        return self._full_name

    # -- parameter creation ------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        """Reference: layers.py create_parameter → LayerHelper.
        attr=False means 'no parameter' (e.g. bias_attr=False)."""
        if attr is False:
            return None
        from .. import initializer as I
        dtype = dtype or self._dtype or "float32"
        attr = ParamAttr._to_attr(attr)
        init = None
        if attr is not None and attr.initializer is not None:
            init = attr.initializer
        elif default_initializer is not None:
            init = default_initializer
        elif is_bias:
            init = I._global_bias_init or I.Constant(0.0)
        else:
            init = I._global_weight_init or I.XavierNormal()
        value = init(tuple(int(s) for s in shape), dtype)
        p = Parameter(value, trainable=(attr.trainable if attr else True),
                      name=(attr.name if attr else None))
        if attr is not None:
            p.optimize_attr["learning_rate"] = attr.learning_rate
            p.regularizer = attr.regularizer
            p.need_clip = attr.need_clip
        return p

    def create_variable(self, name=None, persistable=False, dtype=None):
        jd = dtypes.to_jax(dtype or "float32")
        t = Tensor(jnp.zeros([], jd))
        t.persistable = persistable
        return t

    def create_tensor(self, name=None, persistable=False, dtype=None):
        return self.create_variable(name, persistable, dtype)

    # -- attribute routing -------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError(
                    "call Layer.__init__ before assigning parameters")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
            if value.name is None:
                # auto-name like the reference ("linear_0.weight"): unique
                # via the layer's full_name counter, and carries the class
                # name for name-based decay policies. First owner wins
                # (tied params keep their original name).
                value.name = f"{self._full_name}.{name}"
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError(
                    "call Layer.__init__ before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Tensor) and buffers is not None \
                and name in buffers:
            buffers[name] = value
        else:
            if params is not None:
                params.pop(name, None)
            if layers is not None:
                layers.pop(name, None)
            if buffers is not None:
                buffers.pop(name, None)
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        extra = []
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d:
                extra += list(d)
        return list(super().__dir__()) + extra

    # -- registration APIs -------------------------------------------------
    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        if parameter is not None and parameter.name is None:
            parameter.name = f"{self._full_name}.{name}"
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names_set.add(name)
        else:
            self._non_persistable_buffer_names_set.discard(name)
        return tensor

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    # -- traversal ---------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in
                self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer_prefix, layer in self._walk(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                full = f"{layer_prefix}.{pname}" if layer_prefix else pname
                yield full, p

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer_prefix, layer in self._walk(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                full = f"{layer_prefix}.{bname}" if layer_prefix else bname
                yield full, b

    def _walk(self, prefix="", include_sublayers=True):
        yield self._full_name, prefix, self
        if include_sublayers:
            for name, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sub_prefix = f"{prefix}.{name}" if prefix else name
                yield from sub._walk(sub_prefix, True)

    def children(self):
        for _, l in self.named_children():
            yield l

    def named_children(self):
        for name, sub in self._sub_layers.items():
            if sub is not None:
                yield name, sub

    def sublayers(self, include_self=False):
        out = []
        for _, _, layer in self._walk("", True):
            out.append(layer)
        return out if include_self else out[1:]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        first = True
        for _, p, layer in self._walk(prefix, True):
            if first and not include_self:
                first = False
                continue
            first = False
            yield p, layer

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    # -- mode --------------------------------------------------------------
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    # -- hooks -------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- call --------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            res = hook(self, inputs)
            if res is not None:
                inputs = res if isinstance(res, tuple) else (res,)
        out = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, out)
            if res is not None:
                out = res
        return out

    # -- state dict --------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None \
            else collections.OrderedDict()
        for name, p in self.named_parameters(
                prefix=structured_name_prefix,
                include_sublayers=include_sublayers):
            dest[name] = p
        for _, lp, layer in self._walk(structured_name_prefix,
                                       include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names_set:
                    continue
                full = f"{lp}.{bname}" if lp else bname
                dest[full] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        """Reference: layers.py set_state_dict — match by structured name."""
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k in own:
                tgt = own[k]
                val = v.value if isinstance(v, Tensor) else jnp.asarray(
                    np.asarray(v))
                if tuple(val.shape) != tuple(tgt.value.shape):
                    raise ValueError(
                        f"shape mismatch for {k}: loading {val.shape} into "
                        f"{tuple(tgt.value.shape)}")
                tgt._value = val.astype(tgt.value.dtype)
            else:
                unexpected.append(k)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    # -- dtype/device movement ---------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        for p in self.parameters():
            if dtype is not None and p.dtype.is_floating_point():
                p._value = p._value.astype(dtypes.to_jax(dtype))
            if device is not None:
                import jax as _jax
                from ...framework.device import _resolve_device
                p._value = _jax.device_put(p._value, _resolve_device(device))
        for b in self.buffers():
            if dtype is not None and b.dtype.is_floating_point():
                b._value = b._value.astype(dtypes.to_jax(dtype))
        if dtype is not None:
            self._dtype = dtypes.convert_np_dtype_to_dtype_(dtype).name
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = [sub_repr[0]] + ["  " + l for l in sub_repr[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub_repr))
        main = f"{self.__class__.__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"
