"""Activation layers.  Reference: `python/paddle/nn/layer/activation.py`."""
from __future__ import annotations

from .layers import Layer
from .. import functional as F
from .. import initializer as I

__all__ = ["ReLU", "ReLU6", "GELU", "Sigmoid", "Tanh", "Softmax",
           "LogSoftmax", "LeakyReLU", "PReLU", "ELU", "CELU", "SELU", "SiLU",
           "Swish", "Mish", "Hardtanh", "Hardsigmoid", "Hardswish",
           "Hardshrink", "Softshrink", "Softplus", "Softsign", "Tanhshrink",
           "ThresholdedReLU", "LogSigmoid", "Maxout", "GLU", "RReLU"]


def _mk(fname, cname, **fixed):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._kwargs = {**fixed}
            sig_names = _SIGS.get(cname, [])
            for n, a in zip(sig_names, args):
                self._kwargs[n] = a
            for k, v in kwargs.items():
                if k != "name":
                    self._kwargs[k] = v

        def forward(self, x):
            return getattr(F, fname)(x, **self._kwargs)
    _Act.__name__ = cname
    _Act.__qualname__ = cname
    return _Act


_SIGS = {
    "Softmax": ["axis"], "LogSoftmax": ["axis"],
    "LeakyReLU": ["negative_slope"], "ELU": ["alpha"], "CELU": ["alpha"],
    "Hardtanh": ["min", "max"], "Hardshrink": ["threshold"],
    "Softshrink": ["threshold"], "Softplus": ["beta", "threshold"],
    "ThresholdedReLU": ["threshold", "value"], "Maxout": ["groups", "axis"],
    "GLU": ["axis"], "GELU": ["approximate"], "RReLU": ["lower", "upper"],
}

ReLU = _mk("relu", "ReLU")
ReLU6 = _mk("relu6", "ReLU6")
GELU = _mk("gelu", "GELU")
Sigmoid = _mk("sigmoid", "Sigmoid")
Tanh = _mk("tanh", "Tanh")
Softmax = _mk("softmax", "Softmax")
LogSoftmax = _mk("log_softmax", "LogSoftmax")
LeakyReLU = _mk("leaky_relu", "LeakyReLU")
ELU = _mk("elu", "ELU")
CELU = _mk("celu", "CELU")
SELU = _mk("selu", "SELU")
SiLU = _mk("silu", "SiLU")
Swish = _mk("swish", "Swish")
Mish = _mk("mish", "Mish")
Hardtanh = _mk("hardtanh", "Hardtanh")
Hardsigmoid = _mk("hardsigmoid", "Hardsigmoid")
Hardswish = _mk("hardswish", "Hardswish")
Hardshrink = _mk("hardshrink", "Hardshrink")
Softshrink = _mk("softshrink", "Softshrink")
Softplus = _mk("softplus", "Softplus")
Softsign = _mk("softsign", "Softsign")
Tanhshrink = _mk("tanhshrink", "Tanhshrink")
ThresholdedReLU = _mk("thresholded_relu", "ThresholdedReLU")
LogSigmoid = _mk("log_sigmoid", "LogSigmoid")
Maxout = _mk("maxout", "Maxout")
GLU = _mk("glu", "GLU")


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            shape=[num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)
