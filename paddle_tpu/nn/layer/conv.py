"""Conv layers.  Reference: `python/paddle/nn/layer/conv.py`."""
from __future__ import annotations

import numpy as np

from .layers import Layer
from .. import functional as F
from .. import initializer as I

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose",
           "Conv2DTranspose", "Conv3DTranspose"]


def _tuplize(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _ConvNd(Layer):
    # flax-idiom mixed precision (see nn.set_compute_dtype): fp32
    # params are the masters; the conv runs in the compute dtype with
    # the casts fused into the convolution by XLA
    _compute_dtype = None

    def __init__(self, in_channels, out_channels, kernel_size, nd,
                 stride=1, padding=0, dilation=1, groups=1,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format="NCHW", transpose=False, output_padding=0):
        super().__init__()
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _tuplize(kernel_size, nd)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._padding_mode = padding_mode
        self._data_format = data_format
        self._nd = nd
        self._transpose = transpose
        self._output_padding = output_padding

        if transpose:
            w_shape = [in_channels, out_channels // groups,
                       *self._kernel_size]
        else:
            w_shape = [out_channels, in_channels // groups,
                       *self._kernel_size]
        fan_in = (in_channels // groups) * int(np.prod(self._kernel_size))
        # reference default: Normal(0, sqrt(2.6/fan_in))-style Xavier; we use
        # KaimingUniform like paddle's conv default (nn/layer/conv.py)
        self.weight = self.create_parameter(
            shape=w_shape, attr=weight_attr, dtype=self._dtype,
            default_initializer=I.KaimingUniform(fan_in=fan_in))
        bound = 1.0 / np.sqrt(fan_in)
        self.bias = self.create_parameter(
            shape=[out_channels], attr=bias_attr, is_bias=True,
            dtype=self._dtype,
            default_initializer=I.Uniform(-bound, bound)
            if bias_attr is None else None)

    def forward(self, x):
        fns = {(1, False): F.conv1d, (2, False): F.conv2d,
               (3, False): F.conv3d, (1, True): F.conv1d_transpose,
               (2, True): F.conv2d_transpose, (3, True): F.conv3d_transpose}
        fn = fns[(self._nd, self._transpose)]
        weight, bias = self.weight, self.bias
        if self._compute_dtype is not None:
            # same arg lists as below, just with casted operands — the
            # casts fuse into the convolution under XLA
            cd = self._compute_dtype
            x = x.astype(cd) if hasattr(x, "astype") else x
            weight = weight.astype(cd)
            bias = bias.astype(cd) if bias is not None else None
        if self._transpose:
            return fn(x, weight, bias, self._stride, self._padding,
                      self._output_padding, self._groups, self._dilation,
                      None, self._data_format)
        return fn(x, weight, bias, self._stride, self._padding,
                  self._dilation, self._groups, self._data_format)

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={self._kernel_size}, stride={self._stride}, "
                f"padding={self._padding}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)
