"""Recurrent layers.

Reference: `python/paddle/nn/layer/rnn.py` (SimpleRNN/LSTM/GRU + cells).
TPU-native: the time loop is `jax.lax.scan` — single compiled kernel, no
per-step dispatch (the reference uses cuDNN fused RNNs; scan + XLA fusion is
the TPU analog).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .layers import Layer
from .. import initializer as I
from ... import tensor as pten
from ...framework.tensor import Tensor
from ...framework.dispatch import run, to_tensor_args

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "SimpleRNN",
           "LSTM", "GRU"]


class RNNCellBase(Layer):
    def _make_params(self, input_size, hidden_size, gates):
        k = 1.0 / np.sqrt(hidden_size)
        init = I.Uniform(-k, k)
        self.weight_ih = self.create_parameter(
            [gates * hidden_size, input_size], default_initializer=init)
        self.weight_hh = self.create_parameter(
            [gates * hidden_size, hidden_size], default_initializer=init)
        self.bias_ih = self.create_parameter(
            [gates * hidden_size], is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter(
            [gates * hidden_size], is_bias=True, default_initializer=init)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.activation = activation
        self._make_params(input_size, hidden_size, 1)

    @staticmethod
    def _step(x, h, wih, whh, bih, bhh, activation="tanh"):
        act = jnp.tanh if activation == "tanh" else jax.nn.relu
        return act(x @ wih.T + bih + h @ whh.T + bhh)

    def forward(self, inputs, states=None):
        (inputs,) = to_tensor_args(inputs)
        if states is None:
            states = pten.zeros([inputs.shape[0], self.hidden_size])
        out = run(lambda x, h, a, b, c, d: self._step(
            x, h, a, b, c, d, self.activation), inputs, states,
            self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)
        return out, out


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self._make_params(input_size, hidden_size, 4)

    @staticmethod
    def _step(x, h, c, wih, whh, bih, bhh):
        z = x @ wih.T + bih + h @ whh.T + bhh
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, c_new

    def forward(self, inputs, states=None):
        (inputs,) = to_tensor_args(inputs)
        if states is None:
            h = pten.zeros([inputs.shape[0], self.hidden_size])
            c = pten.zeros([inputs.shape[0], self.hidden_size])
        else:
            h, c = states
        h_new, c_new = run(self._step, inputs, h, c, self.weight_ih,
                           self.weight_hh, self.bias_ih, self.bias_hh)
        return h_new, (h_new, c_new)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self._make_params(input_size, hidden_size, 3)

    @staticmethod
    def _step(x, h, wih, whh, bih, bhh):
        zi = x @ wih.T + bih
        zh = h @ whh.T + bhh
        ri, ui, ci = jnp.split(zi, 3, axis=-1)
        rh, uh, ch = jnp.split(zh, 3, axis=-1)
        r = jax.nn.sigmoid(ri + rh)
        u = jax.nn.sigmoid(ui + uh)
        c = jnp.tanh(ci + r * ch)
        return (1 - u) * c + u * h

    def forward(self, inputs, states=None):
        (inputs,) = to_tensor_args(inputs)
        if states is None:
            states = pten.zeros([inputs.shape[0], self.hidden_size])
        h_new = run(self._step, inputs, states, self.weight_ih,
                    self.weight_hh, self.bias_ih, self.bias_hh)
        return h_new, h_new


class RNN(Layer):
    """Wrap a cell into a scan over time (reference: nn/layer/rnn.py RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        (inputs,) = to_tensor_args(inputs)
        time_axis = 0 if self.time_major else 1
        steps = inputs.shape[time_axis]
        outputs = []
        states = initial_states
        rng = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        for t in rng:
            xt = inputs[t] if self.time_major else inputs[:, t]
            out, states = self.cell(xt, states)
            outputs.append(out)
        if self.is_reverse:
            outputs = outputs[::-1]
        out = pten.stack(outputs, axis=time_axis)
        return out, states


class _MultiLayerRNN(Layer):
    CELL = None
    STATE_N = 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.bidirect = direction in ("bidirect", "bidirectional")
        self.dropout = dropout
        from .container import LayerList
        cells_fw, cells_bw = [], []
        for l in range(num_layers):
            isz = input_size if l == 0 else hidden_size * (
                2 if self.bidirect else 1)
            cells_fw.append(self._make_cell(isz, hidden_size, activation))
            if self.bidirect:
                cells_bw.append(self._make_cell(isz, hidden_size, activation))
        self.cells_fw = LayerList(cells_fw)
        self.cells_bw = LayerList(cells_bw) if self.bidirect else None

    def _make_cell(self, isz, hsz, activation):
        if type(self).CELL is SimpleRNNCell:
            return SimpleRNNCell(isz, hsz, activation)
        return type(self).CELL(isz, hsz)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        out = inputs
        final_h, final_c = [], []
        for l in range(self.num_layers):
            fw = RNN(self.cells_fw[l], time_major=self.time_major)
            o_fw, s_fw = fw(out)
            if self.bidirect:
                bw = RNN(self.cells_bw[l], is_reverse=True,
                         time_major=self.time_major)
                o_bw, s_bw = bw(out)
                out = pten.concat([o_fw, o_bw], axis=-1)
                ss = [s_fw, s_bw]
            else:
                out = o_fw
                ss = [s_fw]
            for s in ss:
                if isinstance(s, tuple):
                    final_h.append(s[0])
                    final_c.append(s[1])
                else:
                    final_h.append(s)
        h = pten.stack(final_h, axis=0)
        if final_c:
            return out, (h, pten.stack(final_c, axis=0))
        return out, h


class SimpleRNN(_MultiLayerRNN):
    CELL = SimpleRNNCell


class LSTM(_MultiLayerRNN):
    CELL = LSTMCell


class GRU(_MultiLayerRNN):
    CELL = GRUCell
