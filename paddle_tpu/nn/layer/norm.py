"""Normalization layers.

Reference: `python/paddle/nn/layer/norm.py` (LayerNorm at :575, BatchNorm
family, GroupNorm, InstanceNorm, SyncBatchNorm).

TPU note: SyncBatchNorm's cross-replica stats come from a psum inside the
jitted step when running under a data-parallel mesh (XLA inserts the
collective); in eager single-process mode it equals BatchNorm.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .layers import Layer
from .. import functional as F
from .. import initializer as I
from ...framework.tensor import Tensor

__all__ = ["LayerNorm", "BatchNorm", "BatchNorm1D", "BatchNorm2D",
           "BatchNorm3D", "SyncBatchNorm", "GroupNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "LocalResponseNorm",
           "SpectralNorm", "RMSNorm"]


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                shape=self._normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                shape=self._normalized_shape, attr=bias_attr, is_bias=True)

    _compute_dtype = None

    def forward(self, input):
        out = F.layer_norm(input, self._normalized_shape, self.weight,
                           self.bias, self._epsilon)
        if self._compute_dtype is not None:
            # normalization math stays fp32 (fp32 params); only the
            # RESULT re-enters the low-precision residual stream
            out = out.astype(self._compute_dtype)
        return out

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """Reference: incubate fused_rms_norm — promoted to a first-class layer
    since it is the LLM hot path (Pallas kernel on TPU)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None,
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            shape=[hidden_size], attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                shape=[num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features],
                                                       jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features],
                                                          jnp.float32)))

    def forward(self, input):
        return F.batch_norm(input, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}"


class BatchNorm(_BatchNormBase):
    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-05,
                 param_attr=None, bias_attr=None, data_layout="NCHW",
                 use_global_stats=None, **kw):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout, use_global_stats)
        self._act = act

    def forward(self, input):
        out = super().forward(input)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm.  Under a dp mesh inside jit, XLA turns the
    mean/var reductions into psums automatically when inputs are sharded on
    batch (GSPMD); eager single-host == BatchNorm.  Reference:
    nn/layer/norm.py SyncBatchNorm (NCCL allreduce of stats)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon, None, None,
                                layer._data_format)
            if layer.weight is not None:
                out.weight = layer.weight
            if layer.bias is not None:
                out.bias = layer.bias
            out._mean = layer._mean
            out._variance = layer._variance
        for name, sub in layer._sub_layers.items():
            out._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            shape=[num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[num_channels], attr=bias_attr, is_bias=True)

    _compute_dtype = None

    def forward(self, input):
        out = F.group_norm(input, self._num_groups, self._epsilon,
                           self.weight, self.bias, self._data_format)
        if self._compute_dtype is not None:
            out = out.astype(self._compute_dtype)
        return out


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self.scale = None
        else:
            self.scale = self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                shape=[num_features], attr=bias_attr, is_bias=True)

    def forward(self, input):
        return F.instance_norm(input, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, input):
        return F.local_response_norm(input, *self.args)


class SpectralNorm(Layer):
    """Power-iteration spectral norm (reference: nn/layer/norm.py
    SpectralNorm)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 name=None, dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter(
            shape=[h], default_initializer=I.Normal(0, 1))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            shape=[w], default_initializer=I.Normal(0, 1))
        self.weight_v.stop_gradient = True

    def forward(self, x):
        from ... import tensor as pten
        w = x
        if self._dim != 0:
            perm = [self._dim] + [i for i in range(w.ndim)
                                  if i != self._dim]
            w = pten.transpose(w, perm)
        h = w.shape[0]
        wm = pten.reshape(w, [h, -1])
        u, v = self.weight_u.value, self.weight_v.value
        for _ in range(self._power_iters):
            v = wm.value.T @ u
            v = v / (jnp.linalg.norm(v) + self._epsilon)
            u = wm.value @ v
            u = u / (jnp.linalg.norm(u) + self._epsilon)
        self.weight_u._value = u
        self.weight_v._value = v
        sigma = u @ wm.value @ v
        out = pten.divide(x, Tensor(sigma))
        return out
