"""Gradient clipping.

Reference: `python/paddle/nn/clip.py` (ClipGradByValue, ClipGradByNorm,
ClipGradByGlobalNorm — applied by Optimizer before update).

TPU-native: global-norm clip computes one fused norm over all grads in a
single jitted reduction (the reference accumulates per-param squared norms
then allreduces; under a mesh XLA inserts the psum automatically).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import Tensor

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm",
           "clip_grad_norm_", "clip_grad_value_"]


class ClipGradBase:
    def __call__(self, params_grads):
        return self._dygraph_clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = min if min is not None else -max

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g.value, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            n = jnp.sqrt(jnp.sum(jnp.square(g.value.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(n, 1e-12), 1.0)
            out.append((p, Tensor((g.value.astype(jnp.float32) * scale
                                   ).astype(g.value.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = clip_norm
        self.group_name = group_name

    def _dygraph_clip(self, params_grads):
        sq = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                continue
            sq.append(jnp.sum(jnp.square(g.value.astype(jnp.float32))))
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor((g.value.astype(jnp.float32) * scale
                                   ).astype(g.value.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.asarray(0.0))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g.value))
                                   for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g.value.astype(jnp.float32)) ** norm_type)
             for g in grads])) ** (1.0 / norm_type)
    clip_coef = jnp.clip(max_norm / (total + 1e-6), a_max=1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._value = (p.grad.value.astype(jnp.float32) * clip_coef
                             ).astype(p.grad.value.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._value = jnp.clip(p.grad.value, -clip_value, clip_value)
