"""paddle.geometric — graph message passing + segment ops.

Reference: `python/paddle/geometric/` (message_passing/send_recv.py
send_u_recv/send_ue_recv, math.py segment_sum/mean/max/min,
reindex_graph, sample_neighbors).  TPU-native: every gather/scatter is
jax.ops.segment_* (static num_segments → XLA scatter on-device); no
dynamic shapes, so everything jits.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.dispatch import run, to_tensor_args
from ..framework.tensor import Tensor

__all__ = ["send_u_recv", "send_ue_recv", "segment_sum", "segment_mean",
           "segment_max", "segment_min", "reindex_graph"]

_REDUCERS = {
    "sum": jax.ops.segment_sum,
    "mean": None,  # composed from sum / count
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
}


def _idx(x):
    v = x._value if isinstance(x, Tensor) else jnp.asarray(np.asarray(x))
    return v.astype(jnp.int32)


def _segment(vals, seg, n, pool):
    if pool == "mean":
        s = jax.ops.segment_sum(vals, seg, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones((vals.shape[0],), vals.dtype),
                                  seg, num_segments=n)
        return s / jnp.maximum(cnt, 1)[(...,) + (None,) * (s.ndim - 1)]
    out = _REDUCERS[pool](vals, seg, num_segments=n)
    if pool in ("max", "min"):
        # empty segments come back +-inf; the reference zeroes them
        out = jnp.where(jnp.isfinite(out), out, 0)
    return out


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src], scatter-reduce onto dst (reference:
    send_recv.py:33 graph_send_recv)."""
    (x,) = to_tensor_args(x)
    src = _idx(src_index)
    dst = _idx(dst_index)
    n = int(out_size) if out_size is not None else x.value.shape[0]
    return run(lambda v: _segment(v[src], dst, n, reduce_op), x,
               name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Edge-weighted variant (reference: send_recv.py send_ue_recv):
    message = x[src] (op) y_edge, then scatter-reduce to dst."""
    (x,) = to_tensor_args(x)
    yv = y if isinstance(y, Tensor) else Tensor(jnp.asarray(np.asarray(y)))
    src = _idx(src_index)
    dst = _idx(dst_index)
    n = int(out_size) if out_size is not None else x.value.shape[0]
    ops = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
           "div": jnp.divide}
    mop = ops[message_op]

    def _fn(v, e):
        msg = mop(v[src], e if e.ndim == v.ndim else e[:, None]
                  if v.ndim > 1 else e)
        return _segment(msg, dst, n, reduce_op)
    return run(_fn, x, yv, name="send_ue_recv")


def segment_sum(data, segment_ids, name=None):
    (data,) = to_tensor_args(data)
    seg = _idx(segment_ids)
    n = int(np.asarray(jax.device_get(seg)).max()) + 1 if seg.size else 0
    return run(lambda v: jax.ops.segment_sum(v, seg, num_segments=n),
               data, name="segment_sum")


def segment_mean(data, segment_ids, name=None):
    (data,) = to_tensor_args(data)
    seg = _idx(segment_ids)
    n = int(np.asarray(jax.device_get(seg)).max()) + 1 if seg.size else 0
    return run(lambda v: _segment(v, seg, n, "mean"), data,
               name="segment_mean")


def segment_max(data, segment_ids, name=None):
    (data,) = to_tensor_args(data)
    seg = _idx(segment_ids)
    n = int(np.asarray(jax.device_get(seg)).max()) + 1 if seg.size else 0
    return run(lambda v: _segment(v, seg, n, "max"), data,
               name="segment_max")


def segment_min(data, segment_ids, name=None):
    (data,) = to_tensor_args(data)
    seg = _idx(segment_ids)
    n = int(np.asarray(jax.device_get(seg)).max()) + 1 if seg.size else 0
    return run(lambda v: _segment(v, seg, n, "min"), data,
               name="segment_min")


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact global ids to local ids (reference: reindex_graph).
    Host-side (python) — graph preprocessing, not a jit path."""
    xs = np.asarray(jax.device_get(_idx(x)))
    nb = np.asarray(jax.device_get(_idx(neighbors)))
    cnt = np.asarray(jax.device_get(_idx(count)))
    order = {int(g): i for i, g in enumerate(xs)}
    out_nodes = list(xs)
    for g in nb:
        if int(g) not in order:
            order[int(g)] = len(out_nodes)
            out_nodes.append(int(g))
    reindex_nb = np.asarray([order[int(g)] for g in nb], np.int32)
    reindex_dst = np.repeat(np.arange(len(cnt), dtype=np.int32), cnt)
    return (Tensor(jnp.asarray(reindex_nb)),
            Tensor(jnp.asarray(reindex_dst)),
            Tensor(jnp.asarray(np.asarray(out_nodes, np.int32))))
