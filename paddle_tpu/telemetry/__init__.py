"""paddle_tpu.telemetry — the fleet metrics/trace plane (ROADMAP item
5c) plus the persistent compile/AOT cache (item 5a).

One in-process plane that every producer publishes into and every
exporter reads from:

  producers                         events
  ---------                         ------
  jit.TrainStep / ShardedTrainStep  train.step (wall_ms, phases, k)
  OffloadPipelineStep               train.step (trainer=offload)
  PipelineEngine.train_batch        pp.train_batch (schedule, micro)
  collective_schedule()             collective.schedule (kind counts)
  ContinuousBatcher                 serve.chunk / serve.recompile /
                                    serve.kv, and the robustness set
                                    (ISSUE 9): serve.shed /
                                    serve.deadline_miss /
                                    serve.requeue / serve.chunk_fault /
                                    serve.hung / serve.drain
  io.prefetch_to_device             io.step (host_wait_ms)
  distributed.watchdog              watchdog.timeout
  distributed.fault                 fault.hit
  distributed.checkpoint            ckpt.commit / ckpt.gc
  compile cache (this package)      compile.program (hit/miss, ms)

Cost contract: with no sink attached the whole plane is one truthiness
check per would-be event, and arming/disarming sinks or
``FLAGS_compile_cache_dir`` leaves every compiled program byte-identical
(bench.py asserts both).  Exporters: `attach_jsonl` (step log),
`attach_chrome_trace` (chrome://tracing / Perfetto), `dump()` (the
snapshot bench.py embeds in its JSON lines).  `tools/telemetry_report.py`
renders a JSONL log into per-phase medians/p99, MFU trend and cache hit
rate.
"""
from __future__ import annotations

from .registry import (MetricsRegistry, Counter, Gauge, Histogram,  # noqa: F401
                       registry, counter, gauge, histogram,
                       add_sink, remove_sink, sinks, active, emit, span,
                       configure, config, reset as _registry_reset,
                       set_rank, rank_info, percentile_of,
                       percentiles_of, summary_of)
from .exporters import (JsonlSink, ChromeTraceSink, MemorySink,  # noqa: F401
                        attach_jsonl, attach_chrome_trace, chrome_event)
from .compile_cache import (cache_dir, maybe_enable_persistent_cache,  # noqa: F401
                            disable_persistent_cache, aot_compile,
                            compile_report, clear_report)
from . import probe  # noqa: F401
from . import memledger  # noqa: F401
from .memledger import memory_report  # noqa: F401
from . import costledger  # noqa: F401
from .costledger import cost_report  # noqa: F401
from . import fleet  # noqa: F401
from . import flightrec  # noqa: F401
from .flightrec import FlightRecorder  # noqa: F401
from . import numerics  # noqa: F401

__all__ = ["MetricsRegistry", "Counter", "Gauge", "Histogram",
           "registry", "counter", "gauge", "histogram",
           "add_sink", "remove_sink", "sinks", "active", "emit", "span",
           "configure", "config", "reset",
           "set_rank", "rank_info", "percentile_of", "percentiles_of",
           "JsonlSink", "ChromeTraceSink", "MemorySink",
           "attach_jsonl", "attach_chrome_trace", "chrome_event",
           "cache_dir", "maybe_enable_persistent_cache",
           "disable_persistent_cache", "aot_compile", "compile_report",
           "clear_report", "probe", "memledger", "memory_report",
           "costledger", "cost_report",
           "fleet", "flightrec", "FlightRecorder", "numerics",
           "summary_of", "dump", "step_event"]


def reset():
    """Detach every sink, clear registry/config/rank AND the memory +
    compute cost ledgers and the flight recorder — the whole plane
    back to pristine (test isolation)."""
    _registry_reset()
    memledger.reset()
    costledger.reset()
    flightrec.reset()
    numerics.reset()


def dump(compact: bool = False) -> dict:
    """One snapshot of the whole plane: registry instruments, the
    compile report, the fleet identity and the (already-resolved)
    memory ledger.  `compact` trims the per-program compile records to
    totals (what bench.py embeds per JSON line).  Never compiles —
    pending ledger entries stay pending (memory_report() resolves)."""
    out = registry().dump()
    rep = compile_report()
    if compact:
        rep = {k: v for k, v in rep.items() if k != "programs"}
    out["compile"] = rep
    info = rank_info()
    if info is not None:
        out["rank"] = {"rank": info[0], "world": info[1]}
    mem = memledger.snapshot()
    if mem["programs"]:
        out["memory"] = mem if not compact else {
            "programs": len(mem["programs"]),
            "peak_hbm_bytes": mem["peak_hbm_bytes"],
            "device_hbm_bytes": mem["device_hbm_bytes"],
        }
    cost = costledger.snapshot()
    if cost["programs"]:
        out["cost"] = cost if not compact else {
            "programs": len(cost["programs"]),
            "drifts": sum(1 for r in cost["programs"].values()
                          if r.get("drift")),
        }
    return out


# a process launched with FLAGS_compile_cache_dir in its environment
# (relaunched worker, fleet job) arms jax's persistent cache at import —
# BEFORE any subsystem compiles; unset, this is one dict lookup.
# Runtime set_flags() arming is picked up lazily at the next trainer
# build or program-cache miss (aot_for / _model_program_cache).
try:
    maybe_enable_persistent_cache()
except Exception:                       # cache must never break import
    pass

# same idiom for the incident flight recorder: FLAGS_flightrec_dir in
# the environment arms the recorder before any subsystem emits; unset,
# this is one flag lookup.
try:
    flightrec.maybe_attach()
except Exception:                       # recorder must never break import
    pass


def step_event(trainer, *, label: str, kind: str, step: int, k: int,
               wall_ms: float, batch_vals=(), loss_fn=None, extra=None):
    """Publish one `train.step` event for a trainer's compiled call —
    the ONE implementation every trainer shares (jit/sharded/offload
    pass their label; schema changes land here once).

    Callers guard with `telemetry.active()` BEFORE assembling any of
    these arguments, and call AFTER writing the new params back into
    the model (the one-time phase probe reads live state_dict values;
    the pre-call buffers were just donated).  `wall_ms` covers the
    whole (possibly K-fused) call; per-step values are derived here.
    `batch_vals` is ONE step's batch (phase probe + token count).
    `kind` names the compiled program ("step"/"multi"); its first event
    per trainer is marked cold=True — that wall may include the XLA
    compile, so the report CLI excludes cold steps."""
    import numpy as _np
    per_step = wall_ms / max(k, 1)
    fields = {"trainer": label, "step": int(step), "k": int(k),
              "wall_ms": round(wall_ms, 3),
              "step_ms": round(per_step, 3)}
    seen = trainer.__dict__.setdefault("_tel_seen", set())
    if kind not in seen:
        seen.add(kind)
        fields["cold"] = True
    if batch_vals and _np.issubdtype(_np.dtype(batch_vals[0].dtype),
                                     _np.integer):
        tokens = int(_np.prod(batch_vals[0].shape)) * k
        fields["tokens"] = tokens
        if wall_ms > 0:
            fields["tokens_per_sec"] = round(tokens / (wall_ms / 1e3), 1)
    phases = probe.trainer_phases(trainer, batch_vals, loss_fn=loss_fn) \
        if batch_vals else None
    if phases:
        fields["phases"] = {
            "fwd_ms": phases["fwd_ms"],
            "bwd_ms": phases["bwd_ms"],
            "opt_ms": round(max(per_step - phases["fwdbwd_ms"], 0.0), 3),
            "n_params": phases["n_params"],
        }
    if extra:
        fields.update(extra)
    # feed the cost ledger's measured-wall window (warm calls only —
    # the first call per program may include the XLA compile).  The
    # label is the memory ledger's, recorded by note_jit, so the wall
    # lands on exactly the program whose cost_analysis() it describes;
    # the whole call sits inside the caller's active() guard, keeping
    # the no-sink path at zero.
    ml_label = trainer.__dict__.get("_memledger_labels", {}).get(kind)
    if ml_label:
        # a retrace (note_jit saw a new sig) pays its compile in THIS
        # wall — exclude it like the first use
        fresh = trainer.__dict__.get("_memledger_fresh")
        refreshed = bool(fresh) and kind in fresh
        if refreshed:
            fresh.discard(kind)
        costledger.observe(ml_label, wall_ms,
                           cold="cold" in fields or refreshed)
    histogram("train.step_ms").observe(per_step)
    emit("train.step", fields)
    # NOTE: the train.steps counter is incremented by the trainers
    # UNCONDITIONALLY (sink or not) so dump() snapshots lifetime totals
    # — incrementing it here too would double-count
