"""Fleet plane — rank-aware aggregation over the telemetry bus
(ROADMAP item 5c's cross-rank half; reference capability:
`python/paddle/distributed/fleet/` monitor + the profiler's
multi-process timeline merge).

Three pieces, all HOST-plane (nothing here can touch a compiled
program; bench.py extends the r11 byte-identical-HLO assert across the
fleet flags):

  * :class:`FleetSink` — a regular telemetry sink a WORKER attaches
    beside its JSONL log: every N `train.step` events it PUTs a compact
    per-rank step summary (wall/step ms, arrival ts, collective kind
    counts) into the launch KV store (`distributed/launch/master.py`),
    under ``<job>/fleet/<rank>/s<step>`` plus a ``latest`` pointer,
    pruning its own keys past a rolling window.  No sink attached →
    the plane's usual zero-overhead contract holds (the sink only ever
    sees events that were already being emitted).

  * :class:`FleetAggregator` — the COORDINATOR side: ``poll()`` reads
    the per-rank summaries, and for every step all `world` ranks have
    reported judges the cross-rank wall-time skew and arrival skew.
    Past ``FLAGS_straggler_skew_ms`` it emits a ``fleet.straggler``
    event naming the slow rank (and ARMS the existing comm watchdog:
    a straggler that persists ages into the standard
    FLAGS_stop_check_timeout report/abort path; catching up disarms
    it).  Rank step-counter spread past ``FLAGS_fleet_desync_steps``
    or disagreeing per-step collective kind counts (the cross-rank
    collective-order checker's runtime shadow) emit ``fleet.desync``.

  * :func:`merge_jsonl_traces` — per-rank JSONL step logs → ONE chrome
    trace with one lane (pid) per rank, `process_name` metadata naming
    the lanes; `tools/fleet_report.py` is the CLI face.

`init_from_env()` stamps the process's (rank, world) identity onto the
bus from the launcher's env (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM)
without touching jax.distributed — `distributed.env.init_parallel_env`
calls it, and single-process stays rank 0.
"""
from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Dict, List, Optional

from ..framework.flags import define_flag, get_flag
from .registry import (counter as _counter, emit as _emit,
                       set_rank)

__all__ = ["init_from_env", "FleetSink", "FleetAggregator",
           "judge_step", "tombstone_rank", "merge_jsonl_traces",
           "load_jsonl", "log_segments"]

define_flag("straggler_skew_ms", 0.0,
            "cross-rank per-step wall/arrival skew (ms) above which the "
            "fleet aggregator flags the slow rank as a straggler "
            "(fleet.straggler event + watchdog arm); 0 disables the "
            "detector (skews are still recorded)")
define_flag("fleet_report_steps", 1,
            "a FleetSink publishes one per-rank step summary to the "
            "coordinator KV store every N train.step events")
define_flag("fleet_desync_steps", 8,
            "rank step-counter spread above which the aggregator emits "
            "fleet.desync (ranks are no longer executing the same step "
            "window)")


def init_from_env():
    """Stamp (rank, world) from the launcher env onto the telemetry
    bus.  Returns the (rank, world) it announced; single process (no
    launcher vars) announces (0, 1) so 'initialized' single-process
    runs still label their events rank 0."""
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    set_rank(rank, world)
    return rank, world


# ---------------------------------------------------------------------------
# worker side

class FleetSink:
    """Telemetry sink publishing per-rank step summaries to the KV
    store.  Attach beside the JSONL sink on every rank::

        kv = KVClient(master_endpoint)
        telemetry.add_sink(FleetSink(kv, job_id=job, rank=r, world=n))

    Only `train.step` (and `collective.schedule`, folded into the next
    summary) events do any work; everything else returns on one string
    compare.  The KV PUTs run on a background publisher thread behind a
    bounded queue — a dead/hung coordinator fills the queue and later
    summaries are DROPPED (counted in `dropped`), never allowed to
    block the train step (KVClient's retry timeouts are seconds-scale).
    `close()` (remove_sink) and an `atexit` hook drain the queue
    synchronously so a finishing worker's last summaries land."""

    def __init__(self, kv, job_id: str = "fleet",
                 rank: Optional[int] = None,
                 world: Optional[int] = None,
                 every: Optional[int] = None, window: int = 64):
        import atexit
        import queue
        import threading
        if isinstance(kv, str):
            from ..distributed.launch.master import KVClient
            kv = KVClient(kv)
        self._kv = kv
        self._job = job_id
        from .registry import rank_info
        info = rank_info() or (0, 1)
        self._rank = int(info[0] if rank is None else rank)
        self._world = int(info[1] if world is None else world)
        self._every = max(1, int(every if every is not None
                                 else get_flag("fleet_report_steps") or 1))
        self._window = max(1, int(window))
        self._n = 0
        self._coll: Optional[dict] = None
        self._published: deque = deque()    # step keys, oldest first
        self.dropped = 0
        self._q: "queue.Queue" = queue.Queue(maxsize=16)
        self._stopping = threading.Event()
        self._thread = threading.Thread(target=self._publish_loop,
                                        name="fleet-publish",
                                        daemon=True)
        self._thread.start()
        atexit.register(self._drain)

    def record(self, rec: dict):
        ev = rec.get("event")
        if ev == "collective.schedule":
            self._coll = dict(rec.get("kinds") or {})
            return
        if ev != "train.step":
            return
        self._n += 1
        if self._n % self._every:
            return
        import queue
        step = int(rec.get("step", self._n))
        summary = {"rank": self._rank, "world": self._world,
                   "step": step,
                   "ts": float(rec.get("ts") or time.time()),
                   "wall_ms": rec.get("wall_ms"),
                   "step_ms": rec.get("step_ms"),
                   "k": rec.get("k", 1),
                   "cold": bool(rec.get("cold", False)),
                   "steps_seen": self._n}
        if rec.get("tokens_per_sec") is not None:
            summary["tokens_per_sec"] = rec["tokens_per_sec"]
        if self._coll is not None:
            # consume the probe result: kinds ride the NEXT summary
            # only — a stale mix smeared onto every later step would
            # read as a permanent (and un-localizable) desync
            summary["collectives"] = self._coll
            self._coll = None
        pre = f"{self._job}/fleet/{self._rank}"
        key = f"{pre}/s{step:08d}"
        # exact rolling window over the keys actually enqueued (step
        # numbers stride by k under fused multi-step trainers, so
        # "delete step-window" would miss); the pop commits only on a
        # successful enqueue — a dropped summary must not strand its
        # prune target outside the deque forever
        self._published.append(key)
        prune = self._published[0] \
            if len(self._published) > self._window else None
        try:
            self._q.put_nowait((key, f"{pre}/latest",
                                json.dumps(summary), prune))
            if prune is not None:
                self._published.popleft()
        except queue.Full:
            self._published.pop()   # this summary never reaches the
            self.dropped += 1       # store; coordinator stalled —
            #                         drop, never block the step

    def _publish_loop(self):
        import queue
        # timed gets so a close() against a FULL queue (stalled
        # coordinator — the sentinel can't be enqueued) still stops
        # the thread instead of leaking it for the process lifetime
        while not self._stopping.is_set():
            try:
                msg = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            if msg is None:
                return
            self._publish(msg)

    def _publish(self, msg):
        key, latest_key, payload, prune = msg
        try:
            self._kv.put(key, payload)
            self._kv.put(latest_key, payload)
            if prune:
                self._kv.delete(prune)
        except Exception:           # KVClient shouldn't raise; belt+
            pass                    # braces for the publisher thread

    def _drain(self):
        """Publish whatever is still queued, synchronously (close() /
        interpreter exit — a finishing worker's tail must land)."""
        import queue
        try:
            while True:
                msg = self._q.get_nowait()
                if msg is not None:
                    self._publish(msg)
        except queue.Empty:
            pass

    def flush(self):
        self._drain()

    def close(self):
        import atexit
        import queue
        atexit.unregister(self._drain)
        self._stopping.set()
        try:
            self._q.put_nowait(None)        # wake the publisher now
        except queue.Full:
            pass                            # timed get notices anyway
        self._thread.join(timeout=2.0)
        self._drain()

    def retire(self):
        """Tombstone this rank on the KV plane and close the sink — a
        replica retired by a scale-in (ISSUE 19) stops heartbeating on
        purpose, and without the tombstone its stale summaries would
        read as a straggler forever."""
        self.close()
        tombstone_rank(self._kv, self._job, self._rank)


def tombstone_rank(kv, job_id: str, rank: int) -> bool:
    """Mark `rank` as deliberately retired (scaled in / drained) under
    ``<job>/fleet/<rank>/tombstone`` — a master-clock stamp, so the
    retirement time is skew-free.  `FleetAggregator.poll()` drops a
    tombstoned rank from the judged set and shrinks the effective
    world, so a scale-in never fires a spurious ``fleet.straggler``."""
    if isinstance(kv, str):
        from ..distributed.launch.master import KVClient
        kv = KVClient(kv)
    try:
        return bool(kv.stamp(f"{job_id}/fleet/{rank}/tombstone"))
    except Exception:
        return False


# ---------------------------------------------------------------------------
# coordinator side

def judge_step(recs: Dict[int, dict], threshold_ms: float = 0.0,
               arrival_baseline: Optional[Dict[int, float]] = None
               ) -> Optional[dict]:
    """Judge ONE step's per-rank records ({rank: summary/event with
    wall_ms, ts, optional cold}) — THE skew rule the live aggregator
    and the offline fleet_report table share.  Returns None when any
    rank's record is cold (its wall includes the XLA compile), else
    {walls, skew_ms, arrival_skew_ms, worst_rank, flagged}: the worst
    rank is the slowest wall when wall skew dominates, the latest
    arrival otherwise.

    Arrival skew is judged as DRIFT relative to `arrival_baseline`
    ({rank: ts} from the first judged warm step): rank wall clocks are
    not synchronized, so a constant cross-host offset (ordinary NTP
    drift) must never read as a straggler — only offset GROWTH, a rank
    falling further behind step over step, does.  Callers judging a
    sequence pass the baseline; without one the raw ts spread is used
    (same-clock ranks only)."""
    if any(rec.get("cold") for rec in recs.values()):
        return None
    walls = {r: float(rec.get("wall_ms") or 0.0)
             for r, rec in recs.items()}
    arrivals = {r: float(rec.get("ts") or 0.0)
                for r, rec in recs.items()}
    if arrival_baseline:
        arrivals = {r: t - arrival_baseline.get(r, 0.0)
                    for r, t in arrivals.items()}
    skew = max(walls.values()) - min(walls.values())
    askew = (max(arrivals.values()) - min(arrivals.values())) * 1e3
    worst = max(walls, key=walls.get) if skew >= askew \
        else max(arrivals, key=arrivals.get)
    return {"walls": walls,
            "skew_ms": round(skew, 3),
            "arrival_skew_ms": round(askew, 3),
            "worst_rank": worst,
            "flagged": threshold_ms > 0
            and max(skew, askew) > threshold_ms}


def arrivals_of(recs: Dict[int, dict]) -> Dict[int, float]:
    """{rank: arrival ts} of one step's records — the baseline a
    sequence judge captures at its first warm step."""
    return {r: float(rec.get("ts") or 0.0) for r, rec in recs.items()}


class FleetAggregator:
    """Coordinator-side collector + straggler/desync detector.

    ``poll()`` is the driver: read every rank's summaries, judge each
    step window all `world` ranks have reported (exactly once), emit
    ``fleet.straggler`` / ``fleet.desync`` into the LOCAL telemetry
    plane (the coordinator's own sinks/log), and return a report dict
    (`tools/fleet_report.py --live` renders it).

    Watchdog arming: a detected straggler registers a named task with
    the existing CommTaskManager — under FLAGS_stop_check_timeout a
    straggler that persists past the timeout gets the standard thread-
    stack dump / abort treatment; a rank that catches up (judged clean
    on a later step) is disarmed.  With the watchdog flag off, arming
    is a no-op and the events remain the signal."""

    def __init__(self, kv, job_id: str = "fleet", world: int = 2,
                 skew_ms: Optional[float] = None,
                 desync_steps: Optional[int] = None,
                 history: int = 256):
        if isinstance(kv, str):
            from ..distributed.launch.master import KVClient
            kv = KVClient(kv)
        self._kv = kv
        self._job = job_id
        self.world = int(world)
        self._skew_ms = skew_ms
        self._desync_steps = desync_steps
        self.skews: deque = deque(maxlen=max(1, int(history)))
        self.straggler_counts: Dict[int, int] = {}
        self._last_judged = 0
        self._arrival_baseline: Optional[Dict[int, float]] = None
        self._desynced = False
        self._watch_tasks: Dict[int, object] = {}

    # -- thresholds --------------------------------------------------------
    def _threshold(self) -> float:
        if self._skew_ms is not None:
            return float(self._skew_ms)
        return float(get_flag("straggler_skew_ms") or 0.0)

    def _desync_threshold(self) -> int:
        if self._desync_steps is not None:
            return int(self._desync_steps)
        return int(get_flag("fleet_desync_steps") or 8)

    # -- watchdog ----------------------------------------------------------
    def _arm(self, rank: int):
        if rank in self._watch_tasks:
            return
        from ..distributed.watchdog import get_comm_task_manager
        task = get_comm_task_manager().start_task(
            f"fleet.straggler rank{rank}")
        if task is not None:            # watchdog disabled -> no-op
            self._watch_tasks[rank] = task

    def _disarm(self, rank: int):
        task = self._watch_tasks.pop(rank, None)
        if task is not None:
            task.done()

    def close(self):
        for rank in list(self._watch_tasks):
            self._disarm(rank)

    # -- the driver --------------------------------------------------------
    def poll(self) -> dict:
        got = self._kv.prefix(f"{self._job}/fleet")
        # tombstones first: a rank retired by a scale-in (ISSUE 19)
        # stopped heartbeating on purpose — its stale summaries must
        # not enter the judged set or read as a straggler
        tombstoned: set = set()
        for key in got:
            if key.endswith("/tombstone"):
                try:
                    tombstoned.add(int(key.split("/")[-2]))
                except ValueError:
                    continue
        per_rank: Dict[int, Dict[int, dict]] = {}
        latest: Dict[int, dict] = {}
        for key, raw in got.items():
            try:
                rec = json.loads(raw)
                rank = int(rec["rank"])
            except (ValueError, KeyError, TypeError):
                continue
            if rank in tombstoned:
                continue
            if key.endswith("/latest"):
                latest[rank] = rec
            else:
                per_rank.setdefault(rank, {})[int(rec["step"])] = rec
        # a tombstoned rank can never age into the watchdog abort path
        for rank in tombstoned:
            self._disarm(rank)
            self.straggler_counts.pop(rank, None)
        world_eff = max(1, self.world - len(tombstoned))

        stragglers_this_poll: set = set()
        judged_this_poll: List[int] = []
        thr = self._threshold()
        if len(per_rank) >= world_eff:
            common = sorted(set.intersection(
                *[set(d) for d in per_rank.values()]))
            for s in common:
                if s <= self._last_judged:
                    continue
                recs = {r: per_rank[r][s] for r in per_rank}
                self._last_judged = s
                if any(rec.get("cold") for rec in recs.values()):
                    # cold step: its wall includes the XLA compile —
                    # judging it (or baselining arrivals on it) would
                    # flag every rank whose compile ran longest
                    continue
                if self._arrival_baseline is None:
                    # first warm step anchors the per-rank clock
                    # offsets; from here arrival skew means DRIFT
                    self._arrival_baseline = arrivals_of(recs)
                verdict = judge_step(recs, thr,
                                     self._arrival_baseline)
                if verdict is None:
                    continue
                self.skews.append({"step": s,
                                   "skew_ms": verdict["skew_ms"],
                                   "arrival_skew_ms":
                                   verdict["arrival_skew_ms"],
                                   "walls": verdict["walls"]})
                judged_this_poll.append(s)
                if verdict["flagged"]:
                    worst = verdict["worst_rank"]
                    stragglers_this_poll.add(worst)
                    self.straggler_counts[worst] = \
                        self.straggler_counts.get(worst, 0) + 1
                    _counter("fleet.stragglers").inc()
                    _emit("fleet.straggler", step=s, straggler=worst,
                          skew_ms=verdict["skew_ms"],
                          arrival_skew_ms=verdict["arrival_skew_ms"],
                          threshold_ms=thr,
                          walls={str(r): round(w, 3) for r, w
                                 in verdict["walls"].items()})
                # collective-schedule divergence: the ranks ran
                # different collective mixes for the SAME step — the
                # runtime shadow of check_collective_order
                colls = {r: rec.get("collectives")
                         for r, rec in recs.items()
                         if rec.get("collectives") is not None}
                if len(colls) >= 2 and len(
                        {json.dumps(c, sort_keys=True)
                         for c in colls.values()}) > 1:
                    _counter("fleet.desyncs").inc()
                    _emit("fleet.desync", reason="collectives", step=s,
                          kinds={str(r): c for r, c in colls.items()})

        # straggler watchdog arm/disarm on this poll's verdicts
        for rank in stragglers_this_poll:
            self._arm(rank)
        if judged_this_poll:
            for rank in list(self._watch_tasks):
                if rank not in stragglers_this_poll:
                    self._disarm(rank)

        # rank step-counter spread (from the latest pointers): ranks no
        # longer executing the same step window
        steps_latest = {r: int(rec.get("step", 0))
                        for r, rec in latest.items()}
        if len(steps_latest) >= 2:
            spread = max(steps_latest.values()) - min(steps_latest.values())
            if spread > self._desync_threshold():
                if not self._desynced:      # edge-trigger, not per poll
                    _counter("fleet.desyncs").inc()
                    _emit("fleet.desync", reason="step-spread",
                          spread=spread,
                          steps={str(r): s
                                 for r, s in steps_latest.items()})
                self._desynced = True
            else:
                self._desynced = False

        return {
            "world": self.world,
            "world_effective": world_eff,
            "tombstoned": sorted(tombstoned),
            "ranks": sorted(per_rank) or sorted(latest),
            "steps_judged": self._last_judged,
            "latest_steps": steps_latest,
            "skews": list(self.skews),
            "max_skew_ms": max((s["skew_ms"] for s in self.skews),
                               default=0.0),
            "stragglers": dict(self.straggler_counts),
            "watchdog_armed": sorted(self._watch_tasks),
        }


# ---------------------------------------------------------------------------
# offline merge: per-rank JSONL logs -> one rank-laned chrome trace

def load_jsonl(path: str) -> List[dict]:
    """Parse a telemetry JSONL log; blank lines skipped, a torn tail
    line (crash mid-write) is dropped rather than failing the merge."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out


def log_segments(path: str) -> List[str]:
    """A JSONL log plus its size-rotated segments, OLDEST FIRST
    (``events.jsonl.N ... events.jsonl.1 events.jsonl`` — the
    JsonlSink rotation shifts older segments to higher suffixes).
    A log that never rotated is just ``[path]``."""
    segs: List[str] = []
    n = 1
    while os.path.exists(f"{path}.{n}"):
        segs.append(f"{path}.{n}")
        n += 1
    return list(reversed(segs)) + [path]


def merge_jsonl_traces(paths: List[str], out_path: Optional[str] = None,
                       ranks: Optional[List[int]] = None) -> dict:
    """Merge per-rank JSONL step logs into ONE chrome trace, one lane
    (pid) per rank.  Each record's own `rank` tag wins; a log whose
    records are untagged (single-process, pre-fleet) gets `ranks[i]`
    (default: its position in `paths`).  A log that size-rotated
    (FLAGS_telemetry_max_log_mb) contributes all its segments in
    order.  Returns the trace doc and writes it to `out_path` when
    given — load in chrome://tracing or Perfetto and every rank is a
    named lane on one timeline."""
    from .exporters import chrome_event, _jsonable
    events: List[dict] = []
    lanes: set = set()
    for i, path in enumerate(paths):
        default_rank = ranks[i] if ranks is not None else i
        for seg in log_segments(path):
            for rec in load_jsonl(seg):
                rank = int(rec.get("rank", default_rank))
                lanes.add(rank)
                events.append(chrome_event(rec, pid=rank, tid=0))
    meta = []
    for rank in sorted(lanes):
        meta.append({"name": "process_name", "ph": "M", "pid": rank,
                     "args": {"name": f"rank {rank}"}})
        meta.append({"name": "process_sort_index", "ph": "M",
                     "pid": rank, "args": {"sort_index": rank}})
    doc = {"traceEvents": meta + events}
    if out_path:
        d = os.path.dirname(out_path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(doc, f, default=_jsonable)
    return doc
