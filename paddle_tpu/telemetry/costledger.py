"""Compute cost ledger — per-program FLOP/byte accounting from XLA's
own `compiled.cost_analysis()`, the compute twin of `memledger.py`
(ROADMAP item 5: the calibrated step-time model primitive.  r14's
memory ledger answered "will this program fit?"; this ledger answers
"is this program as fast as it should be?").

Cost model (the plane's usual contract):

  * ZERO extra compiles: the ledger has no providers of its own — it
    rides the memory ledger's.  When `memledger` resolves a pending
    provider (or the AOT path captures a free executable), the SAME
    Compiled is handed here and `cost_analysis()` extracted alongside
    `memory_analysis()`.  `cost_report()` forces resolution through
    `memledger.memory_report()`, so one compile per program serves
    both ledgers (probe-contract pinned like the memory ledger: serve
    resolution rides the side-effect-free `lower_step` probe).
  * MEASURED walls arrive from the live `train.step` / `serve.chunk`
    events: `telemetry.step_event` and the serving batcher call
    `observe(label, wall_ms, cold=...)` inside their existing
    sink-guarded blocks — with no sink attached nothing here runs
    (the zero-overhead contract bench.py asserts), and cold calls
    (XLA compile in the wall) are excluded like every other timing
    surface in the repo.
  * The roofline verdict uses the backend's CALIBRATED peaks: the
    bf16 matmul peak (bench.py's table) and the HBM stream bandwidth,
    scaled by the CALIBRATION_r05 efficiency anchor (mfu_assumption
    0.6 — llama-1B implied 0.689, bert-base 0.576).  Override with
    `configure_peaks()` or the PEAK_FLOPS / PEAK_HBM_GBPS envs.

Report shape (per program): flops, bytes_accessed, arithmetic
intensity (flops/byte), roofline ``bound`` ("compute" when intensity
clears the ridge point peak_flops/peak_bw, else "memory"),
``predicted_ms`` = max(compute-limb, memory-limb) at the calibrated
peaks, the measured warm-step median when events flowed, and
``attained`` = predicted/measured — the fraction of the calibrated
roofline the program actually achieves (1.0 = running exactly at the
calibrated model; below ``FLAGS_mfu_floor`` emits `perf.drift` and
trips `analysis.lint_mfu_floor`).

Per-layer attribution: the models thread `jax.named_scope` through
their block forwards, so the optimized HLO carries model-structure
names ("llama.layer3", "gpt.embed", ...) — `ingest` runs a cheap
scope census over the compiled text and each entry reports op counts
per scope instead of one opaque program (the same names land in
device chrome traces for tools/fleet_report.py lanes).
"""
from __future__ import annotations

import os
import re
import threading
from collections import deque
from typing import Dict, List, Optional

__all__ = ["cost_of", "model_train_flops", "backend_peaks",
           "chip_peak_flops", "configure_peaks", "ingest", "observe",
           "measured_ms", "program_changed", "cost_report", "snapshot",
           "reset", "scope_census", "note_comm",
           "interconnect_bytes_per_sec"]

_lock = threading.Lock()
_costs: Dict[str, dict] = {}        # label -> entry (insertion-ordered)
_comm: Dict[str, dict] = {}         # label -> {axes: comm profile} —
#                                     one profile per comm axis so a
#                                     composed (hybrid) program's
#                                     columns add instead of replacing
_measured: Dict[str, deque] = {}    # label -> warm wall_ms window
_measured_total: Dict[str, int] = {}
_drifted: set = set()               # labels currently below the floor
#                                     (perf.drift edge-triggers, like
#                                     fleet.desync — a monitoring loop
#                                     polling cost_report() counts
#                                     detections, not polls)
_MEASURED_WINDOW = 512
_peaks_override: Dict[str, float] = {}

# bf16 matmul peak (bench.py's table) and HBM stream bandwidth per
# chip generation; the serving roofline in bench.py already assumes
# the v5e 0.82 TB/s figure, kept consistent here.
PEAK_FLOPS = {"v4": 275e12, "v5e": 197e12, "v5p": 459e12, "v6e": 918e12}
PEAK_HBM_BPS = {"v4": 1.23e12, "v5e": 0.82e12, "v5p": 2.77e12,
                "v6e": 1.64e12}
# CALIBRATION_r05 anchor: predictions at mfu_assumption 0.6 landed
# within 0.88-1.04x of measured full steps on the real chip
CALIBRATED_EFFICIENCY = 0.6
# per-chip ICI all-reduce bandwidth (bytes/s per device, the
# bidirectional-ring figure the exposed-comm column divides by);
# PEAK_ICI_GBPS env overrides for other fabrics (DCN, PCIe hosts)
PEAK_ICI_BPS = {"v4": 300e9, "v5e": 160e9, "v5p": 600e9, "v6e": 400e9}
# CPU placeholder peaks: tier-1 exercises the plumbing, not the
# numbers (tests pin behavior through configure_peaks)
_CPU_PEAKS = {"flops_per_sec": 100e9, "hbm_bytes_per_sec": 50e9,
              "ici_bytes_per_sec": 10e9}


def _chip_name() -> Optional[str]:
    """TPU generation name, or None off-TPU.  THE one chip sniffing
    (bench.chip_peak_flops delegates here): the PALLAS_AXON_TPU_GEN
    relay env wins, then the device kind."""
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    for name in PEAK_FLOPS:
        if name in gen:
            return name
    try:
        import jax
        if jax.default_backend() != "tpu":
            return None
        kind = jax.devices()[0].device_kind.lower().replace(" ", "")
        if "v5lite" in kind or "v5e" in kind:
            return "v5e"
        for name in ("v6e", "v5p", "v4"):
            if name in kind:
                return name
        if "v5" in kind:
            return "v5p"
    except Exception:
        pass
    return None


def chip_peak_flops(default: Optional[str] = "v5e") -> float:
    """Canonical bf16 matmul peak for this backend (bench.py's MFU
    lines and the roofline both read it from HERE): PEAK_FLOPS env
    override, else the sniffed chip, else `default` (bench's historic
    v5e fallback — its smoke lines quote MFU against the target chip
    even off-TPU)."""
    if "PEAK_FLOPS" in os.environ:
        return float(os.environ["PEAK_FLOPS"])
    name = _chip_name() or default
    if name in PEAK_FLOPS:
        return PEAK_FLOPS[name]
    return _CPU_PEAKS["flops_per_sec"]


def configure_peaks(flops_per_sec: Optional[float] = None,
                    hbm_bytes_per_sec: Optional[float] = None,
                    efficiency: Optional[float] = None,
                    ici_bytes_per_sec: Optional[float] = None):
    """Override the calibrated peaks (tools/tests; calibration runs
    feed their implied mfu back through `efficiency`).  Passing None
    for a field leaves it on the chip-table default; `reset()` clears
    every override."""
    with _lock:
        if flops_per_sec is not None:
            _peaks_override["flops_per_sec"] = float(flops_per_sec)
        if hbm_bytes_per_sec is not None:
            _peaks_override["hbm_bytes_per_sec"] = float(hbm_bytes_per_sec)
        if efficiency is not None:
            _peaks_override["efficiency"] = float(efficiency)
        if ici_bytes_per_sec is not None:
            _peaks_override["ici_bytes_per_sec"] = float(
                ici_bytes_per_sec)
    return backend_peaks()


def interconnect_bytes_per_sec() -> float:
    """Calibrated interconnect bandwidth for collective payloads (the
    denominator of the exposed-comm column): PEAK_ICI_GBPS env wins,
    then a configure_peaks override, then the sniffed chip's ICI peak
    scaled by the calibration efficiency, else the CPU placeholder."""
    if "PEAK_ICI_GBPS" in os.environ:
        return float(os.environ["PEAK_ICI_GBPS"]) * 1e9
    with _lock:
        ov = _peaks_override.get("ici_bytes_per_sec")
        eff = _peaks_override.get("efficiency", CALIBRATED_EFFICIENCY)
    if ov is not None:
        return ov
    chip = _chip_name()
    raw = PEAK_ICI_BPS.get(chip, _CPU_PEAKS["ici_bytes_per_sec"])
    return raw * eff


def backend_peaks() -> dict:
    """The calibrated roofline peaks for this backend: raw hardware
    peaks, the calibration efficiency, and the ridge intensity
    (flops/byte) that separates compute- from memory-bound."""
    chip = _chip_name()
    if chip:
        flops = PEAK_FLOPS[chip]
        hbm = PEAK_HBM_BPS[chip]
        source = f"chip-table:{chip}"
    else:
        flops = _CPU_PEAKS["flops_per_sec"]
        hbm = _CPU_PEAKS["hbm_bytes_per_sec"]
        source = "default:cpu"
    if "PEAK_FLOPS" in os.environ:
        flops = float(os.environ["PEAK_FLOPS"])
        source += "+env"
    if "PEAK_HBM_GBPS" in os.environ:
        hbm = float(os.environ["PEAK_HBM_GBPS"]) * 1e9
        source += "+env"
    eff = CALIBRATED_EFFICIENCY
    with _lock:
        flops = _peaks_override.get("flops_per_sec", flops)
        hbm = _peaks_override.get("hbm_bytes_per_sec", hbm)
        eff = _peaks_override.get("efficiency", eff)
        if _peaks_override:
            source += "+override"
    return {"chip": chip, "flops_per_sec": flops,
            "hbm_bytes_per_sec": hbm, "efficiency": eff,
            "ridge_intensity": flops / hbm if hbm else None,
            "source": source}


# ---------------------------------------------------------------------------
# the ONE cost_analysis derivation (paddle.flops() and the ledger both
# read through here; jax returns a list-of-dict on some backends)

def cost_of(compiled) -> dict:
    """`compiled.cost_analysis()` -> plain {flops, bytes_accessed,
    transcendentals} floats."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    ca = ca or {}
    return {
        "flops": float(ca.get("flops", 0.0)),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        "transcendentals": float(ca.get("transcendentals", 0.0)),
    }


def model_train_flops(n_params: float, tokens: float,
                      phase: str = "full",
                      remat_flops_per_token: float = 0.0) -> float:
    """Analytic model-FLOP accounting for dense LM training — the ONE
    derivation tools/profile_mfu.py and bench.py's MFU lines share
    (regression-pinned): 2N/tok forward, 4N/tok backward, 6N/tok full
    step; `remat_flops_per_token` adds the recompute replay FLOPs the
    hardware actually executes (bwd/full phases only)."""
    per_tok = {"fwd": 2.0, "bwd": 4.0, "full": 6.0}[phase] * n_params
    if phase in ("bwd", "full"):
        per_tok += remat_flops_per_token
    return per_tok * tokens


# ---------------------------------------------------------------------------
# scope census — per-layer attribution from named_scope HLO metadata

# the scope vocabulary the model forwards thread (kept tight so
# source-file paths like ".../llama.py" in op metadata never count).
# Lookarounds instead of /-anchors: autodiff wraps scopes in transform
# frames — "jvp(llama.layer0)", "transpose(jvp(llama.layer0))" — and
# those ops belong to the layer all the same.
_SCOPE_PAT = re.compile(
    r'(?<![\w.])((?:llama|gpt|bert)\.'
    r'(?:layer\d+|embed|norm|lm_head|pooler))(?![\w.])')
_CENSUS_TEXT_CAP = 64 * 1024 * 1024


def scope_census(compiled, cap: int = 64) -> Dict[str, int]:
    """Op counts per model-structure `jax.named_scope` name found in
    the optimized HLO's op_name metadata ("llama.layer0", "gpt.embed",
    ...) — the per-layer attribution the block forwards thread in.
    Empty when the program carries no scoped metadata."""
    try:
        text = compiled.as_text()
    except Exception:
        return {}
    if not text or len(text) > _CENSUS_TEXT_CAP:
        return {}
    counts: Dict[str, int] = {}
    for m in _SCOPE_PAT.finditer(text):
        name = m.group(1)
        counts[name] = counts.get(name, 0) + 1
    if len(counts) > cap:
        top = sorted(counts.items(), key=lambda kv: -kv[1])[:cap]
        counts = dict(top)
    return counts


# ---------------------------------------------------------------------------
# ingestion (called by memledger at resolve/capture — the shared
# Compiled means the cost ledger never compiles anything itself)

def ingest(label: str, compiled, meta: Optional[dict] = None):
    """Record cost stats for an in-hand executable under `label`.
    Failures record an error entry rather than raising (the memory
    ledger's resolution must never die on the cost side)."""
    try:
        stats = cost_of(compiled)
    except Exception as e:          # noqa: BLE001
        with _lock:
            _costs[label] = {"label": label, "status": "error",
                             "error": f"{type(e).__name__}: {e}",
                             "meta": dict(meta or {})}
        return None
    entry = {"label": label, "status": "ok", "meta": dict(meta or {}),
             **stats}
    scopes = scope_census(compiled)
    if scopes:
        entry["scopes"] = scopes
    with _lock:
        _costs[label] = entry
    _publish(entry)
    return entry


def note_comm(label: str, profile: dict):
    """Attach a communication profile to `label`'s program (ISSUE 16):
    byte volumes per bucket in issue order plus the overlap shape, as
    produced by CommOverlapPlan.comm_profile().  The report derives
    the exposed-comm column from it — comm time at the calibrated ICI
    peak vs the backward compute available to hide it under — so the
    overlap win is a ledger number before any chip time.  Registered
    at trainer BUILD (zero steady-state cost).

    Profiles are keyed PER COMM AXIS (the `axes` field, e.g.
    ["dp", "sharding"] for the joint grad reduce, ["mp"] for the TP
    activation exchange): a composed hybrid program registers one
    profile per mesh axis under the same label and the report's
    columns ADD across axes — each bucket is counted exactly once,
    under the one axis whose collective drains it.  Re-noting the
    same (label, axes) replaces that axis's profile (a rebuild), never
    duplicates it.  Single-axis callers are unchanged."""
    key = tuple(profile.get("axes") or ())
    with _lock:
        _comm.setdefault(label, {})[key] = dict(profile)


def _publish(entry: dict):
    """cost.program event + counter — a fleet JSONL log carries the
    cost ledger the way it carries mem.program records."""
    from .registry import counter as _counter, emit as _emit
    _counter("cost.programs").inc()
    _emit("cost.program",
          {k: v for k, v in entry.items() if k != "scopes"})


# ---------------------------------------------------------------------------
# measured walls (fed by step_event / the serving batcher, only while
# a sink is attached — the zero-overhead contract)

def program_changed(label: str):
    """A NEW program now owns `label` (memledger.register replaces on
    the same label): the old program's measured walls, cost entry and
    drift edge must not leak onto it — a small model's sub-ms walls
    against a big model's prediction would mask (or spuriously fire)
    a drift.  Called by memledger.register; registration happens
    before the new program's first step_event, so no fresh wall is
    ever dropped."""
    with _lock:
        _measured.pop(label, None)
        _measured_total.pop(label, None)
        _costs.pop(label, None)
        _comm.pop(label, None)
        _drifted.discard(label)


def observe(label: str, wall_ms: float, cold: bool = False):
    """Record one measured warm wall for `label`'s program.  Cold
    calls (first use — the wall may include the XLA compile) are
    excluded, mirroring every other timing surface."""
    if cold:
        return
    with _lock:
        win = _measured.get(label)
        if win is None:
            win = _measured[label] = deque(maxlen=_MEASURED_WINDOW)
        win.append(float(wall_ms))
        _measured_total[label] = _measured_total.get(label, 0) + 1


def measured_ms(label: str) -> Optional[float]:
    """Median warm wall over the recent window, or None."""
    with _lock:
        win = _measured.get(label)
        vals = sorted(win) if win else None
    if not vals:
        return None
    n = len(vals)
    mid = n // 2
    return vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])


# ---------------------------------------------------------------------------
# the report

def _floor() -> float:
    from ..framework.flags import get_flag
    try:
        return float(get_flag("mfu_floor", 0.0) or 0.0)
    except Exception:
        return 0.0


def cost_report(resolve: bool = True,
                measured: Optional[Dict[str, float]] = None) -> dict:
    """The ledger's answer: per-program FLOPs/bytes/intensity, the
    roofline bound and predicted step time at the calibrated peaks,
    and — where `train.step`/`serve.chunk` walls flowed — the measured
    median and `attained` = predicted/measured.  `resolve=True` forces
    the memory ledger's pending providers (ONE compile per program
    serves both ledgers); `measured` lets tools inject explicit walls
    per label (overrides the live window).  Programs whose `attained`
    falls below FLAGS_mfu_floor are marked `drift` and published as
    `perf.drift` events."""
    return _report(resolve=resolve, measured=measured, emit_drift=True)


def snapshot() -> dict:
    """The report without resolution or drift side effects (what
    telemetry.dump() embeds)."""
    return _report(resolve=False, measured=None, emit_drift=False)


def _report(resolve: bool, measured, emit_drift: bool) -> dict:
    if resolve:
        from . import memledger
        # one resolution pass fills BOTH ledgers: memledger compiles
        # each pending provider once and hands the Compiled to ingest
        memledger.memory_report(resolve=True, top_buffers=0)
    peaks = backend_peaks()
    eff = peaks["efficiency"]
    flops_eff = peaks["flops_per_sec"] * eff
    hbm_eff = peaks["hbm_bytes_per_sec"] * eff
    floor = _floor()
    with _lock:
        entries = [dict(e) for e in _costs.values()]
        comm_profiles = {lbl: {k: dict(v) for k, v in p.items()}
                         for lbl, p in _comm.items()}
    ici_bps = interconnect_bytes_per_sec() if comm_profiles else None
    programs: Dict[str, dict] = {}
    drifts: List[str] = []
    for e in entries:
        rec = {k: v for k, v in e.items() if k != "label"}
        if e.get("status") == "ok":
            flops = e["flops"]
            nbytes = e["bytes_accessed"]
            intensity = (flops / nbytes) if nbytes else None
            rec["intensity"] = round(intensity, 3) \
                if intensity is not None else None
            t_compute = flops / flops_eff if flops_eff else 0.0
            t_memory = nbytes / hbm_eff if hbm_eff else 0.0
            rec["bound"] = "compute" if t_compute >= t_memory \
                else "memory"
            predicted_ms = max(t_compute, t_memory) * 1e3
            rec["predicted_compute_ms"] = round(t_compute * 1e3, 4)
            rec["predicted_memory_ms"] = round(t_memory * 1e3, 4)
            rec["predicted_ms"] = round(predicted_ms, 4)
            m = None
            if measured and e["label"] in measured:
                m = float(measured[e["label"]])
            else:
                m = measured_ms(e["label"])
            if m is not None and m > 0:
                rec["measured_ms"] = round(m, 4)
                with _lock:
                    rec["measured_n"] = _measured_total.get(
                        e["label"], 0) or 1
                rec["achieved_flops_per_sec"] = round(
                    flops / (m / 1e3), 1)
                if peaks["flops_per_sec"]:
                    rec["achieved_mfu"] = round(
                        flops / (m / 1e3) / peaks["flops_per_sec"], 4)
                # attained from the UNROUNDED prediction: a sub-50ns
                # program's predicted_ms displays as 0.0 but must not
                # read as attained 0.0 (unconditional drift)
                attained = predicted_ms / m
                rec["attained"] = round(attained, 4)
                if floor > 0 and attained < floor:
                    rec["drift"] = True
                    drifts.append(e["label"])
            cp_map = comm_profiles.get(e["label"])
            if cp_map:
                # the exposed-comm columns (ISSUE 16/17): per-bucket
                # comm at the ICI peak vs the backward compute
                # available to hide it.  Backward ≈ 2/3 of a fwd+bwd
                # step (4N of 6N FLOPs) — the window the bucket chain
                # overlaps into.  One column PER COMM AXIS, summed
                # additively into the program totals: each axis's
                # buckets drain over their own links, and a bucket
                # belongs to exactly one axis profile, so a composed
                # dp×mp×sharding program never double-counts an
                # overlapped bucket.
                from ..analysis.collectives import estimate_exposed_comm
                bwd_ms = predicted_ms * (2.0 / 3.0)
                by_axis = {}
                tot = {"bytes": 0, "buckets": 0, "comm_ms": 0.0,
                       "on": 0.0, "off": 0.0}
                overlap_all = True
                for axes_key in sorted(cp_map, key=repr):
                    cp = cp_map[axes_key]
                    sizes = cp.get("bucket_bytes") \
                        or [cp.get("bytes", 0)]
                    on = estimate_exposed_comm(
                        sizes, bwd_ms, bytes_per_sec=ici_bps,
                        overlap=True)
                    off = estimate_exposed_comm(
                        sizes, bwd_ms, bytes_per_sec=ici_bps,
                        overlap=False)
                    name = "+".join(axes_key) if axes_key else "all"
                    by_axis[name] = {
                        "bytes": on["bytes"],
                        "buckets": on["buckets"],
                        "comm_ms": round(on["comm_ms"], 4),
                        "exposed_ms": round(on["exposed_ms"], 4),
                        "exposed_ms_monolithic": round(
                            off["exposed_ms"], 4)}
                    tot["bytes"] += on["bytes"]
                    tot["buckets"] += on["buckets"]
                    tot["comm_ms"] += on["comm_ms"]
                    tot["on"] += on["exposed_ms"]
                    tot["off"] += off["exposed_ms"]
                    overlap_all = overlap_all \
                        and bool(cp.get("overlap", True))
                rec["comm_bytes"] = tot["bytes"]
                rec["comm_buckets"] = tot["buckets"]
                rec["comm_ms"] = round(tot["comm_ms"], 4)
                rec["exposed_comm_ms"] = round(tot["on"], 4)
                rec["exposed_comm_ms_monolithic"] = round(
                    tot["off"], 4)
                rec["exposed_comm_by_axis"] = by_axis
                rec["overlap_efficiency"] = round(
                    1.0 - tot["on"] / tot["comm_ms"], 4) \
                    if tot["comm_ms"] else 1.0
                rec["comm_overlap"] = overlap_all
        programs[e["label"]] = rec
    if emit_drift:
        from .registry import counter as _counter, emit as _emit
        # predicted-vs-measured records for every measured program (a
        # JSONL log then carries the roofline cross-check, drifting or
        # not — telemetry_report's cost section renders them)
        for lbl, rec in programs.items():
            if "attained" in rec:
                # the measure record carries the drift STATE (the
                # perf.drift event is edge-triggered and won't repeat
                # while a drift persists — readers of the latest
                # measure must still see it)
                _emit("cost.measure", label=lbl,
                      predicted_ms=rec["predicted_ms"],
                      measured_ms=rec["measured_ms"],
                      attained=rec["attained"], bound=rec["bound"],
                      drift=bool(rec.get("drift")))
        # perf.drift is EDGE-triggered per label (the fleet.desync
        # discipline): a monitoring loop that polls cost_report()
        # while one program sits below the floor counts ONE
        # detection, not one per poll; recovery re-arms the edge.
        # snapshot() never reaches here, so it cannot swallow an edge.
        with _lock:
            new = [lbl for lbl in drifts if lbl not in _drifted]
            _drifted.clear()
            _drifted.update(drifts)
        if new:
            _counter("perf.drift").inc(len(new))
            for lbl in new:
                rec = programs[lbl]
                _emit("perf.drift", label=lbl,
                      predicted_ms=rec["predicted_ms"],
                      measured_ms=rec["measured_ms"],
                      attained=rec["attained"], floor=floor)
    return {"programs": programs, "peaks": peaks,
            "mfu_floor": floor or None}


def reset():
    with _lock:
        _costs.clear()
        _comm.clear()
        _measured.clear()
        _measured_total.clear()
        _peaks_override.clear()
        _drifted.clear()
