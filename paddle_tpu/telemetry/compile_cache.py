"""Persistent compilation cache + AOT executable serialization — the
cold-start killer (ROADMAP item 5a: MULTICHIP_r05 logged a 3-minute XLA
compile for ONE step; a 128-chip relaunch or re-elected elastic worker
must not pay trace+compile again).

Two layers, both armed by ``FLAGS_compile_cache_dir`` and both inert
(one flag lookup) when it is unset:

  1. **XLA persistent cache** — `jax.config` compilation-cache setup
     pointed at ``<dir>``: every `jax.jit` in the process (trainers,
     generate(), the serving batcher's scan programs) transparently
     reuses compiled modules across processes.  Hit/miss counts are
     scraped from jax's monitoring events into `compile_report()`.
  2. **AOT executable store** — trainers additionally `.lower()` their
     step once, fingerprint the StableHLO, and serialize the compiled
     executable to ``<dir>/aot/``; a relaunched worker deserializes and
     SKIPS the XLA compile.  NOTE the hit path still pays tracing +
     lowering (the fingerprint requires the StableHLO) — seconds for a
     big model, vs the minutes-scale compile it skips; per-program
     trace_ms in `compile_report()` shows exactly what remains.
     `jax.experimental.serialize_executable` preserves donation and
     shardings.

`compile_report()` is the telemetry face: one record per AOT program
(trace/compile/load ms, hit/miss, key) plus the process-wide XLA cache
counters — cold start becomes a first-class metric.
"""
from __future__ import annotations

import hashlib
import os
import pickle
import threading
import time
import warnings
from typing import Any, Dict, List, Optional

from ..framework.flags import get_flag
# import the functions, not the module: the package __init__ re-exports
# a `registry()` accessor that shadows the submodule attribute
from .registry import counter as _counter, emit as _emit

__all__ = ["cache_dir", "maybe_enable_persistent_cache",
           "disable_persistent_cache", "aot_compile", "aot_for",
           "compile_report", "clear_report"]

_lock = threading.Lock()
_records: List[dict] = []
_xla_counts = {"hits": 0, "misses": 0}
_enabled_dir: Optional[str] = None
_listener_installed = False
_prior_jax_config: Optional[dict] = None


def cache_dir() -> Optional[str]:
    """The armed cache directory, or None.  THE fast-path guard: every
    producer calls this first, and unset it is one dict lookup."""
    d = get_flag("compile_cache_dir") or ""
    return d or None


def _on_jax_event(event: str):
    if event == "/jax/compilation_cache/cache_hits":
        _xla_counts["hits"] += 1
        _counter("compile.xla_cache_hits").inc()
    elif event == "/jax/compilation_cache/cache_misses":
        _xla_counts["misses"] += 1
        _counter("compile.xla_cache_misses").inc()


def maybe_enable_persistent_cache() -> Optional[str]:
    """Point jax's persistent compilation cache at FLAGS_compile_cache_dir
    (idempotent; re-arms on a changed dir).  Returns the dir or None.

    min_compile_time/min_entry_size are zeroed so even small programs
    (and the CPU-backend tier-1 programs) persist — the default 1s
    threshold would silently exclude exactly the quick-compiling
    programs tests use to prove the wiring."""
    global _enabled_dir, _listener_installed
    d = cache_dir()
    if d == _enabled_dir:
        return _enabled_dir
    if d is None:
        # flag cleared after a previous arming: honor the documented
        # "empty disables both layers" — otherwise every later jit
        # keeps writing the stale (possibly deleted temp) dir
        disable_persistent_cache()
        return None
    with _lock:
        global _prior_jax_config
        if d == _enabled_dir:
            return _enabled_dir
        import jax
        os.makedirs(d, exist_ok=True)
        if _prior_jax_config is None:
            # snapshot whatever the user/env configured so disarming
            # restores it instead of clobbering an independently-set
            # jax cache (JAX_COMPILATION_CACHE_DIR etc.)
            _prior_jax_config = {
                "jax_compilation_cache_dir":
                    jax.config.jax_compilation_cache_dir,
                "jax_enable_compilation_cache":
                    jax.config.jax_enable_compilation_cache,
                "jax_persistent_cache_min_compile_time_secs":
                    jax.config.jax_persistent_cache_min_compile_time_secs,
                "jax_persistent_cache_min_entry_size_bytes":
                    jax.config.jax_persistent_cache_min_entry_size_bytes,
            }
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_enable_compilation_cache", True)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        if not _listener_installed:
            try:
                from jax._src import monitoring
                monitoring.register_event_listener(_on_jax_event)
                _listener_installed = True
            except Exception:
                pass     # report simply lacks XLA-level counts
        _enabled_dir = d
    return d


def disable_persistent_cache():
    """Disarm the jax-level cache, restoring the config exactly as it
    was before arming — including any user/env-configured cache dir and
    the persistence thresholds (the zero-overhead bench assert and
    flag-toggle tests restore pristine state through this)."""
    global _enabled_dir, _prior_jax_config
    with _lock:
        if _enabled_dir is None:
            return
        import jax
        for k, v in (_prior_jax_config or
                     {"jax_compilation_cache_dir": None}).items():
            jax.config.update(k, v)
        _prior_jax_config = None
        _enabled_dir = None


# ---------------------------------------------------------------------------
# AOT executable store

def _fingerprint(lowered, label: str) -> str:
    """Content key: the lowered StableHLO + versions + backend.  Any
    change to the program (shapes, flags-driven fusions, shardings,
    jax/jaxlib upgrade) changes the key — a stale executable can never
    be loaded for a different program."""
    import jax
    try:
        import jaxlib
        jl = getattr(jaxlib, "__version__", "?")
    except Exception:
        jl = "?"
    text = str(lowered.compiler_ir(dialect="stablehlo"))
    h = hashlib.sha256()
    h.update(text.encode())
    h.update(f"|{jax.__version__}|{jl}|{jax.default_backend()}|"
             f"{label}".encode())
    return h.hexdigest()[:24]


def _aot_path(d: str, label: str, key: str) -> str:
    safe = "".join(c if c.isalnum() or c in "-_." else "_"
                   for c in label)
    return os.path.join(d, "aot", f"{safe}-{key}.pdexec")


def _record(rec: dict):
    with _lock:
        _records.append(rec)
    # errors get their own counter — folding them into misses would make
    # dump()'s counters disagree with compile_report()'s hit/miss split
    _counter({"hit": "compile.aot_hits",
              "miss": "compile.aot_misses"}.get(rec.get("cache"),
                                                "compile.aot_errors")).inc()
    _emit("compile.program", rec)


def aot_compile(jitfn, args: tuple, label: str):
    """Lower `jitfn` for `args`, then load-or-compile the executable
    through the AOT store.  Returns the compiled callable, or None when
    the flag is unset or anything in the AOT path fails (callers fall
    back to the plain jitted function — the cache must never be able to
    break a step).  Every outcome lands in `compile_report()`."""
    d = cache_dir()
    if d is None:
        return None
    maybe_enable_persistent_cache()
    try:
        t0 = time.perf_counter()
        lowered = jitfn.lower(*args)
        trace_ms = (time.perf_counter() - t0) * 1e3
        key = _fingerprint(lowered, label)
        path = _aot_path(d, label, key)
        if os.path.exists(path):
            from jax.experimental import serialize_executable as se
            t0 = time.perf_counter()
            with open(path, "rb") as f:
                blob, in_tree, out_tree = pickle.load(f)
            compiled = se.deserialize_and_load(blob, in_tree, out_tree)
            load_ms = (time.perf_counter() - t0) * 1e3
            _record({"label": label, "key": key, "cache": "hit",
                     "trace_ms": round(trace_ms, 2),
                     "compile_ms": 0.0,
                     "load_ms": round(load_ms, 2), "path": path})
            return compiled
        t0 = time.perf_counter()
        compiled = lowered.compile()
        compile_ms = (time.perf_counter() - t0) * 1e3
        # the executable is in hand — its HBM accounting is free here
        # (the memory ledger's lazy providers exist for the plain-jit
        # path, which never surfaces a Compiled)
        try:
            from . import memledger
            memledger.capture(label, compiled)
        except Exception:
            pass
        try:
            from jax.experimental import serialize_executable as se
            payload = pickle.dumps(se.serialize(compiled), protocol=4)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + f".tmp.{os.getpid()}"
            with open(tmp, "wb") as f:          # atomic publish: a
                f.write(payload)                # concurrent reader never
            os.replace(tmp, path)               # sees a torn executable
        except Exception as e:                  # noqa: BLE001
            warnings.warn(f"compile cache: could not serialize "
                          f"{label!r} ({type(e).__name__}: {e}); "
                          "executable used un-persisted", RuntimeWarning)
        _record({"label": label, "key": key, "cache": "miss",
                 "trace_ms": round(trace_ms, 2),
                 "compile_ms": round(compile_ms, 2), "path": path})
        return compiled
    except Exception as e:                      # noqa: BLE001
        warnings.warn(f"compile cache: AOT path failed for {label!r} "
                      f"({type(e).__name__}: {e}); falling back to "
                      "plain jit", RuntimeWarning)
        _record({"label": label, "cache": "error",
                 "error": f"{type(e).__name__}: {e}"})
        return None


def aot_for(store: Dict[Any, Any], kind: str, jitfn, args: tuple,
            batch_vals, label: str, mesh=None):
    """The trainers' shared AOT swap-in: unset flag → ONE dict lookup
    and the retracing jit runs untouched; armed → the step is lowered
    once per (kind, batch-aval signature), the compiled executable is
    served from (or published to) the store, and `store` memoizes it —
    a batch shape change simply compiles a second entry.  `mesh` wraps
    the lowering so shardings resolve exactly as the jit path's
    would."""
    if cache_dir() is None:
        return jitfn
    sig = (kind,) + tuple((tuple(b.shape), str(b.dtype))
                          for b in batch_vals)
    fn = store.get(sig)
    if fn is None:
        if mesh is not None:
            with mesh:
                fn = aot_compile(jitfn, args, label) or jitfn
        else:
            fn = aot_compile(jitfn, args, label) or jitfn
        store[sig] = fn
    return fn


def compile_report() -> dict:
    """Per-program AOT records + process-wide XLA-cache counters —
    trace/compile ms and hit/miss per program, so cold-start cost is a
    number, not a log line."""
    with _lock:
        programs = list(_records)
    hits = sum(1 for r in programs if r.get("cache") == "hit")
    misses = sum(1 for r in programs if r.get("cache") == "miss")
    return {
        "dir": _enabled_dir or cache_dir(),
        "programs": programs,
        "aot_hits": hits,
        "aot_misses": misses,
        "hit_rate": round(hits / (hits + misses), 3)
        if (hits + misses) else None,
        "xla_cache": dict(_xla_counts),
        "trace_ms_total": round(sum(r.get("trace_ms", 0.0)
                                    for r in programs), 2),
        "compile_ms_total": round(sum(r.get("compile_ms", 0.0)
                                      for r in programs), 2),
    }


def clear_report():
    with _lock:
        _records.clear()
    _xla_counts["hits"] = 0
    _xla_counts["misses"] = 0
