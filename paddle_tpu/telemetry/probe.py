"""Always-on step-phase probe — tools/profile_mfu's fwd/bwd/opt
decomposition generalized into a cheap path every trainer can afford.

profile_mfu re-compiles the step in nested pieces and times each with
median-of-reps — the honest decomposition, but too expensive to run per
step.  The always-on shape:

  * ONCE per trainer (first step with a sink attached), build and time
    a forward-only and a forward+backward jit over the live params —
    two small extra compiles, results cached on the trainer;
  * EVERY step, the trainer measures wall_ms around its (single,
    unchanged) compiled call and attaches the cached fwd/bwd split plus
    ``opt_ms = wall - fwdbwd`` — so each step event carries the full
    phase picture at the cost of two perf_counter() calls.

The probe is pure w.r.t. training state: it never calls the real step
(no optimizer advance, no RNG draw, no donated-buffer consumption) and
the trainer's own program is untouched — with no sink attached none of
this runs (the zero-overhead contract bench.py asserts).
"""
from __future__ import annotations

import time
from typing import Callable, Optional

import numpy as np

__all__ = ["phase_probe", "trainer_phases"]


def _sync():
    import jax
    import jax.numpy as jnp
    _ = np.asarray(jax.device_get(jnp.zeros(()) + 0))


def _timed(fn, inner: int = 2) -> float:
    """One warmup (compile) + `inner` timed calls; returns ms/call."""
    fn()
    _sync()
    t0 = time.perf_counter()
    for _ in range(inner):
        fn()
    _sync()
    return (time.perf_counter() - t0) / inner * 1e3


def phase_probe(model, batch_vals, loss_fn: Optional[Callable] = None,
                inner: int = 2) -> dict:
    """Time forward-only and forward+backward jits of `model` over
    `batch_vals` (= (*inputs, labels) jax arrays).  Returns
    {fwd_ms, bwd_ms, fwdbwd_ms, n_params}; the caller derives
    opt_ms = step_wall - fwdbwd per step."""
    import jax
    from ..jit import _swapped_state
    from ..framework.tensor import Tensor

    sd = model.state_dict()
    names = list(sd)
    vals = [sd[n]._value for n in names]
    n_params = sum(int(np.prod(sd[n].value.shape))
                   for n, _ in model.named_parameters())

    def loss_of(param_vals, *batch):
        with _swapped_state(model, names, list(param_vals)):
            out = model(*[Tensor(b) for b in batch[:-1]])
            if loss_fn is not None:
                loss = loss_fn(out, Tensor(batch[-1]))
            else:
                loss = model.compute_loss(out, Tensor(batch[-1]))
        return loss._value if isinstance(loss, Tensor) else loss

    fwd = jax.jit(loss_of)
    fwdbwd = jax.jit(lambda pv, *b: jax.value_and_grad(loss_of)(pv, *b))

    t_fwd = _timed(lambda: fwd(vals, *batch_vals), inner)
    t_fb = _timed(lambda: fwdbwd(vals, *batch_vals), inner)
    return {"fwd_ms": round(t_fwd, 3),
            "bwd_ms": round(max(t_fb - t_fwd, 0.0), 3),
            "fwdbwd_ms": round(t_fb, 3),
            "n_params": n_params}


def trainer_phases(trainer, batch_vals, loss_fn=None) -> Optional[dict]:
    """Cached phase decomposition for a trainer object: computed on the
    first call (while a sink is live), reused for every subsequent step
    event.  A probe failure is cached too — one warning's worth of
    cost, never a per-step retry loop."""
    from .registry import config
    if not config("step_phases"):
        return None
    cached = getattr(trainer, "_tel_phases", None)
    if cached is not None:
        return cached if cached else None      # {} = earlier failure
    try:
        phases = phase_probe(trainer.model, batch_vals, loss_fn=loss_fn)
    except Exception as e:                     # noqa: BLE001
        import warnings
        warnings.warn(f"telemetry phase probe failed for "
                      f"{type(trainer).__name__} "
                      f"({type(e).__name__}: {e}); step events will "
                      "carry wall_ms only", RuntimeWarning)
        trainer._tel_phases = {}
        return None
    trainer._tel_phases = phases
    return phases
