"""In-step numerics telemetry — per-layer grad/param/update norms and
the first-nonfinite-layer index, computed IN-GRAPH from the grads the
train step already materialized (ISSUE 14's numerics plane: the loop
stops being numerically blind between "loss is finite" and "loss is
NaN" — when a step goes bad, the event names the layer that went bad
first).

Cost contract (the plane's usual shape, but note the flag is a
PROGRAM switch, not a host switch):

  * ``FLAGS_numerics_stats`` is read at trainer BUILD time, exactly
    like ``FLAGS_skip_nonfinite_steps``: off (the default), the
    compiled step is byte-identical to a numerics-free build
    (bench-asserted alongside the other telemetry flags); on, the step
    additionally returns one small stats pytree — one fused reduction
    per layer bundle over the already-materialized grads/params/new
    params, no extra forward or backward pass, donation contracts
    untouched.
  * The HOST half (`record`) emits `train.numerics` events and feeds
    the registry histograms; with no sink attached the emit is the
    usual single truthiness check.  A detected nonfinite bundle also
    emits `train.anomaly` — the flight recorder's nonfinite-step
    trigger — and returns the offending layer's name so the trainers
    hand it to :class:`StepAnomalyGuard` (an abort-after-bad-steps
    report then names the first offending layer, not just the loss).

Layer bundles: parameters group by the first NUMERIC path component of
their state-dict name ("layers.3.attn.q_proj.weight" → "layers.3"),
falling back to the leading component ("fc.weight" → "fc") — the same
model-structure vocabulary the cost ledger's scope census uses, derived
from names instead of HLO metadata.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["enabled", "bundles_of", "graph_stats", "record", "reset"]

# trainer labels whose bundle list already rode an event: the list is
# positional metadata, identical every step — emitting it on every
# event would dominate a deep model's step log, so it rides the FIRST
# train.numerics event per label (consumers index positionally after
# that; the nonfinite layer is always resolved by name in-event)
_announced: set = set()


def reset():
    """Forget which labels announced their bundles (test isolation —
    telemetry.reset() calls this)."""
    _announced.clear()


def enabled() -> bool:
    """FLAGS_numerics_stats — trainers consult this at BUILD time (a
    mid-process toggle takes effect at the next trainer build, the
    skip-step guard's documented behavior)."""
    from ..framework.flags import get_flag
    return bool(get_flag("numerics_stats"))


def bundles_of(names: Sequence[str]) -> Tuple[List[str], List[int]]:
    """Group parameter names into layer bundles.

    Returns ``(labels, assign)``: bundle labels in first-seen order and
    the per-parameter bundle index.  A name's bundle is its path up to
    (and including) the first numeric component ("layers.3"), else its
    leading component ("fc"), else the name itself.
    """
    labels: List[str] = []
    index: Dict[str, int] = {}
    assign: List[int] = []
    for n in names:
        parts = n.split(".")
        label = None
        for i, p in enumerate(parts[:-1]):
            if p.isdigit():
                label = ".".join(parts[:i + 1])
                break
        if label is None:
            label = parts[0] if len(parts) > 1 else n
        if label not in index:
            index[label] = len(labels)
            labels.append(label)
        assign.append(index[label])
    return labels, assign


def graph_stats(assign: Sequence[int], n_bundles: int, param_vals,
                grads, new_params) -> dict:
    """The in-graph reduction: per-bundle grad-norm / param-norm /
    update-ratio vectors (shape [n_bundles], fp32) plus the first
    bundle index whose grad went nonfinite (int32, -1 = all finite).

    Traced inside the step function AFTER the optimizer update, from
    values the program already holds — per-parameter sum-of-squares
    folded into one scalar per bundle (XLA fuses the chain), so the
    numerics plane adds reductions, never a second fwd/bwd.
    """
    import jax.numpy as jnp

    def _sumsq(x):
        return jnp.sum(jnp.square(x.astype(jnp.float32)))

    g2 = [jnp.float32(0.0)] * n_bundles
    p2 = [jnp.float32(0.0)] * n_bundles
    u2 = [jnp.float32(0.0)] * n_bundles
    for i, (p, g, np_) in enumerate(zip(param_vals, grads, new_params)):
        b = assign[i]
        g2[b] = g2[b] + _sumsq(g)
        p2[b] = p2[b] + _sumsq(p)
        u2[b] = u2[b] + _sumsq(np_.astype(jnp.float32)
                               - p.astype(jnp.float32))
    return stats_from_sumsq(jnp.stack(g2), jnp.stack(p2), jnp.stack(u2))


def stats_from_sumsq(grad_sq, param_sq, update_sq) -> dict:
    """Per-bundle sum-of-squares vectors → the stats pytree the step
    returns.  Shared with the offload pipeline, whose backward scan
    accumulates the per-layer sums itself (one ys entry per layer)."""
    import jax.numpy as jnp
    eps = jnp.float32(1e-12)
    grad_norm = jnp.sqrt(grad_sq)
    param_norm = jnp.sqrt(param_sq)
    # update/param ratio: the "is the step size sane" signal (LR sweeps
    # and divergence both show here before the loss does)
    update_ratio = jnp.sqrt(update_sq) / (param_norm + eps)
    bad = ~jnp.isfinite(grad_sq)
    first_nonfinite = jnp.where(jnp.any(bad),
                                jnp.argmax(bad).astype(jnp.int32),
                                jnp.int32(-1))
    return {"grad_norm": grad_norm, "param_norm": param_norm,
            "update_ratio": update_ratio,
            "first_nonfinite": first_nonfinite}


def record(label: str, step0: int, k: int, bundles: Sequence[str],
           stats, extra: Optional[dict] = None) -> Optional[str]:
    """HOST half: publish one compiled call's numerics stats.

    `stats` is the step's returned pytree — per-bundle vectors for a
    single step, or stacked [K, n_bundles] vectors from a fused
    multi-step scan.  Emits `train.numerics` for the LAST step of the
    window (the trend sample) and, when any step saw a nonfinite
    bundle, for the FIRST bad step too — plus the `train.anomaly`
    trigger naming the first offending layer.  Returns that layer name
    (or None) so the caller can feed StepAnomalyGuard.

    `step0` is the optimizer step count AFTER the call (the trainers'
    convention); inner step i of the window is step0 - k + 1 + i.
    """
    import numpy as np
    from .registry import counter, emit, histogram

    gn = np.atleast_2d(np.asarray(stats["grad_norm"]))
    pn = np.atleast_2d(np.asarray(stats["param_norm"]))
    ur = np.atleast_2d(np.asarray(stats["update_ratio"]))
    fi = np.atleast_1d(np.asarray(stats["first_nonfinite"]))
    k = max(1, int(k))
    bundles = list(bundles)

    def _fields(i, announce=False):
        f = {"trainer": label, "step": int(step0 - k + 1 + i),
             "grad_norm": [round(float(v), 6) for v in gn[i]],
             "param_norm": [round(float(v), 6) for v in pn[i]],
             "update_ratio": [round(float(v), 6) for v in ur[i]],
             "first_nonfinite": int(fi[i])}
        if announce:
            f["bundles"] = bundles
        if int(fi[i]) >= 0:
            f["first_nonfinite_layer"] = bundles[int(fi[i])]
        if extra:
            f.update(extra)
        return f

    announce = label not in _announced
    _announced.add(label)
    bad_layer = None
    bad_steps = [i for i in range(len(fi)) if int(fi[i]) >= 0]
    first_bad = bad_steps[0] if bad_steps else None
    if bad_steps:
        bad_layer = bundles[int(fi[first_bad])]
        counter("numerics.nonfinite_steps").inc(len(bad_steps))
        emit("train.numerics", _fields(first_bad, announce=announce))
        announce = False
        # the flight recorder's nonfinite-step trigger: one compact
        # event naming the layer that went bad first
        emit("train.anomaly", trainer=label,
             step=int(step0 - k + 1 + first_bad), layer=bad_layer,
             source="numerics")
    last = len(fi) - 1
    if last != first_bad:           # trend sample, unless already sent
        emit("train.numerics", _fields(last, announce=announce))
    # registry histograms always accumulate (dump() carries the trend
    # even when no sink ever ran): the global grad norm and the worst
    # update ratio of the window's last step
    if np.all(np.isfinite(gn[last])):
        histogram("numerics.grad_norm").observe(
            float(np.sqrt(np.sum(gn[last] ** 2))))
    if ur[last].size and np.all(np.isfinite(ur[last])):
        histogram("numerics.update_ratio").observe(float(np.max(ur[last])))
    return bad_layer
