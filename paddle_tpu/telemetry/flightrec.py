"""Incident flight recorder — a bounded in-memory ring of recent
telemetry events that, when an anomaly trigger fires, dumps a
self-contained *incident bundle* directory explaining the detection
(ISSUE 14's tentpole: the r14 fleet plane and r16 cost ledger DETECT
drift/stragglers/hangs/nonfinite steps; this captures the *why* so
nobody has to re-run under a profiler and hope it reproduces).

Cost contract (the plane's usual shape):

  * The recorder is a regular telemetry sink — it only ever sees
    events that were already being emitted, so with it attached the
    compiled train/serve programs stay byte-identical (bench-asserted)
    and the per-event cost is one deque append + one set lookup.
  * A TRIGGER event (`perf.drift`, `fleet.straggler`, `fleet.desync`,
    `serve.hung`, `watchdog.timeout`, `fault.hit`, `train.anomaly`)
    dumps a bundle — rate-limited PER TRIGGER KIND
    (``FLAGS_flightrec_interval_s``), with bounded retention
    (``FLAGS_flightrec_keep`` newest bundles kept), written crash-safe
    via the r9 tmp+rename idiom (a bundle directory either exists
    complete or not at all).
  * A dump failure (disk full, race) is counted, never raised — a
    raising sink would be detached by the bus, losing the recorder.

Bundle layout (rendered by `tools/incident_report.py`)::

    incident-000001-perf-drift/
      manifest.json     kind, trigger ts, ring size, file list, rank
      trigger.json      the trigger event itself
      events.jsonl      the ring's recent events (JSONL, oldest first)
      trace.json        the same window as a chrome-trace slice
      memory.json       telemetry.memledger.snapshot()
      cost.json         telemetry.costledger.snapshot()
      fingerprint.json  resolved FLAGS + the r16 capture-id env
                        fingerprint (the perf sentry's match key)
      profile/          (optional) jax.profiler trace of the next K
                        steps AFTER the trigger — the post-anomaly
                        device timeline (``FLAGS_flightrec_profile_steps``;
                        capability-gated, no-op where unsupported)

Zero-config: a process launched with ``FLAGS_flightrec_dir`` in its
environment arms the recorder at import (the compile-cache idiom);
`attach()` arms it programmatically.
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..framework.flags import define_flag, get_flag
from .registry import add_sink, counter as _counter, emit as _emit, \
    rank_info, remove_sink

__all__ = ["FlightRecorder", "TRIGGER_EVENTS", "attach", "attached",
           "detach", "restore", "maybe_attach", "env_fingerprint",
           "capture_id", "reset"]

define_flag("flightrec_dir", "",
            "incident-bundle directory arming the flight recorder at "
            "import (a relaunched worker records from its first "
            "event); empty leaves the recorder detached — attach() "
            "arms it programmatically")
define_flag("flightrec_ring", 512,
            "events retained in the flight recorder's in-memory ring "
            "(the bundle's recent-history window)")
define_flag("flightrec_keep", 8,
            "incident bundles retained on disk; older bundles are "
            "deleted oldest-first after each dump")
define_flag("flightrec_interval_s", 60.0,
            "minimum seconds between bundles of the SAME trigger kind "
            "(a persistent drift or a straggler storm produces one "
            "bundle, not one per poll); suppressed triggers are "
            "counted, and a different kind dumps immediately")
define_flag("flightrec_profile_steps", 0,
            "arm a jax.profiler programmatic trace into the bundle's "
            "profile/ dir for the next K train.step/serve.chunk events "
            "after a trigger — the POST-anomaly device timeline; 0 "
            "disables, and unsupported backends degrade to a no-op")

# trigger event -> bundle kind (the rate-limit key); every detection
# event the observability planes emit lands here
TRIGGER_EVENTS = ("perf.drift", "fleet.straggler", "fleet.desync",
                  "serve.hung", "watchdog.timeout", "fault.hit",
                  "train.anomaly")

# step-shaped events that advance (and close) an armed post-trigger
# profiler window
_STEP_EVENTS = ("train.step", "serve.chunk")


# ---------------------------------------------------------------------------
# env fingerprint (shared with bench.py — the r16 capture-id contract:
# perf records compare only between identical fingerprints, and an
# incident bundle carries the same identity so a rendered incident can
# be matched against the BENCH baselines it drifted from)

_FINGERPRINT_FLAGS = (
    "FLAGS_fused_ce", "FLAGS_bf16_adamw_moments",
    "FLAGS_weight_only_dtype", "FLAGS_weight_only_group_size",
    "FLAGS_kv_cache_dtype", "FLAGS_kv_page_size",
    "FLAGS_serve_spec_tokens", "FLAGS_serve_draft_layers",
)
_FINGERPRINT_ENVS = ("BENCH_BATCH", "BENCH_RECOMPUTE_LAYERS",
                     "BENCH_OFFLOAD_SIZE", "BENCH_OFFLOAD_PREFETCH",
                     "BENCH_LONGCTX_SEQ", "BENCH_LONGCTX_REMAT",
                     "BENCH_UNET_DTYPE", "PEAK_FLOPS")


def env_fingerprint(flags=_FINGERPRINT_FLAGS,
                    envs=_FINGERPRINT_ENVS) -> dict:
    """Environment fingerprint (ISSUE 12): jax/jaxlib versions,
    backend + device kind, and the metric-relevant flags/envs.  THE one
    derivation — bench.py's capture lines and the incident bundles
    share it, so their capture ids agree."""
    fp = {}
    try:
        import jax
        import jaxlib
        fp["jax"] = jax.__version__
        fp["jaxlib"] = jaxlib.__version__
        fp["backend"] = jax.default_backend()
        fp["device"] = jax.devices()[0].device_kind
    except Exception:
        pass
    try:
        from ..framework.flags import get_flags
        fp["flags"] = {k: v for k, v in sorted(
            get_flags(list(flags)).items())}
    except Exception:
        pass
    fp["env"] = {k: os.environ[k] for k in envs if k in os.environ}
    return fp


def capture_id(fp: Optional[dict] = None) -> str:
    """Stable id of the env fingerprint (BENCH_CAPTURE_ID overrides):
    the perf sentry's match key."""
    if "BENCH_CAPTURE_ID" in os.environ:
        return os.environ["BENCH_CAPTURE_ID"]
    import hashlib
    blob = json.dumps(fp if fp is not None else env_fingerprint(),
                      sort_keys=True).encode()
    return hashlib.sha1(blob).hexdigest()[:12]


# ---------------------------------------------------------------------------
# the recorder

class FlightRecorder:
    """The sink.  Attach beside (or instead of) a JSONL log::

        rec = telemetry.flightrec.attach("incidents/")
        ... anomaly fires ...
        rec.bundles()   # -> ["incidents/incident-000001-perf-drift"]
    """

    def __init__(self, dir_path: Optional[str] = None,
                 ring: Optional[int] = None,
                 keep: Optional[int] = None,
                 interval_s: Optional[float] = None,
                 profile_steps: Optional[int] = None):
        self.dir = dir_path or get_flag("flightrec_dir") or "incidents"
        self._ring: deque = deque(
            maxlen=max(8, int(ring if ring is not None
                              else get_flag("flightrec_ring") or 512)))
        self.keep = max(1, int(keep if keep is not None
                               else get_flag("flightrec_keep") or 8))
        self.interval_s = float(
            interval_s if interval_s is not None
            else get_flag("flightrec_interval_s") or 0.0)
        self._profile_steps = int(
            profile_steps if profile_steps is not None
            else get_flag("flightrec_profile_steps") or 0)
        self._lock = threading.Lock()
        self._last_dump: Dict[str, float] = {}   # kind -> monotonic ts
        self.suppressed: Dict[str, int] = {}     # kind -> rate-limited
        self.errors = 0
        self._seq = self._next_seq()
        self._profile_left = 0
        self._profile_active = False
        self._profile_ok = True     # flips False on the first failure

    # -- sink protocol -----------------------------------------------------
    def record(self, rec: dict):
        ev = rec.get("event")
        with self._lock:
            self._ring.append(rec)
        if self._profile_active and ev in _STEP_EVENTS:
            self._profile_tick()
        if ev in TRIGGER_EVENTS:
            # dumps must never raise into the bus — a raising sink is
            # detached, and losing the recorder on a full disk is the
            # one failure mode this sink cannot afford
            try:
                self._trigger(dict(rec))
            except Exception:       # noqa: BLE001
                self.errors += 1
                _counter("flightrec.errors").inc()

    def flush(self):
        pass

    def close(self):
        self._stop_profile()

    # -- trigger path ------------------------------------------------------
    def _trigger(self, rec: dict):
        kind = rec["event"]
        now = time.monotonic()
        with self._lock:
            last = self._last_dump.get(kind)
            if (last is not None and self.interval_s > 0
                    and now - last < self.interval_s):
                self.suppressed[kind] = self.suppressed.get(kind, 0) + 1
                _counter("flightrec.suppressed").inc()
                return
            # claim the window now (a concurrent same-kind trigger must
            # not double-dump) ...
            self._last_dump[kind] = now
            ring = list(self._ring)
            seq = self._seq = self._seq + 1
        try:
            path = self._dump(seq, kind, rec, ring)
        except Exception:
            # ... but a FAILED dump releases the claim: a full disk
            # must not eat the whole interval's re-triggers — edge-
            # triggered detections (perf.drift) may never fire again
            with self._lock:
                if self._last_dump.get(kind) == now:
                    del self._last_dump[kind]
            raise
        _counter("flightrec.bundles").inc()
        _emit("flightrec.bundle", kind=kind, path=path, events=len(ring))
        self._prune()
        if self._profile_steps > 0:
            self._start_profile(path)

    def _next_seq(self) -> int:
        """Resume numbering past existing bundles so a relaunched
        worker never collides with (or reorders) its predecessor's."""
        seq = 0
        try:
            for name in os.listdir(self.dir):
                if name.startswith("incident-"):
                    try:
                        seq = max(seq, int(name.split("-")[1]))
                    except (IndexError, ValueError):
                        continue
        except OSError:
            pass
        return seq

    def _dump(self, seq: int, kind: str, trigger: dict,
              ring: List[dict]) -> str:
        from .exporters import _jsonable, chrome_event
        from . import costledger, memledger
        info = rank_info()
        # rank rides the NAME (not just the manifest): fleet workers
        # sharing one FLAGS_flightrec_dir must never collide on a seq
        name = (f"incident-{seq:06d}-r{info[0] if info else 0}-"
                f"{kind.replace('.', '-')}")
        final = os.path.join(self.dir, name)
        tmp = os.path.join(self.dir, f".tmp-{name}-{os.getpid()}")
        os.makedirs(tmp, exist_ok=True)

        def _write(fname, obj):
            with open(os.path.join(tmp, fname), "w") as f:
                json.dump(obj, f, indent=1, default=_jsonable)

        _write("trigger.json", trigger)
        with open(os.path.join(tmp, "events.jsonl"), "w") as f:
            for r in ring:
                f.write(json.dumps(r, default=_jsonable) + "\n")
        _write("trace.json",
               {"traceEvents": [chrome_event(r) for r in ring]})
        # snapshots, not reports: resolution compiles, and a trigger
        # can fire from inside a train step — the bundle records what
        # the ledgers already know, never pays a compile to know more
        _write("memory.json", memledger.snapshot())
        _write("cost.json", costledger.snapshot())
        fp = env_fingerprint()
        flags = {}
        try:
            from ..framework.flags import known_flags
            flags = {"FLAGS_" + k: v["value"]
                     for k, v in sorted(known_flags().items())}
        except Exception:
            pass
        _write("fingerprint.json",
               {"capture_id": capture_id(fp), "env": fp,
                "flags": flags})
        # `info` from the top of _dump: name and manifest must agree
        _write("manifest.json", {
            "kind": kind, "ts": trigger.get("ts"), "seq": seq,
            "events": len(ring),
            "rank": info[0] if info else 0,
            "world": info[1] if info else 1,
            "files": ["manifest.json", "trigger.json", "events.jsonl",
                      "trace.json", "memory.json", "cost.json",
                      "fingerprint.json"],
        })
        # the r9 tmp+rename publish: the final name appears only once
        # every file inside is complete — a crash mid-dump leaves a
        # .tmp-* directory, never a half bundle that parses.  A name
        # collision (two same-rank processes sharing the dir) falls
        # back to a pid-suffixed name rather than dropping the bundle
        try:
            os.rename(tmp, final)
        except OSError:
            final = f"{final}-p{os.getpid()}"
            os.rename(tmp, final)
        return final

    def _prune(self):
        try:
            names = sorted(n for n in os.listdir(self.dir)
                           if n.startswith("incident-"))
        except OSError:
            return
        for name in names[:-self.keep] if len(names) > self.keep else []:
            try:
                shutil.rmtree(os.path.join(self.dir, name))
            except OSError:
                pass

    def bundles(self) -> List[str]:
        """Finalized bundle directories, oldest first."""
        try:
            return [os.path.join(self.dir, n)
                    for n in sorted(os.listdir(self.dir))
                    if n.startswith("incident-")]
        except OSError:
            return []

    # -- post-trigger profiler window (capability-gated) -------------------
    def _start_profile(self, bundle_dir: str):
        if not self._profile_ok or self._profile_active:
            return                  # one window at a time
        try:
            import jax
            jax.profiler.start_trace(os.path.join(bundle_dir, "profile"))
            self._profile_left = self._profile_steps
            self._profile_active = True
        except Exception:           # noqa: BLE001 — unsupported backend
            self._profile_ok = False

    def _profile_tick(self):
        self._profile_left -= 1
        if self._profile_left <= 0:
            self._stop_profile()

    def _stop_profile(self):
        if not self._profile_active:
            return
        self._profile_active = False
        self._profile_left = 0
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception:           # noqa: BLE001 — backend lost it
            pass


# ---------------------------------------------------------------------------
# module-level attach (one recorder per process, the sink registry's
# compile-cache idiom)

_RECORDER: Optional[FlightRecorder] = None


def attach(dir_path: Optional[str] = None, **kw) -> FlightRecorder:
    """Create AND attach the process flight recorder (idempotent: a
    second attach returns the live one — and WARNS if it asked for a
    different directory, since its bundles would land elsewhere).
    Detach with `detach()`."""
    global _RECORDER
    if _RECORDER is None:
        _RECORDER = add_sink(FlightRecorder(dir_path, **kw))
    elif dir_path and dir_path != _RECORDER.dir:
        import warnings
        warnings.warn(
            f"flightrec.attach({dir_path!r}): a recorder is already "
            f"attached at {_RECORDER.dir!r}; returning it (use "
            "detach()/restore() to scope a temporary recorder)",
            RuntimeWarning)
    return _RECORDER


def attached() -> Optional[FlightRecorder]:
    return _RECORDER


def detach() -> Optional[FlightRecorder]:
    """Detach and RETURN the process recorder (so a bench/test scope
    can `restore()` it after running with its own temporary one — a
    production recorder armed via FLAGS_flightrec_dir must survive a
    bench run's asserts)."""
    global _RECORDER
    rec = _RECORDER
    if rec is not None:
        remove_sink(rec, close=False)
        _RECORDER = None
    return rec


def restore(recorder: Optional[FlightRecorder]
            ) -> Optional[FlightRecorder]:
    """Re-attach a recorder previously returned by `detach()` (no-op
    on None).  The save/restore pair bench.py's asserts use."""
    global _RECORDER
    if recorder is None:
        return None
    detach()
    _RECORDER = add_sink(recorder)
    return recorder


def maybe_attach() -> Optional[FlightRecorder]:
    """Arm the recorder iff FLAGS_flightrec_dir is set (called at
    telemetry import — a relaunched worker records from its first
    event).  Unset: one flag lookup."""
    if get_flag("flightrec_dir"):
        return attach()
    return None


def reset():
    """Drop the process recorder (test isolation; telemetry.reset()
    already detached it as a sink — this clears the module global so
    the next attach() builds fresh)."""
    global _RECORDER
    if _RECORDER is not None:
        try:
            _RECORDER.close()
        except Exception:
            pass
    _RECORDER = None
