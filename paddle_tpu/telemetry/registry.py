"""MetricsRegistry + the in-process event bus.

Reference: `python/paddle/profiler/` keeps host-side instrumentation in a
module-global event list guarded by a recording flag; fleet-scale
operability needs the inverse shape — ONE always-importable plane that
every producer (trainers, serving batcher, watchdog, fault registry,
checkpoint runtime, data loader) publishes into, with the cost model of
the analysis subsystem: **near-zero when nothing is attached**.

Cost contract (bench-asserted, like analysis/fault):

  * `emit()` with no sink attached is one module-global truthiness
    check and a return — no dict building, no timestamps, no locking.
  * `span()` with no sink attached returns a shared no-op context
    manager — no allocation.
  * Counters/gauges always accumulate (a few ns: one dict lookup and an
    int add) so `telemetry.dump()` can snapshot lifetime totals even
    when no sink ever ran; histograms keep a bounded reservoir.
  * Nothing here ever touches jax or the compiled step — the plane is
    host-side only, so arming/disarming sinks cannot change a program
    (bench asserts byte-identical HLO across an attach/detach cycle).

Sinks are objects with a ``record(rec: dict)`` method (and optionally
``flush()``/``close()``); see exporters.py.  A raising sink is detached
rather than allowed to kill a train step.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "registry", "counter", "gauge", "histogram",
           "add_sink", "remove_sink", "sinks", "active", "emit", "span",
           "configure", "config", "reset",
           "set_rank", "rank_info", "percentile_of", "percentiles_of",
           "summary_of"]


# one lock for all instrument mutation: `value += n` is LOAD/ADD/STORE
# under the GIL, and the producers span threads (loader prefetch,
# watchdog monitor, checkpoint writer) — a lost increment would flake
# exactly the count-pinning regression tests this plane feeds
_METRICS_LOCK = threading.Lock()


def percentile_of(values, q) -> float:
    """One percentile over a value list (key-naming handled here —
    fractional q like 99.9 works)."""
    key = f"p{int(q) if float(q).is_integer() else q}"
    return percentiles_of(values, (q,))[key]


def percentiles_of(values, qs=(50, 90, 99)) -> Dict[str, float]:
    """Nearest-rank percentiles over a value list — THE one percentile
    derivation (Histogram.percentiles, stats() blocks and the report
    CLIs all call this; the rounding convention changes in one place)."""
    out = {f"p{int(q) if float(q).is_integer() else q}": 0.0
           for q in qs}
    if not values:
        return out
    xs = sorted(float(v) for v in values)
    for q in qs:
        k = min(len(xs) - 1,
                max(0, int(round(q / 100.0 * (len(xs) - 1)))))
        out[f"p{int(q) if float(q).is_integer() else q}"] = xs[k]
    return out


def summary_of(values, qs=(50, 90, 99)) -> Dict[str, float]:
    """Count + TRUE min/max + nearest-rank percentiles over a value
    list — THE one window-summary derivation (ISSUE 14: the serving
    latency blocks and the report CLIs read through here).  The
    percentiles come from whatever window the caller kept, but min/max
    are exact over it — reservoir-style sampling upstream of this call
    is what loses the extreme straggler/TTFT outliers an incident
    investigation needs, so keep the raw window and summarize HERE."""
    vals = [float(v) for v in values]
    out = {"count": len(vals),
           "min": min(vals) if vals else 0.0,
           "max": max(vals) if vals else 0.0}
    out.update(percentiles_of(vals, qs))
    return out


class Counter:
    """Monotonic int counter."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1):
        with _METRICS_LOCK:
            self.value += n
            return self.value


class Gauge:
    """Last-value-wins float."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, v: float):
        with _METRICS_LOCK:
            self.value = float(v)
            return self.value


class Histogram:
    """Running count/sum/min/max plus a bounded reservoir of recent
    observations (enough for p50/p99 over the window without unbounded
    growth in a long-lived server — same discipline as the serving
    batcher's chunk-time deque)."""

    __slots__ = ("name", "count", "total", "min", "max", "_window",
                 "_cap", "_i")

    def __init__(self, name: str, window: int = 1024):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self._window: List[float] = []
        self._cap = window
        self._i = 0

    def observe(self, v: float):
        v = float(v)
        with _METRICS_LOCK:
            self.count += 1
            self.total += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            if len(self._window) < self._cap:
                self._window.append(v)
            else:                   # ring overwrite: keep the recent cap
                self._window[self._i] = v
                self._i = (self._i + 1) % self._cap

    def percentile(self, q: float) -> float:
        key = f"p{int(q) if float(q).is_integer() else q}"
        return self.percentiles((q,))[key]

    def percentiles(self, qs=(50, 90, 99)) -> Dict[str, float]:
        """{pN: value} over the reservoir window — consumers (dump(),
        stats() blocks, the report CLIs) read these instead of
        re-deriving percentiles from raw reservoir dumps."""
        with _METRICS_LOCK:
            window = list(self._window)
        return percentiles_of(window, qs)

    def summary(self) -> dict:
        if not self.count:
            return {"count": 0}
        pct = self.percentiles((50, 90, 99))
        return {"count": self.count,
                "sum": round(self.total, 4),
                "min": round(self.min, 4),
                "max": round(self.max, 4),
                "p50": round(pct["p50"], 4),
                "p90": round(pct["p90"], 4),
                "p99": round(pct["p99"], 4)}


class MetricsRegistry:
    """Name → instrument store.  get-or-create accessors are the hot
    path, so instruments are cached in plain dicts; the lock only guards
    creation (worker threads — loader prefetch, watchdog monitor,
    checkpoint writer — all publish here)."""

    def __init__(self):
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._hists: Dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name))
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(name))
        return g

    def histogram(self, name: str, window: int = 1024) -> Histogram:
        h = self._hists.get(name)
        if h is None:
            with self._lock:
                h = self._hists.setdefault(name,
                                           Histogram(name, window))
        return h

    def dump(self) -> dict:
        return {
            "counters": {n: c.value for n, c in
                         sorted(self._counters.items())},
            "gauges": {n: g.value for n, g in sorted(self._gauges.items())},
            "histograms": {n: h.summary() for n, h in
                           sorted(self._hists.items())},
        }

    def reset(self):
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REGISTRY


def counter(name: str) -> Counter:
    return _REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return _REGISTRY.gauge(name)


def histogram(name: str, window: int = 1024) -> Histogram:
    return _REGISTRY.histogram(name, window)


# ---------------------------------------------------------------------------
# event bus

_SINKS: List = []           # truthiness of this list IS the fast path
_SINKS_LOCK = threading.Lock()

# fleet identity: (rank, world), stamped onto every emitted record once
# distributed.env (or telemetry.fleet.init_from_env) announces it.
# None until then — a single uninitialized process emits exactly the
# records it always did (readers treat a missing rank as rank 0).
_RANK: Optional[tuple] = None


def set_rank(rank: int, world: int = 1):
    """Announce this process's fleet identity.  From here on every
    emitted event carries `rank` (and `world` when > 1) so per-rank
    JSONL logs merge into one rank-laned timeline.  Called by
    distributed.env.init_parallel_env; idempotent."""
    global _RANK
    _RANK = (int(rank), max(1, int(world)))


def rank_info() -> Optional[tuple]:
    """(rank, world) once announced, else None (treat as (0, 1))."""
    return _RANK

# plane configuration — host-side behavior switches only (nothing here
# may change a compiled program):
#   step_phases: trainers attach the one-time fwd/bwd phase
#     decomposition to their step events while a sink is live (costs two
#     extra small compiles per trainer, once)
#   sync_steps: trainers block_until_ready the loss inside the step
#     span so wall_ms is exact step wall (default off: with donated
#     buffers steady-state dispatch wall tracks step wall, and a forced
#     sync costs a relay round trip per step on tunneled accelerators)
_CONFIG_DEFAULTS = {"step_phases": True, "sync_steps": False}
_CONFIG = dict(_CONFIG_DEFAULTS)


def configure(**kw):
    """Update plane config; unknown keys raise (typo'd switches must
    fail loudly, not silently do nothing)."""
    for k, v in kw.items():
        if k not in _CONFIG:
            raise KeyError(f"unknown telemetry config key {k!r}; "
                           f"known: {sorted(_CONFIG)}")
        _CONFIG[k] = v
    return dict(_CONFIG)


def config(key: str):
    return _CONFIG[key]


def add_sink(sink):
    """Attach a sink; returns it (so `s = add_sink(JsonlSink(p))`)."""
    with _SINKS_LOCK:
        if sink not in _SINKS:
            _SINKS.append(sink)
    return sink


def remove_sink(sink, close: bool = True):
    with _SINKS_LOCK:
        if sink in _SINKS:
            _SINKS.remove(sink)
    if close:
        try:
            sink.close()
        except Exception:
            pass


def sinks() -> list:
    return list(_SINKS)


def active() -> bool:
    """True iff at least one sink is attached — producers consult this
    before doing ANY per-event work beyond the check itself."""
    return bool(_SINKS)


def emit(event: str, fields: Optional[dict] = None, **kw):
    """Publish one event to every attached sink.  No sink → return
    immediately (the zero-overhead contract)."""
    if not _SINKS:
        return
    rec = {"ts": time.time(), "event": event}
    if fields:
        rec.update(fields)
    if kw:
        rec.update(kw)
    if _RANK is not None:
        # rank-aware records (ISSUE 10): every producer — trainers,
        # watchdog, fault registry, checkpoint runtime, serving — gets
        # the fleet identity for free, so no call site can forget it
        rec.setdefault("rank", _RANK[0])
        if _RANK[1] > 1:
            rec.setdefault("world", _RANK[1])
    for s in list(_SINKS):
        try:
            s.record(rec)
        except Exception as e:      # noqa: BLE001
            # a broken sink (disk full, closed file) must not take the
            # training loop down with it — detach (close=True attempts
            # a final flush of buffered lines; remove_sink swallows a
            # failing close) and SAY SO: a silently dying step log is
            # the failure mode this plane exists to prevent
            import warnings
            warnings.warn(
                f"telemetry: detaching sink {type(s).__name__} after "
                f"record() failed ({type(e).__name__}: {e}); events "
                "from here on are not exported to it", RuntimeWarning)
            remove_sink(s, close=True)


class _Span:
    __slots__ = ("event", "fields", "_t0")

    def __init__(self, event: str, fields: dict):
        self.event = event
        self.fields = fields
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = (time.perf_counter() - self._t0) * 1e3
        if exc_type is not None:
            # a raising body must be distinguishable from a clean one
            # in the trace (ISSUE 14): mark the span and RE-raise — an
            # incident bundle's timeline then shows the failing phase
            emit(self.event, self.fields, dur_ms=round(dur, 4),
                 error=exc_type.__name__)
        else:
            emit(self.event, self.fields, dur_ms=round(dur, 4))
        return False


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


def span(event: str, **fields):
    """Timed context manager: emits `event` with dur_ms on exit.  With
    no sink attached returns a shared no-op (no allocation)."""
    if not _SINKS:
        return _NOOP
    return _Span(event, fields)


def reset():
    """Detach every sink, clear the registry, drop the fleet identity
    and restore the default config (test isolation — the whole plane
    back to pristine)."""
    global _RANK
    for s in list(_SINKS):
        remove_sink(s)
    _REGISTRY.reset()
    _CONFIG.clear()
    _CONFIG.update(_CONFIG_DEFAULTS)
    _RANK = None
