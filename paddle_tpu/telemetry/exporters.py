"""Telemetry exporters: JSONL step log, Chrome-trace timeline, and the
in-memory sink tests and the profiler facade build on.

Reference: the profiler's `export_chrome_tracing` handler wrote a
`{"traceEvents": [...]}` document after a RECORD window closed; here
any sink can be attached/detached at any time and the trainers publish
continuously, so export is a property of the sink, not of a profiler
state machine.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
from typing import IO, List, Optional, Union

from .registry import add_sink

__all__ = ["JsonlSink", "ChromeTraceSink", "MemorySink",
           "attach_jsonl", "attach_chrome_trace", "chrome_event"]


class JsonlSink:
    """One JSON object per line per event — the fleet step log.  Each
    record is written (and flushed by default) as it arrives, so a
    preempted worker's log is complete up to its last event — the same
    torn-tail discipline as the checkpoint runtime.

    An `atexit` hook flushes whatever a `flush_every > 1` batch still
    buffers, so a SIGTERM drain (sys.exit path) or an uncaught crash
    loses nothing the process ever emitted — only a hard `os._exit`
    (mode=kill preemption) can truncate the tail.

    Size-capped rotation (ISSUE 14): under ``FLAGS_telemetry_max_log_mb``
    (or `max_mb`) a path-owned sink whose file crosses the cap rotates
    it to ``<path>.1`` (existing segments shift up: .1 -> .2, ...) and
    reopens a fresh file — a long-running job's log never grows one
    unbounded file, the atexit drain-flush keeps covering the LIVE
    segment, and `telemetry.fleet.merge_jsonl_traces` reads the
    rotated segments back oldest-first."""

    def __init__(self, path_or_file: Union[str, IO], flush_every: int = 1,
                 max_mb: Optional[float] = None):
        if hasattr(path_or_file, "write"):
            self._f = path_or_file
            self.path = getattr(path_or_file, "name", None)
            self._own = False
        else:
            self.path = path_or_file
            d = os.path.dirname(path_or_file)
            if d:
                os.makedirs(d, exist_ok=True)
            self._f = open(path_or_file, "a")
            self._own = True
        if max_mb is None:
            from ..framework.flags import get_flag
            max_mb = float(get_flag("telemetry_max_log_mb", 0.0) or 0.0)
        # rotation needs to own the file AND know its name
        self._max_bytes = int(max_mb * 1e6) \
            if (max_mb and self._own and self.path) else 0
        self._bytes = 0
        if self._max_bytes:
            try:
                self._bytes = os.path.getsize(self.path)
            except OSError:
                pass
        self._flush_every = max(1, int(flush_every))
        self._n = 0
        self._lock = threading.Lock()
        self._closed = False
        atexit.register(self._drain_flush)

    def record(self, rec: dict):
        line = json.dumps(rec, default=_jsonable)
        with self._lock:
            self._f.write(line + "\n")
            self._n += 1
            if self._n % self._flush_every == 0:
                self._f.flush()
            if self._max_bytes:
                self._bytes += len(line) + 1
                if self._bytes >= self._max_bytes:
                    self._rotate_locked()

    def _rotate_locked(self):
        """Shift <path>.i -> <path>.(i+1) (highest first), publish the
        live file as <path>.1, reopen fresh.  Called under self._lock;
        a rotation failure (permissions, races) keeps writing to the
        current file rather than losing events — and keeps the TRUE
        byte count, so the cap retries at the next record instead of
        granting another full segment of unbounded growth."""
        try:
            self._f.flush()
            self._f.close()
        except Exception:           # noqa: BLE001 — reopen below anyway
            pass
        rotated = True
        try:
            n = 1
            while os.path.exists(f"{self.path}.{n}"):
                n += 1
            for i in range(n, 1, -1):
                os.replace(f"{self.path}.{i - 1}", f"{self.path}.{i}")
            os.replace(self.path, f"{self.path}.1")
        except OSError:
            rotated = False
        self._f = open(self.path, "a")
        if rotated:
            self._bytes = 0
        else:
            try:
                self._bytes = os.path.getsize(self.path)
            except OSError:
                self._bytes = 0

    def flush(self):
        with self._lock:
            self._f.flush()

    def _drain_flush(self):
        # interpreter-exit path: never raise (the file may already be
        # gone), never double-close
        try:
            if not self._closed:
                self.flush()
        except Exception:
            pass

    def close(self):
        atexit.unregister(self._drain_flush)
        with self._lock:
            self._closed = True
            try:
                self._f.flush()
            finally:
                if self._own:
                    self._f.close()


def _jsonable(x):
    """Last-resort JSON coercion: numpy scalars/arrays and anything
    else stringify rather than kill the sink."""
    try:
        import numpy as np
        if isinstance(x, np.ndarray):
            return x.tolist()
        if isinstance(x, np.generic):
            return x.item()
    except Exception:
        pass
    return str(x)


def chrome_event(rec: dict, pid: Optional[int] = None,
                 tid: Optional[int] = None) -> dict:
    """One telemetry record → one chrome-trace event: ``dur_ms`` makes
    a complete ('X') slice ending at the record's ts, anything else an
    instant ('i').  THE conversion both the live ChromeTraceSink and
    the offline per-rank log merge (telemetry.fleet) share — the lane
    identity (pid) is the caller's choice: process id live, RANK in a
    merged fleet trace."""
    ts_us = rec.get("ts", 0.0) * 1e6
    name = rec.get("event", "event")
    pid = os.getpid() if pid is None else pid
    tid = threading.get_ident() if tid is None else tid
    args = {k: v for k, v in rec.items() if k not in ("ts", "event")}
    if "dur_ms" in rec:
        dur_us = float(rec["dur_ms"]) * 1e3
        return {"name": name, "ph": "X", "pid": pid, "tid": tid,
                "ts": ts_us - dur_us, "dur": dur_us, "args": args}
    return {"name": name, "ph": "i", "s": "p", "pid": pid,
            "tid": tid, "ts": ts_us, "args": args}


class ChromeTraceSink:
    """Collect events as a chrome://tracing / Perfetto timeline.

    Events carrying ``dur_ms`` become complete ('X') slices; everything
    else becomes an instant ('i') event.  ``save(path)`` (or close, when
    constructed with a path) writes the `{"traceEvents": [...]}` doc.
    Constructed with a path, an `atexit` hook saves it too, so a drain
    or crash exit still leaves the timeline on disk."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self.trace_events: List[dict] = []
        self._lock = threading.Lock()
        self._closed = False
        if path is not None:
            atexit.register(self._drain_save)

    def record(self, rec: dict):
        ev = chrome_event(rec)
        with self._lock:
            self.trace_events.append(ev)

    def save(self, path: Optional[str] = None) -> str:
        path = path or self.path
        if path is None:
            raise ValueError("ChromeTraceSink.save needs a path (none "
                             "given at construction)")
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with self._lock:
            doc = {"traceEvents": list(self.trace_events)}
        with open(path, "w") as f:
            json.dump(doc, f, default=_jsonable)
        return path

    def _drain_save(self):
        try:
            if not self._closed and self.path is not None:
                self.save()
        except Exception:
            pass

    def close(self):
        if self.path is not None:
            atexit.unregister(self._drain_save)
            self._closed = True
            self.save()


class MemorySink:
    """Record into a list — tests and the profiler summary view."""

    def __init__(self):
        self.records: List[dict] = []
        self._lock = threading.Lock()

    def record(self, rec: dict):
        with self._lock:
            self.records.append(rec)

    def close(self):
        pass


def attach_jsonl(path_or_file, flush_every: int = 1,
                 max_mb: Optional[float] = None) -> JsonlSink:
    """Create AND attach a JSONL sink; returns it (detach with
    `telemetry.remove_sink(sink)`)."""
    return add_sink(JsonlSink(path_or_file, flush_every, max_mb=max_mb))


def attach_chrome_trace(path: Optional[str] = None) -> ChromeTraceSink:
    """Create AND attach a chrome-trace sink; `remove_sink` (or
    `.save()`) writes the timeline."""
    return add_sink(ChromeTraceSink(path))
