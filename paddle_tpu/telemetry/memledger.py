"""HBM memory ledger — per-program byte accounting from XLA's own
`compiled.memory_analysis()` (ROADMAP item 5c: the SCALE/PROFILE peak-
HBM numbers were hand-derived because nothing in the repo ever asked
XLA; now every trainer and the serve step registers its program here
and `telemetry.memory_report()` answers with measured bytes).

Cost model (the plane's usual contract):

  * Trainers REGISTER a provider at first call — one `seen`-set check
    per step, one aval-ization (ShapeDtypeStructs, no live buffers
    pinned) on the first.  Registration never lowers, never compiles,
    never touches the step program (bench.py's byte-identical-HLO
    assert covers the armed plane).
  * RESOLUTION is lazy and explicit: `memory_report()` (or
    `analysis.lint_peak_hbm`) lowers+compiles each pending provider
    once and caches the stats — the cost is paid exactly when someone
    asks for the numbers, the way tools/profile_mfu pays for its phase
    probes.  The AOT path (FLAGS_compile_cache_dir) captures stats for
    free at its own `.lower()`/compile.
  * Labels are a small fixed space ("jit.TrainStep.step",
    "ShardedTrainStep.step", "serve_step.decode", ...): a new trainer
    REPLACES its label's entry, so a long test suite or notebook never
    grows the ledger past the program zoo's size.

Report shape (per program): argument/output/temp/alias/generated-code
bytes straight from CompiledMemoryStats, plus ``peak_bytes`` =
arguments + outputs + temps − aliased (donated buffers counted once —
the number to hold against device HBM) and its share of the device's
reported capacity when the backend exposes one.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

import numpy as np

__all__ = ["register", "note_jit", "capture", "memory_report",
           "snapshot", "device_hbm_bytes", "reset"]

_lock = threading.Lock()
_programs: Dict[str, dict] = {}     # label -> entry (insertion-ordered)


def _stats_from(compiled) -> dict:
    """CompiledMemoryStats -> plain byte dict (+ derived peak)."""
    ma = compiled.memory_analysis()
    stats = {
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
        "generated_code_bytes": int(ma.generated_code_size_in_bytes),
    }
    stats["peak_bytes"] = max(
        0, stats["argument_bytes"] + stats["output_bytes"]
        + stats["temp_bytes"] - stats["alias_bytes"])
    return stats


def register(label: str, provider: Callable[[], Any], meta:
             Optional[dict] = None):
    """Register a pending program under `label`; `provider()` must
    return a jax Compiled (anything with `.memory_analysis()`) when the
    ledger resolves.  Same label replaces — the ledger tracks the
    CURRENT program per label, not history."""
    with _lock:
        _programs[label] = {"label": label, "status": "pending",
                            "provider": provider,
                            "meta": dict(meta or {})}
    # the label now describes a NEW program — the cost ledger's
    # measured walls / entry for the old one must not leak onto it
    try:
        from . import costledger
        costledger.program_changed(label)
    except Exception:
        pass


def note_jit(owner, kind: str, jitfn, args: tuple, label: str,
             mesh=None, sig=None):
    """The trainers' one-line hook: on the first call of `kind` for
    this `owner`, aval-ize `args` (ShapeDtypeStructs — the ledger must
    not pin donated buffers) and register a provider that re-lowers the
    jitted step for those avals on demand.  Subsequent calls are one
    tuple build + set lookup.

    `sig` is a cheap retrace discriminator (the trainers pass their
    batch shapes): a call whose sig DIFFERS from the previous call's
    re-REGISTERS — the jit has retraced (e.g. run_steps at a new K),
    so the label must describe the CURRENT program and the cost
    ledger must drop the old program's measured walls, not mix them
    (tracking the last sig rather than a seen-set keeps an
    alternating-K workload honest too)."""
    last = owner.__dict__.setdefault("_memledger_sig", {})
    if kind in last and last[kind] == sig:
        return
    refreshed = kind in last
    last[kind] = sig
    if refreshed:
        # the call that triggers a retrace pays the XLA compile in its
        # own wall — step_event must treat it as cold for the cost
        # ledger's measured window, like every first use
        owner.__dict__.setdefault("_memledger_fresh", set()).add(kind)
    # remember the ledger label per program kind: step_event feeds the
    # cost ledger's measured walls by looking the label up here
    owner.__dict__.setdefault("_memledger_labels", {})[kind] = label
    import jax
    mesh_devs = None if mesh is None else set(np.asarray(mesh.devices).flat)

    def _aval_sharding(a):
        # carry each argument's sharding AND memory kind: a host-
        # offloaded trainer's pinned_host stacks must lower exactly as
        # placed, or the analysis counts them as device HBM — but an
        # UNCOMMITTED scalar (lr, step count) materialized on device 0
        # must NOT pin the aval there: under a size>1 mesh the live
        # call auto-places it, while a pinned aval makes the provider's
        # re-lower fail with incompatible-devices
        s = getattr(a, "sharding", None)
        if s is None or mesh_devs is None:
            return s
        try:
            return s if set(s.device_set) == mesh_devs else None
        except Exception:
            return None
    try:
        avals = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(
                a.shape, a.dtype, sharding=_aval_sharding(a)), args)
    except Exception:
        return                      # odd leaf: skip, never break a step

    def provider():
        import contextlib
        ctx = mesh if mesh is not None else contextlib.nullcontext()
        with ctx:
            return jitfn.lower(*avals).compile()
    register(label, provider)


def capture(label: str, compiled, meta: Optional[dict] = None):
    """Record stats from an ALREADY-compiled executable (the AOT path
    has one in hand at `.lower()` time — its memory accounting is
    free).  Failures record an error entry rather than raising."""
    try:
        stats = _stats_from(compiled)
    except Exception as e:          # noqa: BLE001
        with _lock:
            _programs[label] = {"label": label, "status": "error",
                                "error": f"{type(e).__name__}: {e}",
                                "meta": dict(meta or {})}
        return None
    entry = {"label": label, "status": "ok", "meta": dict(meta or {}),
             **stats}
    with _lock:
        _programs[label] = entry
    _publish(entry)
    _ingest_cost(label, compiled, meta)
    return entry


def _ingest_cost(label: str, compiled, meta=None):
    """Hand the in-hand executable to the compute cost ledger — the
    one Compiled serves both ledgers (costledger's zero-extra-compiles
    contract).  Never breaks the memory side."""
    try:
        from . import costledger
        costledger.ingest(label, compiled, meta=meta)
    except Exception:
        pass


def _publish(entry: dict):
    """mem.program event + counter — so a fleet JSONL log carries the
    ledger and fleet_report can render a memory section offline."""
    from .registry import counter as _counter, emit as _emit
    _counter("mem.programs").inc()
    _emit("mem.program",
          {k: v for k, v in entry.items() if k != "provider"})


def _resolve(entry: dict) -> dict:
    with _lock:
        # claim the provider atomically: two concurrent reports must
        # not both compile (or leave the loser seeing half a record)
        provider = entry.pop("provider", None)
    if provider is None:
        return entry
    try:
        compiled = provider()
        stats = _stats_from(compiled)
    except Exception as e:          # noqa: BLE001
        entry["status"] = "error"
        entry["error"] = f"{type(e).__name__}: {e}"
        return entry
    entry.update(stats)
    entry["status"] = "ok"
    _publish(entry)
    _ingest_cost(entry["label"], compiled, entry.get("meta"))
    return entry


def device_hbm_bytes() -> Optional[int]:
    """The device's reported memory capacity (TPU: memory_stats
    bytes_limit), or None when the backend doesn't say (CPU)."""
    try:
        import jax
        stats = jax.devices()[0].memory_stats()
        if stats:
            limit = stats.get("bytes_limit")
            if limit:
                return int(limit)
    except Exception:
        pass
    return None


def _live_buffers(top: int = 10) -> List[dict]:
    """Top live device allocations grouped by (shape, dtype) — the
    census a peak-HBM post-mortem wants next to the per-program plan
    (same source as the watchdog's hang report)."""
    if top <= 0:
        return []                   # dump()/bench ask for none: free
    try:
        import jax
        arrs = jax.live_arrays()
    except Exception:
        return []
    groups: Dict[tuple, dict] = {}
    for a in arrs:
        try:
            key = (tuple(a.shape), str(a.dtype))
            nbytes = int(a.size) * a.dtype.itemsize
        except Exception:
            continue
        g = groups.setdefault(key, {"shape": list(key[0]),
                                    "dtype": key[1], "count": 0,
                                    "bytes": 0})
        g["count"] += 1
        g["bytes"] += nbytes
    out = sorted(groups.values(), key=lambda g: -g["bytes"])[:top]
    return out


def memory_report(resolve: bool = True, top_buffers: int = 10) -> dict:
    """The ledger's answer: per-program byte accounting (resolving any
    pending providers unless resolve=False — resolution compiles, so
    an idle dump() passes False), device capacity, per-program peak
    share, the fleet-wide max peak, and the top live device buffers."""
    with _lock:
        entries = list(_programs.values())
    if resolve:
        for e in entries:
            if e.get("status") == "pending":
                _resolve(e)
    hbm = device_hbm_bytes()
    programs = {}
    peak = 0
    for e in entries:
        rec = {k: v for k, v in e.items()
               if k not in ("provider", "label")}
        if e.get("status") == "ok":
            peak = max(peak, e["peak_bytes"])
            # a backend without memory_stats()/bytes_limit (CPU
            # tier-1) degrades to share=None — never a KeyError or a
            # raise downstream
            rec["peak_share"] = round(e["peak_bytes"] / hbm, 4) \
                if hbm else None
        programs[e["label"]] = rec
    return {"programs": programs,
            "device_hbm_bytes": hbm,
            "peak_hbm_bytes": peak,
            "peak_hbm_share": round(peak / hbm, 4) if (hbm and peak)
            else None,
            "live_buffers": _live_buffers(top_buffers)}


def snapshot() -> dict:
    """The ledger without resolution — registered-but-pending entries
    stay pending and nothing compiles (what telemetry.dump() embeds)."""
    return memory_report(resolve=False, top_buffers=0)


def reset():
    with _lock:
        _programs.clear()
