"""Single-source op registry.

Reference: `paddle/phi/ops/yaml/ops.yaml` (467 op declarations) driving the
api/vjp/binding code generators (`paddle/phi/api/generator/api_gen.py`,
`eager_gen.py`, `python_c_gen.py`) — SURVEY §1 flags this single-source +
codegen pattern as the most important structural idea to replicate.

TPU-native version: one `OpSpec` per op carries
  * the jnp implementation (the "kernel" — XLA compiles it),
  * the numpy/scipy reference used by the OpTest harness,
  * sample inputs for the generated tests,
  * dispatch metadata (tensor arity, method exposure, multi-output).
From this table `build_ops()` generates the `paddle.*` functions (all
routed through `framework.dispatch.run`, so eager autograd and jit tracing
work uniformly) and `paddle_tpu._C_ops` exposes the same flat namespace the
reference's generated python bindings do.  VJPs need no per-op rules —
dispatch differentiates through `jax.vjp`, the structural win of building
on jax (the reference generates 337 backward configs for this).

Adding an op = adding ONE entry here; the function, its test, and its
`_C_ops` binding all appear.
"""
from __future__ import annotations

import dataclasses
import functools
import math
from typing import Callable, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

__all__ = ["OpSpec", "REGISTRY", "build_ops"]


@dataclasses.dataclass
class OpSpec:
    name: str
    fn: Callable                       # jnp impl: fn(*arrays, **attrs)
    np_ref: Optional[Callable] = None  # numpy reference (same signature)
    samples: Optional[Callable] = None  # () -> (arrays, attrs)
    n_tensors: int = 1                 # -1 → first arg is a tensor list
    method: bool = False               # also expose as Tensor method
    grad: bool = True                  # generated test checks gradients
    atol: Optional[float] = None
    grad_atol: Optional[float] = None
    ref: str = ""                      # reference file for parity checks


def _rs(seed=0):
    return np.random.RandomState(seed)


def _seed_of(*key):
    # crc32, not hash(): string hashing is salted per interpreter run,
    # which would make the generated OpTest data non-reproducible
    import zlib
    return zlib.crc32(repr(key).encode()) % (2 ** 31)


def _u(lo, hi, *shape):
    return _rs(_seed_of("u", lo, hi, shape)).uniform(
        lo, hi, shape).astype(np.float32)


def _n(*shape):
    return _rs(_seed_of("n", shape)).randn(*shape).astype(np.float32)


def _away_from_int(x, margin=0.1):
    """Nudge samples off integer values: ops with integer-breakpoint
    discontinuities (trunc/frac/floor) break finite-difference grad
    checks when eps straddles a breakpoint."""
    near = np.abs(x - np.round(x)) < margin
    return (x + np.where(near, 2 * margin, 0.0)).astype(np.float32)


def _diag_embed(x, offset=0, dim1=-2, dim2=-1):
    # scatter the last dim onto the (dim1, dim2) diagonal
    n = x.shape[-1] + abs(offset)
    base = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    idx = jnp.arange(x.shape[-1])
    r = idx + max(-offset, 0)
    c = idx + max(offset, 0)
    base = base.at[..., r, c].set(x)
    if (dim1, dim2) != (-2, -1):
        base = jnp.moveaxis(base, (-2, -1), (dim1, dim2))
    return base


def _np_diag_embed(x, offset=0):
    n = x.shape[-1] + abs(offset)
    out = np.zeros(x.shape[:-1] + (n, n), x.dtype)
    idx = np.arange(x.shape[-1])
    out[..., idx + max(-offset, 0), idx + max(offset, 0)] = x
    return out


def _renorm(x, p, axis, max_norm):
    xm = jnp.moveaxis(x, axis, 0).reshape(x.shape[axis], -1)
    norms = jnp.linalg.norm(xm, ord=p, axis=1)
    scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    return x * scale.reshape(shape).astype(x.dtype)


def _np_renorm(x, p, axis, max_norm):
    xm = np.moveaxis(x, axis, 0).reshape(x.shape[axis], -1)
    norms = np.linalg.norm(xm, ord=p, axis=1)
    scale = np.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    return x * scale.reshape(shape).astype(x.dtype)


def _combinations(x, r=2, with_replacement=False):
    import itertools
    n = x.shape[0]
    it = (itertools.combinations_with_replacement(range(n), r)
          if with_replacement else itertools.combinations(range(n), r))
    idx = np.asarray(list(it), np.int32)
    if idx.size == 0:
        return jnp.zeros((0, r), x.dtype)
    return x[jnp.asarray(idx)]


def _np_combinations(x, r=2, with_replacement=False):
    import itertools
    it = (itertools.combinations_with_replacement(x, r)
          if with_replacement else itertools.combinations(x, r))
    arr = np.asarray(list(it), x.dtype)
    return arr if arr.size else arr.reshape(0, r)


def _cdist(x, y, p=2.0):
    d = jnp.abs(x[..., :, None, :] - y[..., None, :, :])
    if p == float("inf"):
        return jnp.max(d, -1)
    if p == 0.0:
        return jnp.sum((d != 0).astype(x.dtype), -1)
    if p == 2.0:
        return jnp.sqrt(jnp.sum(d * d, -1) + 1e-30)
    return jnp.sum(d ** p, -1) ** (1.0 / p)


def _unflatten(x, axis, shape, mod):
    axis = axis % x.ndim
    return mod.reshape(x, tuple(x.shape[:axis]) + tuple(shape)
                       + tuple(x.shape[axis + 1:]))


def _np_cdist(x, y, p=2.0):
    from scipy.spatial.distance import cdist as scdist
    return scdist(x, y, "minkowski", p=p).astype(x.dtype)


def _pdist(x, p=2.0):
    n = x.shape[0]
    iu = np.triu_indices(n, 1)
    full = _cdist(x, x, p)
    return full[iu]


def _np_pdist(x, p=2.0):
    from scipy.spatial.distance import pdist as spdist
    return spdist(x, "minkowski", p=p).astype(x.dtype)


def _tensor_split_np(x, num_or_indices, axis=0):
    return [np.asarray(a) for a in
            np.array_split(x, num_or_indices, axis)]


def _np_select_scatter_ref(x, src, axis=0, index=0):
    out = np.array(x)
    sl = [slice(None)] * x.ndim
    sl[axis % x.ndim] = index
    out[tuple(sl)] = src
    return out


def _slice_scatter(x, src, axis=0, start=None, stop=None, step=1):
    sl = [slice(None)] * x.ndim
    sl[axis % x.ndim] = slice(start, stop, step)
    return x.at[tuple(sl)].set(src)


def _np_slice_scatter(x, src, axis=0, start=None, stop=None, step=1):
    out = np.array(x)
    sl = [slice(None)] * x.ndim
    sl[axis % x.ndim] = slice(start, stop, step)
    out[tuple(sl)] = src
    return out


# scipy backs the numpy REFERENCES only (consumed by the generated tests);
# the library itself must import without it
try:
    import scipy.special as ssp
except ImportError:  # pragma: no cover
    class _NoScipy:
        def __getattr__(self, name):
            raise ModuleNotFoundError(
                "scipy is required only to run the registry OpTests")
    ssp = _NoScipy()

REGISTRY: Sequence[OpSpec] = [
    # -- special functions (reference: phi/kernels/*erf*, *lgamma*, ...) --
    OpSpec("erf", lambda x: jsp.erf(x), ssp.erf,
           lambda: ([_n(3, 4)], {}), method=True,
           ref="paddle/phi/kernels/impl/erf_kernel_impl.h"),
    OpSpec("erfinv", lambda x: jsp.erfinv(x), ssp.erfinv,
           lambda: ([_u(-0.9, 0.9, 3, 4)], {}), method=True,
           ref="paddle/phi/kernels/erfinv_kernel.h"),
    OpSpec("expm1", jnp.expm1, np.expm1, lambda: ([_n(3, 4)], {}),
           method=True, ref="paddle/phi/ops/yaml/ops.yaml expm1"),
    OpSpec("lgamma", jsp.gammaln, ssp.gammaln,
           lambda: ([_u(0.5, 5.0, 3, 4)], {}), method=True,
           ref="paddle/phi/kernels/lgamma_kernel.h"),
    OpSpec("gammaln", jsp.gammaln, ssp.gammaln,
           lambda: ([_u(0.5, 5.0, 3, 4)], {}), method=True,
           ref="python/paddle/tensor/math.py gammaln"),
    OpSpec("digamma", jsp.digamma, ssp.digamma,
           lambda: ([_u(0.5, 5.0, 3, 4)], {}), method=True,
           ref="paddle/phi/kernels/digamma_kernel.h"),
    OpSpec("polygamma",
           lambda x, n=1: jsp.polygamma(n, x),
           lambda x, n=1: ssp.polygamma(n, x).astype(np.float32),
           lambda: ([_u(0.5, 5.0, 3, 4)], {"n": 1}), method=True,
           ref="python/paddle/tensor/math.py polygamma"),
    OpSpec("gammainc",
           lambda x, y: jsp.gammainc(x, y),
           lambda x, y: ssp.gammainc(x, y),
           lambda: ([_u(0.5, 5.0, 3, 4), _u(0.1, 5.0, 3, 4)], {}),
           n_tensors=2, grad=False,
           ref="python/paddle/tensor/math.py gammainc"),
    OpSpec("gammaincc",
           lambda x, y: jsp.gammaincc(x, y),
           lambda x, y: ssp.gammaincc(x, y),
           lambda: ([_u(0.5, 5.0, 3, 4), _u(0.1, 5.0, 3, 4)], {}),
           n_tensors=2, grad=False,
           ref="python/paddle/tensor/math.py gammaincc"),
    OpSpec("i0", jsp.i0, ssp.i0, lambda: ([_n(3, 4)], {}), method=True,
           ref="paddle/phi/kernels/i0_kernel.h"),
    OpSpec("i0e", jsp.i0e, ssp.i0e, lambda: ([_n(3, 4)], {}), method=True,
           ref="paddle/phi/kernels/i0e_kernel.h"),
    OpSpec("i1", jsp.i1, ssp.i1, lambda: ([_n(3, 4)], {}), method=True,
           ref="paddle/phi/kernels/i1_kernel.h"),
    OpSpec("i1e", jsp.i1e, ssp.i1e, lambda: ([_n(3, 4)], {}), method=True,
           ref="paddle/phi/kernels/i1e_kernel.h"),
    OpSpec("sinc", jnp.sinc, np.sinc, lambda: ([_n(3, 4)], {}),
           ref="python/paddle/tensor/math.py sinc"),
    OpSpec("logit",
           lambda x, eps=None: jsp.logit(
               jnp.clip(x, eps, 1 - eps) if eps is not None else x),
           lambda x, eps=None: ssp.logit(
               np.clip(x, eps, 1 - eps) if eps is not None else x),
           lambda: ([_u(0.05, 0.95, 3, 4)], {"eps": 0.0}), method=True,
           ref="paddle/phi/kernels/logit_kernel.h"),
    # -- binary elementwise ------------------------------------------------
    OpSpec("logaddexp", jnp.logaddexp, np.logaddexp,
           lambda: ([_n(3, 4), _n(4)], {}), n_tensors=2,
           ref="python/paddle/tensor/math.py logaddexp"),
    OpSpec("hypot", jnp.hypot, np.hypot,
           lambda: ([_n(3, 4), _n(4)], {}), n_tensors=2, method=True,
           ref="python/paddle/tensor/math.py hypot"),
    OpSpec("copysign", jnp.copysign, np.copysign,
           lambda: ([_n(3, 4), _n(4)], {}), n_tensors=2, method=True,
           grad=False, ref="python/paddle/tensor/math.py copysign"),
    OpSpec("nextafter", jnp.nextafter, np.nextafter,
           lambda: ([_n(3, 4), _n(4)], {}), n_tensors=2, grad=False,
           ref="paddle/phi/kernels/nextafter_kernel.h"),
    OpSpec("ldexp", lambda x, y: jnp.ldexp(x, y.astype(jnp.int32)),
           lambda x, y: np.ldexp(x, y.astype(np.int32)),
           lambda: ([_n(3, 4), np.array([1, 2, 0, 3], np.float32)], {}),
           n_tensors=2, grad=False,
           ref="python/paddle/tensor/math.py ldexp"),
    OpSpec("atan2", jnp.arctan2, np.arctan2,
           lambda: ([_n(3, 4), _n(4)], {}), n_tensors=2, method=True,
           ref="paddle/phi/kernels/atan2_kernel.h"),
    OpSpec("fmax", jnp.fmax, np.fmax, lambda: ([_n(3, 4), _n(4)], {}),
           n_tensors=2, method=True, grad=False,
           ref="paddle/phi/kernels/elementwise_kernel.h fmax"),
    OpSpec("fmin", jnp.fmin, np.fmin, lambda: ([_n(3, 4), _n(4)], {}),
           n_tensors=2, method=True, grad=False,
           ref="paddle/phi/kernels/elementwise_kernel.h fmin"),
    OpSpec("heaviside", jnp.heaviside, np.heaviside,
           lambda: ([_n(3, 4), _n(4)], {}), n_tensors=2, method=True,
           grad=False, ref="python/paddle/tensor/math.py heaviside"),
    # -- unary -------------------------------------------------------------
    OpSpec("trunc", jnp.trunc, np.trunc, lambda: ([_n(3, 4) * 3], {}),
           method=True, grad=False,
           ref="paddle/phi/kernels/trunc_kernel.h"),
    OpSpec("frac", lambda x: x - jnp.trunc(x),
           lambda x: x - np.trunc(x), lambda: ([_away_from_int(_n(3, 4) * 3)], {}),
           method=True, ref="python/paddle/tensor/math.py frac"),
    OpSpec("rsqrt", jax.lax.rsqrt, lambda x: 1.0 / np.sqrt(x),
           lambda: ([_u(0.1, 4.0, 3, 4)], {}), method=True,
           ref="paddle/phi/ops/yaml/ops.yaml rsqrt"),
    OpSpec("asinh", jnp.arcsinh, np.arcsinh, lambda: ([_n(3, 4)], {}),
           method=True, ref="paddle/phi/ops/yaml/ops.yaml asinh"),
    OpSpec("acosh", jnp.arccosh, np.arccosh,
           lambda: ([_u(1.1, 4.0, 3, 4)], {}), method=True,
           ref="paddle/phi/ops/yaml/ops.yaml acosh"),
    OpSpec("atanh", jnp.arctanh, np.arctanh,
           lambda: ([_u(-0.9, 0.9, 3, 4)], {}), method=True,
           ref="paddle/phi/ops/yaml/ops.yaml atanh"),
    OpSpec("neg", jnp.negative, np.negative, lambda: ([_n(3, 4)], {}),
           method=True, ref="python/paddle/tensor/math.py neg"),
    OpSpec("positive", lambda x: x, lambda x: x, lambda: ([_n(3, 4)], {}),
           ref="python/paddle/tensor/math.py positive"),
    OpSpec("angle", jnp.angle, np.angle, lambda: ([_n(3, 4)], {}),
           grad=False, ref="paddle/phi/kernels/angle_kernel.h"),
    OpSpec("conj", jnp.conj, np.conj, lambda: ([_n(3, 4)], {}),
           method=True, ref="paddle/phi/kernels/conj_kernel.h"),
    OpSpec("isposinf", jnp.isposinf,
           np.isposinf, lambda: ([np.array([1.0, np.inf, -np.inf, np.nan],
                                           np.float32)], {}),
           method=True, grad=False,
           ref="python/paddle/tensor/math.py isposinf"),
    OpSpec("isneginf", jnp.isneginf, np.isneginf,
           lambda: ([np.array([1.0, np.inf, -np.inf, np.nan],
                              np.float32)], {}),
           method=True, grad=False,
           ref="python/paddle/tensor/math.py isneginf"),
    OpSpec("signbit", jnp.signbit, np.signbit,
           lambda: ([np.array([1.0, -2.0, 0.0, -0.0], np.float32)], {}),
           method=True, grad=False,
           ref="python/paddle/tensor/math.py signbit"),
    # -- nan-aware reductions ---------------------------------------------
    OpSpec("nanmean",
           lambda x, axis=None, keepdim=False: jnp.nanmean(
               x, axis=axis, keepdims=keepdim),
           lambda x, axis=None, keepdim=False: np.nanmean(
               x, axis=axis, keepdims=keepdim),
           lambda: ([np.array([[1, np.nan, 3], [4, 5, np.nan]],
                              np.float32)], {"axis": 1}),
           method=True, grad=False,
           ref="python/paddle/tensor/stat.py nanmean"),
    OpSpec("nansum",
           lambda x, axis=None, keepdim=False: jnp.nansum(
               x, axis=axis, keepdims=keepdim),
           lambda x, axis=None, keepdim=False: np.nansum(
               x, axis=axis, keepdims=keepdim),
           lambda: ([np.array([[1, np.nan, 3], [4, 5, np.nan]],
                              np.float32)], {"axis": 0}),
           method=True, grad=False,
           ref="python/paddle/tensor/math.py nansum"),
    OpSpec("logsumexp",
           lambda x, axis=None, keepdim=False: jsp.logsumexp(
               x, axis=axis, keepdims=keepdim),
           lambda x, axis=None, keepdim=False: ssp.logsumexp(
               x, axis=axis, keepdims=keepdim),
           lambda: ([_n(3, 4)], {"axis": 1}), method=True,
           ref="paddle/phi/kernels/logsumexp_kernel.h"),
    OpSpec("logcumsumexp",
           lambda x, axis=-1: jax.lax.associative_scan(
               jnp.logaddexp, x, axis=axis),
           lambda x, axis=-1: np.logaddexp.accumulate(x, axis=axis),
           lambda: ([_n(3, 4)], {"axis": 1}),
           ref="paddle/phi/kernels/logcumsumexp_kernel.h"),
    OpSpec("amax",
           lambda x, axis=None, keepdim=False: jnp.amax(
               x, axis=axis, keepdims=keepdim),
           lambda x, axis=None, keepdim=False: np.amax(
               x, axis=axis, keepdims=keepdim),
           lambda: ([_n(3, 4)], {"axis": 1}), method=True,
           ref="python/paddle/tensor/math.py amax"),
    OpSpec("amin",
           lambda x, axis=None, keepdim=False: jnp.amin(
               x, axis=axis, keepdims=keepdim),
           lambda x, axis=None, keepdim=False: np.amin(
               x, axis=axis, keepdims=keepdim),
           lambda: ([_n(3, 4)], {"axis": 0}), method=True,
           ref="python/paddle/tensor/math.py amin"),
    # -- indexing / manipulation ------------------------------------------
    OpSpec("index_fill",
           lambda x, index, axis=0, value=0.0: x.at[
               (slice(None),) * (axis % x.ndim)
               + (index.astype(jnp.int32),)].set(value),
           lambda x, index, axis=0, value=0.0: _np_index_fill(
               x, index, axis % x.ndim, value),
           lambda: ([_n(3, 4), np.array([0, 2], np.float32)],
                    {"axis": -1, "value": 9.0}),
           n_tensors=2, method=True, grad=False,
           ref="python/paddle/tensor/manipulation.py index_fill"),
    OpSpec("diag_embed", _diag_embed, _np_diag_embed,
           lambda: ([_n(3, 4)], {"offset": 1}),
           ref="python/paddle/tensor/creation.py diag_embed"),
    OpSpec("vander",
           lambda x, n=None, increasing=False: jnp.vander(
               x, N=n, increasing=increasing),
           lambda x, n=None, increasing=False: np.vander(
               x, N=n, increasing=increasing),
           lambda: ([_n(4)], {"n": 3, "increasing": True}),
           ref="python/paddle/tensor/creation.py vander"),
    OpSpec("renorm", _renorm, _np_renorm,
           lambda: ([_n(3, 4, 2)], {"p": 2.0, "axis": 0,
                                    "max_norm": 1.0}),
           method=True, ref="python/paddle/tensor/math.py renorm"),
    OpSpec("unflatten", lambda x, axis, shape: _unflatten(x, axis,
                                                          shape, jnp),
           lambda x, axis, shape: _unflatten(x, axis, shape, np),
           lambda: ([_n(3, 12)], {"axis": -1, "shape": (3, 4)}),
           ref="python/paddle/tensor/manipulation.py unflatten"),
    OpSpec("combinations", _combinations, _np_combinations,
           lambda: ([_n(5)], {"r": 2}), grad=False,
           ref="python/paddle/tensor/math.py combinations"),
    OpSpec("cartesian_prod",
           lambda xs: jnp.stack(
               [a.ravel() for a in jnp.meshgrid(*xs, indexing="ij")],
               axis=-1),
           lambda xs: np.stack(
               [a.ravel() for a in np.meshgrid(*xs, indexing="ij")],
               axis=-1),
           lambda: ([[_n(3), _n(2)]], {}), n_tensors=-1, grad=False,
           ref="python/paddle/tensor/math.py cartesian_prod"),
    OpSpec("row_stack", lambda xs: jnp.vstack(xs), np.vstack,
           lambda: ([[_n(2, 4), _n(3, 4)]], {}), n_tensors=-1,
           ref="python/paddle/tensor/manipulation.py row_stack"),
    OpSpec("column_stack", lambda xs: jnp.column_stack(xs),
           np.column_stack, lambda: ([[_n(3), _n(3, 2)]], {}),
           n_tensors=-1,
           ref="python/paddle/tensor/manipulation.py column_stack"),
    OpSpec("hsplit",
           lambda x, num_or_indices: jnp.hsplit(x, num_or_indices),
           lambda x, num_or_indices: np.hsplit(x, num_or_indices),
           lambda: ([_n(4, 6)], {"num_or_indices": 3}), grad=False,
           ref="python/paddle/tensor/manipulation.py hsplit"),
    OpSpec("vsplit",
           lambda x, num_or_indices: jnp.vsplit(x, num_or_indices),
           lambda x, num_or_indices: np.vsplit(x, num_or_indices),
           lambda: ([_n(6, 4)], {"num_or_indices": 2}), grad=False,
           ref="python/paddle/tensor/manipulation.py vsplit"),
    OpSpec("dsplit",
           lambda x, num_or_indices: jnp.dsplit(x, num_or_indices),
           lambda x, num_or_indices: np.dsplit(x, num_or_indices),
           lambda: ([_n(2, 3, 4)], {"num_or_indices": 2}), grad=False,
           ref="python/paddle/tensor/manipulation.py dsplit"),
    OpSpec("tensor_split",
           lambda x, num_or_indices, axis=0: jnp.array_split(
               x, num_or_indices, axis=axis),
           _tensor_split_np,
           lambda: ([_n(7, 3)], {"num_or_indices": 3}), grad=False,
           ref="python/paddle/tensor/manipulation.py tensor_split"),
    # -- linalg-ish --------------------------------------------------------
    OpSpec("baddbmm",
           lambda inp, x, y, beta=1.0, alpha=1.0:
           beta * inp + alpha * jnp.einsum("bij,bjk->bik", x, y),
           lambda inp, x, y, beta=1.0, alpha=1.0:
           beta * inp + alpha * np.einsum("bij,bjk->bik", x, y),
           lambda: ([_n(2, 3, 5), _n(2, 3, 4), _n(2, 4, 5)],
                    {"beta": 0.5, "alpha": 2.0}),
           n_tensors=3, grad_atol=5e-2,
           ref="python/paddle/tensor/math.py baddbmm"),
    OpSpec("cdist", _cdist, _np_cdist,
           lambda: ([_n(5, 3), _n(4, 3)], {}), n_tensors=2,
           atol=1e-3, ref="python/paddle/tensor/linalg.py cdist"),
    OpSpec("pdist", _pdist, _np_pdist, lambda: ([_n(5, 3)], {}),
           atol=1e-3,
           ref="python/paddle/nn/functional/distance.py pdist"),
    # -- integration / flips / shape utilities ----------------------------
    OpSpec("trapezoid",
           lambda y, dx=1.0, axis=-1: jnp.trapezoid(y, dx=dx, axis=axis),
           lambda y, dx=1.0, axis=-1: np.trapezoid(y, dx=dx, axis=axis),
           lambda: ([_n(3, 5)], {"dx": 0.5, "axis": 1}),
           ref="python/paddle/tensor/math.py trapezoid"),
    OpSpec("cumulative_trapezoid",
           lambda y, dx=1.0, axis=-1: jnp.cumsum(
               dx * 0.5 * (jnp.take(y, jnp.arange(1, y.shape[axis]),
                                    axis=axis)
                           + jnp.take(y, jnp.arange(y.shape[axis] - 1),
                                      axis=axis)), axis=axis),
           lambda y, dx=1.0, axis=-1: __import__(
               "scipy.integrate", fromlist=["x"]).cumulative_trapezoid(
               y, dx=dx, axis=axis),
           lambda: ([_n(3, 5)], {"dx": 0.5, "axis": 1}),
           ref="python/paddle/tensor/math.py cumulative_trapezoid"),
    OpSpec("fliplr", jnp.fliplr, np.fliplr, lambda: ([_n(3, 4)], {}),
           ref="python/paddle/tensor/manipulation.py flip"),
    OpSpec("flipud", jnp.flipud, np.flipud, lambda: ([_n(3, 4)], {}),
           ref="python/paddle/tensor/manipulation.py flip"),
    OpSpec("atleast_1d", jnp.atleast_1d, np.atleast_1d,
           lambda: ([np.float32(3.0).reshape(())], {}),
           ref="python/paddle/tensor/manipulation.py atleast_1d"),
    OpSpec("atleast_2d", jnp.atleast_2d, np.atleast_2d,
           lambda: ([_n(4)], {}),
           ref="python/paddle/tensor/manipulation.py atleast_2d"),
    OpSpec("atleast_3d", jnp.atleast_3d, np.atleast_3d,
           lambda: ([_n(3, 4)], {}),
           ref="python/paddle/tensor/manipulation.py atleast_3d"),
    OpSpec("block_diag",
           lambda xs: jax.scipy.linalg.block_diag(*xs),
           lambda xs: __import__(
               "scipy.linalg", fromlist=["x"]).block_diag(*xs),
           lambda: ([[_n(2, 3), _n(2, 2)]], {}), n_tensors=-1,
           ref="python/paddle/tensor/creation.py block_diag"),
    OpSpec("view_as", lambda x, other: jnp.reshape(x, other.shape),
           lambda x, other: np.reshape(x, other.shape),
           lambda: ([_n(3, 4), _n(2, 6)], {}), n_tensors=2,
           ref="python/paddle/tensor/manipulation.py view_as"),
    OpSpec("select_scatter",
           lambda x, src, axis=0, index=0: x.at[
               (slice(None),) * (axis % x.ndim) + (index,)].set(src),
           _np_select_scatter_ref,
           lambda: ([_n(3, 4), _n(3)], {"axis": 1, "index": 2}),
           n_tensors=2,
           ref="python/paddle/tensor/manipulation.py select_scatter"),
    OpSpec("slice_scatter", _slice_scatter, _np_slice_scatter,
           lambda: ([_n(5, 4), _n(2, 4)],
                    {"axis": 0, "start": 1, "stop": 3}),
           n_tensors=2,
           ref="python/paddle/tensor/manipulation.py slice_scatter"),
    # -- search / logic ---------------------------------------------------
    OpSpec("argwhere", jnp.argwhere, np.argwhere,
           lambda: ([np.array([[0, 1], [2, 0]], np.float32)], {}),
           grad=False,
           ref="python/paddle/tensor/search.py nonzero/argwhere"),
    OpSpec("isin",
           lambda x, test: jnp.isin(x, test),
           lambda x, test: np.isin(x, test),
           lambda: ([np.array([1., 2., 3., 4.], np.float32),
                     np.array([2., 4.], np.float32)], {}),
           n_tensors=2, grad=False,
           ref="python/paddle/tensor/search.py isin"),
    OpSpec("nanargmax",
           lambda x, axis=None: jnp.nanargmax(x, axis=axis),
           lambda x, axis=None: np.nanargmax(x, axis=axis),
           lambda: ([np.array([[1, np.nan, 3], [np.nan, 5, 0]],
                              np.float32)], {"axis": 1}), grad=False,
           ref="python/paddle/tensor/search.py nanargmax"),
    OpSpec("nanargmin",
           lambda x, axis=None: jnp.nanargmin(x, axis=axis),
           lambda x, axis=None: np.nanargmin(x, axis=axis),
           lambda: ([np.array([[1, np.nan, 3], [np.nan, 5, 0]],
                              np.float32)], {"axis": 1}), grad=False,
           ref="python/paddle/tensor/search.py nanargmin"),
    # -- math extras ------------------------------------------------------
    OpSpec("exp2", jnp.exp2, np.exp2, lambda: ([_n(3, 4)], {}),
           ref="python/paddle/tensor/math.py exp2"),
    OpSpec("frexp", jnp.frexp,
           lambda x: tuple(np.frexp(x)),
           lambda: ([_n(3, 4) * 8], {}), grad=False,
           ref="python/paddle/tensor/math.py frexp"),
    OpSpec("float_power",
           lambda x, y: jnp.float_power(x, y),
           lambda x, y: np.float_power(x, y),
           lambda: ([_u(0.5, 3.0, 3, 4), _u(-2.0, 2.0, 3, 4)], {}),
           n_tensors=2, grad=False,
           ref="python/paddle/tensor/math.py float_power"),
    OpSpec("bitwise_invert",
           lambda x: jnp.invert(x) if not jnp.issubdtype(
               x.dtype, jnp.floating)
           else jnp.invert(x.astype(jnp.int32)).astype(x.dtype),
           lambda x: np.invert(x) if not np.issubdtype(
               x.dtype, np.floating)
           else np.invert(x.astype(np.int32)).astype(x.dtype),
           lambda: ([np.array([0, 1, 5, -3], np.float32)], {}),
           grad=False, method=True,
           ref="python/paddle/tensor/logic.py bitwise_invert"),
    OpSpec("sgn", jnp.sign, np.sign, lambda: ([_n(3, 4)], {}),
           grad=False, method=True,
           ref="python/paddle/tensor/math.py sgn"),
    OpSpec("conj_physical", jnp.conj, np.conj, lambda: ([_n(3, 4)], {}),
           ref="python/paddle/tensor/math.py conj"),
    # -- blas extras ------------------------------------------------------
    OpSpec("addmv",
           lambda inp, mat, vec, beta=1.0, alpha=1.0:
           beta * inp + alpha * (mat @ vec),
           lambda inp, mat, vec, beta=1.0, alpha=1.0:
           beta * inp + alpha * (mat @ vec),
           lambda: ([_n(3), _n(3, 4), _n(4)], {"beta": 0.5,
                                               "alpha": 2.0}),
           n_tensors=3, ref="python/paddle/tensor/math.py addmv"),
    OpSpec("addbmm",
           lambda inp, x, y, beta=1.0, alpha=1.0:
           beta * inp + alpha * jnp.sum(
               jnp.einsum("bij,bjk->bik", x, y), axis=0),
           lambda inp, x, y, beta=1.0, alpha=1.0:
           beta * inp + alpha * np.einsum("bij,bjk->bik", x, y).sum(0),
           lambda: ([_n(3, 5), _n(2, 3, 4), _n(2, 4, 5)],
                    {"beta": 0.5, "alpha": 2.0}),
           n_tensors=3, grad_atol=5e-2,
           ref="python/paddle/tensor/math.py addbmm"),
    OpSpec("chain_matmul",
           lambda xs: jnp.linalg.multi_dot(xs),
           lambda xs: np.linalg.multi_dot(xs),
           lambda: ([[_n(2, 3), _n(3, 4), _n(4, 2)]], {}),
           n_tensors=-1, grad_atol=5e-2,
           ref="python/paddle/tensor/linalg.py multi_dot"),
    OpSpec("vdot",
           lambda x, y: jnp.vdot(x, y),
           lambda x, y: np.vdot(x, y),
           lambda: ([_n(6), _n(6)], {}), n_tensors=2,
           ref="python/paddle/tensor/linalg.py dot"),
    OpSpec("ger",
           lambda x, y: jnp.outer(x, y),
           lambda x, y: np.outer(x, y),
           lambda: ([_n(3), _n(4)], {}), n_tensors=2,
           ref="python/paddle/tensor/linalg.py outer"),
]

# ops.yaml long-tail extension (round-4 audit close) — kept in its own
# module; build_extra takes the helpers as args to avoid a circular
# import at module load
from .registry_ext import build_extra as _build_extra  # noqa: E402
REGISTRY = list(REGISTRY) + _build_extra(OpSpec, _n, _u, _rs, _seed_of)


def _np_index_fill(x, index, axis, value):
    out = np.array(x)
    sl = [slice(None)] * x.ndim
    sl[axis] = index.astype(np.int64)
    out[tuple(sl)] = value
    return out


def _make_op(spec: OpSpec):
    from ..framework.dispatch import run, to_tensor_args

    @functools.wraps(spec.fn)
    def op(*args, **kwargs):
        kwargs.pop("name", None)
        if spec.n_tensors == -1:
            seq = list(args[0])
            rest = args[1:]
            tensors = to_tensor_args(*seq)

            def raw(*vals):
                return spec.fn(list(vals), *rest, **kwargs)

            return run(raw, *tensors, name=spec.name)
        nt = spec.n_tensors
        tensors = to_tensor_args(*args[:nt])
        rest = args[nt:]

        def raw(*vals):
            return spec.fn(*vals, *rest, **kwargs)

        return run(raw, *tensors, name=spec.name)

    op.__name__ = spec.name
    op.__qualname__ = spec.name
    op.__doc__ = (f"Generated from the op registry "
                  f"(paddle_tpu/ops/registry.py).  Reference: {spec.ref}")
    return op


def build_ops(namespace: dict, tensor_cls=None):
    """Generate all registry ops into `namespace` (e.g. the paddle_tpu
    module dict) and attach method variants to `tensor_cls`."""
    made = {}
    for spec in REGISTRY:
        if spec.name in namespace:
            # hand-written impl wins; the spec still supplies OpTest
            # coverage for it via the generated test matrix
            fn = namespace[spec.name]
        else:
            fn = _make_op(spec)
            namespace[spec.name] = fn
            made[spec.name] = fn
        if tensor_cls is not None and spec.method \
                and not hasattr(tensor_cls, spec.name):
            setattr(tensor_cls, spec.name, fn)
    return made
