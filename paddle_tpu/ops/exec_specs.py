"""Execution-level verification specs, keyed by REFERENCE YAML op name.

Reference: every op in `paddle/phi/ops/yaml/ops.yaml` is numerically
checked by the reference's OpTest harness
(`test/legacy_test/op_test.py:2925 check_output`).  The OpSpec registry
(`registry.py`) already gives forward+grad tests to 130 ops; this table
closes the gap for the REST of the covered surface: one ExecSpec per
yaml op name runs the op on sampled inputs and checks the result against
a numpy/scipy reference (or a property/statistical check for ops with no
closed form — RNG ops, `empty`, sampling ops).

`tools/op_audit.py` consumes `executed_yaml_names()` to print *executed*
coverage (ops with passing numeric tests) alongside by-name coverage;
`tests/test_op_exec.py` is the generated parametrized test that actually
runs every spec in CI.

Adding a spec = one `E(...)` line; the test and the audit accounting
appear automatically.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Callable, Optional

import numpy as np
from scipy import special as sps

from .registry import REGISTRY, _n, _u, _rs, _seed_of

__all__ = ["ExecSpec", "EXEC_SPECS", "EXEC_INDEX", "run_spec",
           "executed_yaml_names"]


@dataclasses.dataclass
class ExecSpec:
    op: str                      # reference yaml op name
    api: str                     # dotted path under paddle_tpu
    sample: Callable             # () -> (args, kwargs)
    ref: Optional[Callable] = None   # numpy reference, same signature
    check: Optional[Callable] = None  # (np_out, args, kwargs) -> None
    custom: Optional[Callable] = None  # full custom test () -> None
    sel: Optional[int] = None    # compare only output[sel]
    atol: float = 1e-5
    note: str = ""               # why no ref, for the audit


EXEC_SPECS: list[ExecSpec] = []


def E(op, api, sample=None, ref=None, check=None, custom=None, sel=None,
      atol=1e-5, note=""):
    EXEC_SPECS.append(ExecSpec(op, api, sample, ref, check, custom, sel,
                               atol, note))


def _i(lo, hi, *shape, dtype=np.int64):
    return _rs(_seed_of("i", lo, hi, shape)).randint(
        lo, hi, shape).astype(dtype)


def _b(*shape):
    return _rs(_seed_of("b", shape)).rand(*shape) > 0.5


def _resolve(api: str):
    import importlib
    root = importlib.import_module("paddle_tpu")
    obj = root
    for part in api.split("."):
        obj = getattr(obj, part)
    return obj


def _to_tensors(tree):
    import paddle_tpu as paddle
    if isinstance(tree, np.ndarray):
        return paddle.to_tensor(tree)
    if isinstance(tree, (list, tuple)):
        out = [_to_tensors(x) for x in tree]
        return type(tree)(out) if isinstance(tree, tuple) else out
    if isinstance(tree, dict):
        return {k: _to_tensors(v) for k, v in tree.items()}
    return tree


def _to_np(out):
    from ..framework.tensor import Tensor
    if isinstance(out, Tensor):
        return np.asarray(out.value)
    if isinstance(out, (list, tuple)):
        return tuple(_to_np(x) for x in out)
    return out


def _compare(got, want, atol):
    if isinstance(want, (list, tuple)):
        assert isinstance(got, tuple) and len(got) == len(want), \
            (type(got), len(want))
        for g, w in zip(got, want):
            _compare(g, w, atol)
        return
    w = np.asarray(want)
    g = np.asarray(got)
    assert g.shape == w.shape, (g.shape, w.shape)
    if w.dtype == bool or np.issubdtype(w.dtype, np.integer):
        np.testing.assert_array_equal(g, w)
    else:
        np.testing.assert_allclose(g.astype(np.float64),
                                   w.astype(np.float64),
                                   rtol=atol * 10, atol=atol,
                                   equal_nan=True)


def run_spec(spec: ExecSpec):
    """Execute one spec; raises AssertionError on numeric mismatch."""
    if spec.custom is not None:
        spec.custom()
        return
    fn = _resolve(spec.api)
    args, kwargs = spec.sample()
    out = fn(*_to_tensors(list(args)), **_to_tensors(dict(kwargs)))
    got = _to_np(out)
    if spec.sel is not None:
        got = got[spec.sel]
    if spec.ref is not None:
        _compare(got, spec.ref(*args, **kwargs), spec.atol)
    elif spec.check is not None:
        spec.check(got, args, kwargs)
    else:
        raise AssertionError(f"spec {spec.op} has no ref/check/custom")


def executed_yaml_names():
    """Yaml op names with numeric execution tests: this table plus every
    name that resolves (directly or via the audit aliases) onto an
    OpSpec in the registry (those get generated fwd+grad tests)."""
    names = {s.op for s in EXEC_SPECS}
    reg = {s.name for s in REGISTRY}
    names |= reg          # registry ops share yaml names by convention
    return names


# ---------------------------------------------------------------------------
# generic gradient verification (reference: op_test.py:3129 check_grad —
# numeric-vs-analytic per op).  TPU-native: the analytic gradient is
# jax.grad THROUGH the public api; the numeric side is a directional
# derivative (dot-product test): perturb every float input along a fixed
# random direction v, compare (f(x+εv) − f(x−εv)) / 2ε against ⟨∇f, v⟩.
# One scalar per spec — cheap, and catches any wrong VJP that projects
# onto a random direction (i.e. almost any wrong VJP).
# ---------------------------------------------------------------------------

# ops whose outputs are piecewise-constant in their float inputs or
# selection-indexed (derivative a.e. zero / FD ill-defined at scale):
# the dot-product test is vacuous or noisy there, so they are skipped
# and stay accounted as forward-only
GRAD_CHECK_SKIP = {
    # integer-valued / index outputs
    "argmax", "argmin", "argsort", "searchsorted", "bucketize",
    "nonzero", "unique", "unique_consecutive", "mode", "kthvalue",
    "topk", "sort", "median", "nanmedian",
    # piecewise-constant
    "floor", "ceil", "round", "trunc", "sign", "equal", "not_equal",
    "greater_than", "greater_equal", "less_than", "less_equal",
    "isnan", "isinf", "isfinite", "isclose", "allclose",
    "logical_and", "logical_or", "logical_not", "logical_xor",
    "heaviside", "histogram", "bincount",
    # discontinuous selection / counting semantics
    "nms", "viterbi_decode", "edit_distance", "accuracy", "auc",
    "matrix_rank", "clip_by_norm", "box_coder", "prior_box",
    "yolo_box", "generate_proposals",
    # stochastic or property-checked only
    "bernoulli", "multinomial", "randint", "randperm", "uniform",
    "gaussian", "poisson", "exponential", "dropout", "rrelu",
    "class_center_sample", "gumbel_softmax", "standard_gamma",
    # spec sample sits at a non-differentiable point (dist: x == y so
    # ||x-y|| is at the norm's kink) or an eps-sized FD step crosses an
    # argmax selection boundary (reduce max/min with close value pairs)
    "dist", "max", "min", "amax", "amin",
    # API mutates Tensor state in place (raw-array call unsupported)
    "increment", "batch_norm", "sync_batch_norm_",
    # host-side graph message passing (converts to numpy internally)
    "send_ue_recv",
}


# eligible-by-input specs whose outputs carry no real float Tensor to
# project (complex-valued: eig/fft_r2c/as_complex; integer/bool: shape,
# numel, cast-to-int, is_empty, binomial; rank outputs) — the real
# dot-product test is undefined there, so they stay forward-only
NO_FLOAT_OUTPUT = {
    "as_complex", "binomial", "cast", "complex", "eig", "eigvals",
    "fft_r2c", "is_empty", "matrix_rank_atol_rtol", "matrix_rank_tol",
    "numel", "shape", "view_dtype",
}


def _float_leaves(args):
    """Paths of perturbable float arrays in the positional args: (i,
    None) for a top-level ndarray, (i, j) for an element of a
    list/tuple arg (concat/stack/multi_dot-style multi-tensor ops)."""
    paths = []
    for i, a in enumerate(args):
        if isinstance(a, np.ndarray) \
                and np.issubdtype(a.dtype, np.floating):
            paths.append((i, None))
        elif isinstance(a, (list, tuple)):
            for j, e in enumerate(a):
                if isinstance(e, np.ndarray) \
                        and np.issubdtype(e.dtype, np.floating):
                    paths.append((i, j))
    return paths


def _leaf_get(args, path):
    i, j = path
    return args[i] if j is None else args[i][j]


def _leaf_set(args, path, val):
    i, j = path
    if j is None:
        args[i] = val
    else:
        sub = list(args[i])
        sub[j] = val
        args[i] = sub


def check_grad_spec(spec: ExecSpec, eps: float = 1e-2,
                    tol: float = 3e-2):
    """Dot-product grad test for one spec.  Returns True when the check
    RAN, False when the spec is ineligible (custom body, no float
    inputs, skip-listed op, or non-scalar-projectable outputs)."""
    if spec.custom is not None or spec.sample is None \
            or spec.op in GRAD_CHECK_SKIP:
        return False
    import jax
    import jax.numpy as jnp
    fn = _resolve(spec.api)
    args, kwargs = spec.sample()
    paths = _float_leaves(args)
    if not paths:
        return False
    rs = _rs(_seed_of("gradchk", spec.op))
    dirs = [rs.randn(*_leaf_get(args, p).shape).astype(np.float64)
            for p in paths]
    proj = {}

    def scalar(*fvals):
        new_args = list(args)
        for p, v in zip(paths, fvals):
            _leaf_set(new_args, p, v)
        out = fn(*_to_tensors(new_args), **_to_tensors(dict(kwargs)))
        from ..framework.tensor import Tensor
        outs = out if isinstance(out, (list, tuple)) else [out]
        total = None
        for k, o in enumerate(outs):
            if not isinstance(o, Tensor):
                continue
            v = jnp.asarray(o.value)
            if not jnp.issubdtype(v.dtype, jnp.floating):
                continue
            if k not in proj:
                proj[k] = np.asarray(
                    _rs(_seed_of("gradw", spec.op, k)).randn(*v.shape),
                    np.float32)
            term = jnp.sum(v.astype(jnp.float32) * proj[k])
            total = term if total is None else total + term
        return total

    vals32 = [jnp.asarray(_leaf_get(args, p), jnp.float32)
              for p in paths]
    probe = scalar(*vals32)
    if probe is None:
        return False
    g = jax.grad(scalar, argnums=tuple(range(len(vals32))))(*vals32)
    ad = float(sum(np.sum(np.asarray(gi, np.float64) * d)
                   for gi, d in zip(g, dirs)))
    # numeric side via two forward evals
    def at(t):
        shifted = [jnp.asarray(_leaf_get(args, p) + t * eps * d,
                               jnp.float32)
                   for p, d in zip(paths, dirs)]
        return float(np.asarray(scalar(*shifted)))
    fd = (at(+1.0) - at(-1.0)) / (2.0 * eps)
    scale = max(1.0, abs(fd), abs(ad))
    assert abs(fd - ad) <= tol * scale, \
        (spec.op, fd, ad, abs(fd - ad) / scale)
    return True


# ops whose gradients are pinned by TARGETED tests at constructed safe
# points instead of the generic sweep (tests/test_op_grad_exec.py
# TestSkipListedGradsAtSafePoints): selection scatters at distinct
# values, zero-grads of piecewise-constant ops, reinterpret
# pass-throughs, RNN/FFT directional derivatives, dropout's scaled-mask
# relation.  Consumed by tools/op_audit.py's backward accounting.
GRAD_CHECKED_TARGETED = {
    "max", "min", "dist", "ceil", "floor", "round", "sign", "cast",
    "complex", "real", "imag", "as_complex", "as_real",
    "topk", "kthvalue", "mode", "nanmedian", "argsort",
    "dropout", "lstm", "gru", "rnn", "fill", "view_dtype",
    "fft_c2c", "fft_r2c", "fft_c2r",
}


def grad_checked_yaml_names():
    """Yaml names whose derived gradient is numerically verified (used
    by tools/op_audit.py's backward.yaml accounting): the dot-product
    sweep's eligible set — check_grad_spec's eligibility including the
    float-INPUT probe (sample() is cheap), minus NO_FLOAT_OUTPUT —
    UNION the GRAD_CHECKED_TARGETED ops pinned by safe-point tests in
    tests/test_op_grad_exec.py (those are in GRAD_CHECK_SKIP and never
    run through the sweep)."""
    out = set()
    for s in EXEC_SPECS:
        if s.custom is not None or s.sample is None \
                or s.op in GRAD_CHECK_SKIP \
                or s.op in NO_FLOAT_OUTPUT:
            continue
        try:
            args, _ = s.sample()
        except Exception:
            continue
        if _float_leaves(args):
            out.add(s.op)
    out |= GRAD_CHECKED_TARGETED
    return out


# ---------------------------------------------------------------------------
# samples shared below
# ---------------------------------------------------------------------------
def _s(*shape):
    """Distinct-valued float sample (stable argsort/topk indices)."""
    x = _n(*shape).ravel()
    order = np.argsort(x, kind="stable")
    ranks = np.empty_like(order)
    ranks[order] = np.arange(x.size)
    return (x + ranks * 1e-4).reshape(shape).astype(np.float32)


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


# ===========================================================================
# unary elementwise
# ===========================================================================
E("abs", "abs", lambda: ([_n(3, 4)], {}), np.abs)
E("acos", "acos", lambda: ([_u(-0.9, 0.9, 3, 4)], {}), np.arccos)
E("asin", "asin", lambda: ([_u(-0.9, 0.9, 3, 4)], {}), np.arcsin)
E("atan", "atan", lambda: ([_n(3, 4)], {}), np.arctan)
E("cos", "cos", lambda: ([_n(3, 4)], {}), np.cos)
E("cosh", "cosh", lambda: ([_n(3, 4)], {}), np.cosh)
E("sin", "sin", lambda: ([_n(3, 4)], {}), np.sin)
E("sinh", "sinh", lambda: ([_n(3, 4)], {}), np.sinh)
E("tan", "tan", lambda: ([_u(-1.0, 1.0, 3, 4)], {}), np.tan)
E("exp", "exp", lambda: ([_n(3, 4)], {}), np.exp)
E("log", "log", lambda: ([_u(0.1, 3.0, 3, 4)], {}), np.log)
E("log10", "log10", lambda: ([_u(0.1, 3.0, 3, 4)], {}), np.log10)
E("log1p", "log1p", lambda: ([_u(-0.5, 3.0, 3, 4)], {}), np.log1p)
E("log2", "log2", lambda: ([_u(0.1, 3.0, 3, 4)], {}), np.log2)
E("ceil", "ceil", lambda: ([_n(3, 4)], {}), np.ceil)
E("floor", "floor", lambda: ([_n(3, 4)], {}), np.floor)
E("round", "round", lambda: ([_u(0.6, 5.3, 3, 4)], {}),
  lambda x: np.round(x))
E("sqrt", "sqrt", lambda: ([_u(0.1, 4.0, 3, 4)], {}), np.sqrt)
E("square", "square", lambda: ([_n(3, 4)], {}), np.square)
E("sign", "sign", lambda: ([_n(3, 4)], {}), np.sign)
E("reciprocal", "reciprocal", lambda: ([_u(0.5, 2.0, 3, 4)], {}),
  lambda x: 1.0 / x)
E("sigmoid", "sigmoid", lambda: ([_n(3, 4)], {}), _sigmoid)
E("isfinite", "isfinite",
  lambda: ([np.float32([1.0, np.inf, -np.inf, np.nan, 0.0])], {}),
  np.isfinite)
E("isinf", "isinf",
  lambda: ([np.float32([1.0, np.inf, -np.inf, np.nan, 0.0])], {}),
  np.isinf)
E("isnan", "isnan",
  lambda: ([np.float32([1.0, np.inf, -np.inf, np.nan, 0.0])], {}),
  np.isnan)
E("logical_not", "logical_not", lambda: ([_b(3, 4)], {}),
  np.logical_not)
E("bitwise_not", "bitwise_not", lambda: ([_i(-50, 50, 3, 4)], {}),
  np.bitwise_not)

# activations
E("relu", "nn.functional.relu", lambda: ([_n(3, 4)], {}),
  lambda x: np.maximum(x, 0))
E("relu6", "nn.functional.relu6", lambda: ([_u(-2, 8, 3, 4)], {}),
  lambda x: np.clip(x, 0, 6))
E("silu", "nn.functional.silu", lambda: ([_n(3, 4)], {}),
  lambda x: x * _sigmoid(x))
E("swish", "nn.functional.silu", lambda: ([_n(3, 4)], {}),
  lambda x: x * _sigmoid(x))
E("gelu", "nn.functional.gelu", lambda: ([_n(3, 4)], {}),
  lambda x: x * 0.5 * (1 + sps.erf(x / np.sqrt(2))))
E("celu", "nn.functional.celu", lambda: ([_n(3, 4)], {"alpha": 1.2}),
  lambda x, alpha: np.maximum(0, x)
  + np.minimum(0, alpha * (np.exp(x / alpha) - 1)))
E("elu", "nn.functional.elu", lambda: ([_n(3, 4)], {"alpha": 1.1}),
  lambda x, alpha: np.where(x > 0, x, alpha * (np.exp(x) - 1)))
E("selu", "nn.functional.selu", lambda: ([_n(3, 4)], {}),
  lambda x: 1.0507009873554805 * np.where(
      x > 0, x, 1.6732632423543772 * (np.exp(x) - 1)))
E("mish", "nn.functional.mish", lambda: ([_n(3, 4)], {}),
  lambda x: x * np.tanh(np.log1p(np.exp(x))))
E("softplus", "nn.functional.softplus", lambda: ([_n(3, 4)], {}),
  lambda x: np.log1p(np.exp(x)))
E("softsign", "nn.functional.softsign", lambda: ([_n(3, 4)], {}),
  lambda x: x / (1 + np.abs(x)))
E("softshrink", "nn.functional.softshrink",
  lambda: ([_n(3, 4)], {"threshold": 0.4}),
  lambda x, threshold: np.sign(x) * np.maximum(np.abs(x) - threshold, 0))
E("hardshrink", "nn.functional.hardshrink",
  lambda: ([_n(3, 4)], {"threshold": 0.4}),
  lambda x, threshold: x * (np.abs(x) > threshold))
E("hardsigmoid", "nn.functional.hardsigmoid",
  lambda: ([_n(3, 4)], {}),
  lambda x: np.clip(x / 6.0 + 0.5, 0, 1))
E("hardtanh", "nn.functional.hardtanh", lambda: ([_n(3, 4) * 2], {}),
  lambda x: np.clip(x, -1, 1))
E("leaky_relu", "nn.functional.leaky_relu",
  lambda: ([_n(3, 4)], {"negative_slope": 0.1}),
  lambda x, negative_slope: np.where(x > 0, x, negative_slope * x))
E("logsigmoid", "nn.functional.log_sigmoid", lambda: ([_n(3, 4)], {}),
  lambda x: -np.log1p(np.exp(-x)))
E("tanh", "tanh", lambda: ([_n(3, 4)], {}), np.tanh)
E("tanh_shrink", "nn.functional.tanhshrink", lambda: ([_n(3, 4)], {}),
  lambda x: x - np.tanh(x))
E("stanh", "stanh", lambda: ([_n(3, 4)], {}),
  lambda x: 1.7159 * np.tanh(0.67 * x))
E("thresholded_relu", "nn.functional.thresholded_relu",
  lambda: ([_n(3, 4) * 2], {}), lambda x: np.where(x > 1.0, x, 0.0))
E("prelu", "nn.functional.prelu",
  lambda: ([_n(2, 3, 4, 5), np.float32([0.1, 0.2, 0.3])], {}),
  lambda x, w: np.where(x > 0, x, w.reshape(1, 3, 1, 1) * x))
E("maxout", "nn.functional.maxout",
  lambda: ([_n(2, 6, 4, 5)], {"groups": 2}),
  lambda x, groups: x.reshape(2, 3, 2, 4, 5).max(axis=2))
E("rrelu", "nn.functional.rrelu",
  lambda: ([_n(3, 4)], {"lower": 0.1, "upper": 0.3, "training": False}),
  lambda x, lower, upper, training: np.where(
      x >= 0, x, x * (lower + upper) / 2))
E("log_softmax", "nn.functional.log_softmax",
  lambda: ([_n(3, 4)], {"axis": -1}),
  lambda x, axis: np.log(_softmax(x, axis)))

# ===========================================================================
# binary / ternary elementwise
# ===========================================================================
E("pow", "pow", lambda: ([_u(0.2, 2.0, 3, 4)], {"y": 2.5}),
  lambda x, y: x ** y)
E("bitwise_and", "bitwise_and",
  lambda: ([_i(-50, 50, 3, 4), _i(-50, 50, 4)], {}), np.bitwise_and)
E("bitwise_or", "bitwise_or",
  lambda: ([_i(-50, 50, 3, 4), _i(-50, 50, 4)], {}), np.bitwise_or)
E("bitwise_xor", "bitwise_xor",
  lambda: ([_i(-50, 50, 3, 4), _i(-50, 50, 4)], {}), np.bitwise_xor)
E("bitwise_left_shift", "bitwise_left_shift",
  lambda: ([_i(0, 50, 3, 4), _i(0, 5, 3, 4)], {}), np.left_shift)
E("bitwise_right_shift", "bitwise_right_shift",
  lambda: ([_i(0, 50, 3, 4), _i(0, 5, 3, 4)], {}), np.right_shift)
E("logical_and", "logical_and", lambda: ([_b(3, 4), _b(3, 4)], {}),
  np.logical_and)
E("logical_or", "logical_or", lambda: ([_b(3, 4), _b(3, 4)], {}),
  np.logical_or)
E("logical_xor", "logical_xor", lambda: ([_b(3, 4), _b(3, 4)], {}),
  np.logical_xor)
E("dot", "dot", lambda: ([_n(5), _n(5)], {}), np.dot)
E("cross", "cross", lambda: ([_n(4, 3), _n(4, 3)], {"axis": 1}),
  lambda x, y, axis: np.cross(x, y, axis=axis))
E("dist", "dist", lambda: ([_n(3, 4), _n(3, 4)], {"p": 2}),
  lambda x, y, p: np.linalg.norm((x - y).ravel(), ord=p))
E("kron", "kron", lambda: ([_n(2, 3), _n(3, 2)], {}), np.kron)
E("lerp", "lerp", lambda: ([_n(3, 4), _n(3, 4), 0.3], {}),
  lambda x, y, w: x + w * (y - x))
E("mv", "mv", lambda: ([_n(3, 4), _n(4)], {}), np.matmul)
E("bmm", "bmm", lambda: ([_n(2, 3, 4), _n(2, 4, 5)], {}), np.matmul)
E("addmm", "addmm",
  lambda: ([_n(3, 5), _n(3, 4), _n(4, 5)],
           {"beta": 0.7, "alpha": 1.3}),
  lambda inp, x, y, beta, alpha: beta * inp + alpha * (x @ y))
E("allclose", "allclose",
  lambda: ([np.float32([1.0, 2.0]), np.float32([1.0, 2.0 + 1e-9])], {}),
  lambda x, y: np.allclose(x, y))
E("isclose", "isclose",
  lambda: ([np.float32([1.0, 2.0, 3.0]),
            np.float32([1.0, 2.5, 3.0 + 1e-9])], {}),
  lambda x, y: np.isclose(x, y))
E("equal_all", "equal_all",
  lambda: ([_i(0, 5, 3, 4), _i(0, 5, 3, 4)], {}),
  lambda x, y: np.array_equal(x, y))
E("where", "where", lambda: ([_b(3, 4), _n(3, 4), _n(3, 4)], {}),
  lambda c, x, y: np.where(c, x, y))
E("clip", "clip", lambda: ([_n(3, 4) * 2], {"min": -1.0, "max": 0.5}),
  lambda x, min, max: np.clip(x, min, max))
E("scale", "scale",
  lambda: ([_n(3, 4)], {"scale": 2.0, "bias": 1.5}),
  lambda x, scale, bias: scale * x + bias)
E("increment", "increment", lambda: ([_n(3)], {"value": 2.0}),
  lambda x, value: x + value)

# ===========================================================================
# reductions / argsort family
# ===========================================================================
E("all", "all", lambda: ([_b(3, 4)], {"axis": 1}),
  lambda x, axis: np.all(x, axis=axis))
E("any", "any", lambda: ([_b(3, 4)], {"axis": 1}),
  lambda x, axis: np.any(x, axis=axis))
E("max", "max", lambda: ([_n(3, 4)], {"axis": 1}),
  lambda x, axis: np.max(x, axis=axis))
E("mean", "mean", lambda: ([_n(3, 4)], {"axis": 1}),
  lambda x, axis: np.mean(x, axis=axis))
E("mean_all", "mean", lambda: ([_n(3, 4)], {}), np.mean)
E("identity_loss", "mean", lambda: ([_n(3, 4)], {}), np.mean)
E("sum", "sum", lambda: ([_n(3, 4)], {"axis": 0}),
  lambda x, axis: np.sum(x, axis=axis))
E("prod", "prod", lambda: ([_u(0.5, 1.5, 3, 4)], {"axis": 1}),
  lambda x, axis: np.prod(x, axis=axis))
E("norm", "norm", lambda: ([_n(3, 4)], {}),
  lambda x: np.linalg.norm(x.ravel()))
E("p_norm", "norm", lambda: ([_n(3, 4)], {"p": 3, "axis": 1}),
  lambda x, p, axis: np.linalg.norm(x, ord=p, axis=axis))
E("frobenius_norm", "norm", lambda: ([_n(3, 4)], {}),
  lambda x: np.linalg.norm(x.ravel()))
E("squared_l2_norm", "norm", lambda: ([_n(3, 4)], {}),
  lambda x: np.linalg.norm(x.ravel()))
E("nanmedian", "nanmedian",
  lambda: ([np.float32([[1, np.nan, 3, 7], [2, 4, np.nan, 8]])],
           {"axis": 1}),
  lambda x, axis: np.nanmedian(x, axis=axis))
E("cumsum", "cumsum", lambda: ([_n(3, 4)], {"axis": 1}),
  lambda x, axis: np.cumsum(x, axis=axis))
E("cumprod", "cumprod", lambda: ([_u(0.5, 1.5, 3, 4)], {"dim": 1}),
  lambda x, dim: np.cumprod(x, axis=dim))
E("cummax", "cummax", lambda: ([_s(3, 5)], {"axis": 1}), sel=0,
  ref=lambda x, axis: np.maximum.accumulate(x, axis=axis))
E("cummin", "cummin", lambda: ([_s(3, 5)], {"axis": 1}), sel=0,
  ref=lambda x, axis: np.minimum.accumulate(x, axis=axis))
E("argmax", "argmax", lambda: ([_s(3, 5)], {"axis": 1}),
  lambda x, axis: np.argmax(x, axis=axis))
E("argmin", "argmin", lambda: ([_s(3, 5)], {"axis": 1}),
  lambda x, axis: np.argmin(x, axis=axis))
E("argsort", "argsort", lambda: ([_s(3, 5)], {"axis": 1}),
  lambda x, axis: np.argsort(x, axis=axis))
E("topk", "topk", lambda: ([_s(3, 6)], {"k": 3}),
  lambda x, k: (np.sort(x, axis=-1)[:, ::-1][:, :k],
                np.argsort(-x, axis=-1)[:, :k]))
E("kthvalue", "kthvalue", lambda: ([_s(3, 6)], {"k": 2}),
  lambda x, k: (np.sort(x, axis=-1)[:, 1],
                np.argsort(x, axis=-1)[:, 1]))
E("mode", "mode",
  lambda: ([np.float32([[1, 2, 2, 3], [5, 5, 4, 0], [7, 7, 7, 1]])],
           {}), sel=0,
  ref=lambda x: np.float32([2, 5, 7]))
E("logsumexp", "logsumexp", lambda: ([_n(3, 4)], {"axis": 1}),
  lambda x, axis: np.log(np.sum(np.exp(x), axis=axis)))

# ===========================================================================
# shape / manipulation
# ===========================================================================
E("cast", "cast", lambda: ([_n(3, 4)], {"dtype": "int32"}),
  lambda x, dtype: x.astype(np.int32))
E("concat", "concat", lambda: ([[_n(2, 3), _n(2, 3), _n(1, 3)]],
                               {"axis": 0}),
  lambda xs, axis: np.concatenate(xs, axis=axis))
E("stack", "stack", lambda: ([[_n(2, 3), _n(2, 3)]], {"axis": 1}),
  lambda xs, axis: np.stack(xs, axis=axis))
E("reshape", "reshape", lambda: ([_n(3, 4)], {"shape": [2, 6]}),
  lambda x, shape: x.reshape(shape))
E("transpose", "transpose",
  lambda: ([_n(2, 3, 4)], {"perm": [2, 0, 1]}),
  lambda x, perm: np.transpose(x, perm))
E("trans_layout", "transpose",
  lambda: ([_n(2, 3, 4)], {"perm": [2, 0, 1]}),
  lambda x, perm: np.transpose(x, perm))
E("squeeze", "squeeze", lambda: ([_n(3, 1, 4)], {"axis": 1}),
  lambda x, axis: np.squeeze(x, axis=axis))
E("unsqueeze", "unsqueeze", lambda: ([_n(3, 4)], {"axis": 1}),
  lambda x, axis: np.expand_dims(x, axis))
E("flatten", "flatten",
  lambda: ([_n(2, 3, 4)], {"start_axis": 1, "stop_axis": 2}),
  lambda x, start_axis, stop_axis: x.reshape(2, 12))
E("flip", "flip", lambda: ([_n(3, 4)], {"axis": 1}),
  lambda x, axis: np.flip(x, axis=axis))
E("reverse", "flip", lambda: ([_n(3, 4)], {"axis": 0}),
  lambda x, axis: np.flip(x, axis=axis))
E("roll", "roll", lambda: ([_n(3, 4)], {"shifts": 2, "axis": 1}),
  lambda x, shifts, axis: np.roll(x, shifts, axis=axis))
E("tril", "tril", lambda: ([_n(4, 4)], {"diagonal": -1}),
  lambda x, diagonal: np.tril(x, k=diagonal))
E("triu", "triu", lambda: ([_n(4, 4)], {"diagonal": 1}),
  lambda x, diagonal: np.triu(x, k=diagonal))
E("diag", "diag", lambda: ([_n(4)], {"offset": 1}),
  lambda x, offset: np.diag(x, k=offset))
E("diagonal", "diagonal", lambda: ([_n(3, 4, 4)],
                                   {"offset": 0, "axis1": 1, "axis2": 2}),
  lambda x, offset, axis1, axis2: np.diagonal(x, offset, axis1, axis2))
E("trace", "trace", lambda: ([_n(4, 4)], {"offset": 1}),
  lambda x, offset: np.trace(x, offset=offset))
E("split", "split", lambda: ([_n(6, 4)], {"num_or_sections": 3}),
  lambda x, num_or_sections: tuple(np.split(x, 3, axis=0)))
E("split_with_num", "split",
  lambda: ([_n(6, 4)], {"num_or_sections": 2, "axis": 1}),
  lambda x, num_or_sections, axis: tuple(np.split(x, 2, axis=1)))
E("unbind", "unbind", lambda: ([_n(3, 4)], {"axis": 0}),
  lambda x, axis: tuple(x[i] for i in range(3)))
E("unstack", "unstack", lambda: ([_n(3, 4)], {"axis": 1}),
  lambda x, axis: tuple(x[:, i] for i in range(4)))
E("expand", "expand", lambda: ([_n(1, 4)], {"shape": [3, 4]}),
  lambda x, shape: np.broadcast_to(x, shape))
E("expand_as", "expand_as", lambda: ([_n(1, 4), _n(3, 4)], {}),
  lambda x, y: np.broadcast_to(x, y.shape))
E("slice", "slice",
  lambda: ([_n(4, 5)], {"axes": [0, 1], "starts": [1, 0],
                        "ends": [3, 4]}),
  lambda x, axes, starts, ends: x[1:3, 0:4])
E("strided_slice", "strided_slice",
  lambda: ([_n(6, 5)], {"axes": [0], "starts": [0], "ends": [6],
                        "strides": [2]}),
  lambda x, axes, starts, ends, strides: x[0:6:2])
E("crop", "crop",
  lambda: ([_n(4, 5)], {"shape": [2, 3], "offsets": [1, 1]}),
  lambda x, shape, offsets: x[1:3, 1:4])
E("repeat_interleave", "repeat_interleave",
  lambda: ([_n(3, 4)], {"repeats": 2, "axis": 1}),
  lambda x, repeats, axis: np.repeat(x, repeats, axis=axis))
E("repeat_interleave_with_tensor_index", "repeat_interleave",
  lambda: ([_n(3), np.int64([1, 2, 3])], {"axis": 0}),
  lambda x, r, axis: np.repeat(x, r, axis=axis))
E("meshgrid", "meshgrid", lambda: ([_n(3), _n(4)], {}),
  lambda x, y: tuple(np.meshgrid(x, y, indexing="ij")))
E("tensor_unfold", "unfold",
  lambda: ([_n(8)], {"axis": 0, "size": 3, "step": 2}),
  lambda x, axis, size, step: np.stack(
      [x[i:i + 3] for i in range(0, 6, 2)]))
E("as_strided", "as_strided",
  lambda: ([_n(12)], {"shape": [3, 4], "stride": [4, 1]}),
  lambda x, shape, stride: x.reshape(3, 4))
E("view_shape", "view", lambda: ([_n(3, 4)], {"shape_or_dtype": [4, 3]}),
  lambda x, shape_or_dtype: x.reshape(4, 3))
E("view_dtype", "view",
  lambda: ([_n(3, 4)], {"shape_or_dtype": "int32"}),
  lambda x, shape_or_dtype: x.view(np.int32))
E("multiplex", "multiplex",
  lambda: ([[_n(4, 3), _n(4, 3)], _i(0, 2, 4, 1)], {}),
  lambda ins, idx: np.stack(
      [ins[idx[i, 0]][i] for i in range(4)]))
E("broadcast_tensors", "broadcast_tensors",
  lambda: ([[_n(1, 4), _n(3, 1)]], {}),
  lambda xs: tuple(np.broadcast_arrays(*xs)))
E("numel", "numel", lambda: ([_n(3, 4)], {}),
  lambda x: np.int64(12))
E("shape", "shape", lambda: ([_n(3, 4)], {}),
  lambda x: np.int64([3, 4]), note="shape-as-tensor op")
E("is_empty", "is_empty", lambda: ([np.zeros((0, 3), np.float32)], {}),
  lambda x: np.array(True))

# ===========================================================================
# indexing / scatter / gather
# ===========================================================================
E("gather", "gather", lambda: ([_n(5, 3), np.int64([0, 2, 4])],
                               {"axis": 0}),
  lambda x, idx, axis: x[idx])
E("gather_nd", "gather_nd",
  lambda: ([_n(3, 4), np.int64([[0, 1], [2, 3]])], {}),
  lambda x, idx: x[idx[:, 0], idx[:, 1]])
E("scatter", "scatter",
  lambda: ([_n(5, 3), np.int64([1, 3]), _n(2, 3) + 10], {}),
  lambda x, idx, upd: _np_scatter(x, idx, upd))
E("scatter_nd_add", "scatter_nd_add",
  lambda: ([_n(4, 3), np.int64([[0], [2], [0]]), _n(3, 3)], {}),
  lambda x, idx, upd: _np_scatter_nd_add(x, idx, upd))
E("index_select", "index_select",
  lambda: ([_n(4, 5), np.int64([0, 2])], {"axis": 1}),
  lambda x, idx, axis: x[:, idx])
E("index_select_strided", "index_select",
  lambda: ([_n(4, 5), np.int64([3, 1])], {"axis": 0}),
  lambda x, idx, axis: x[idx])
E("index_add", "index_add",
  lambda: ([_n(4, 3), np.int64([1, 1, 3]), 0, _n(3, 3)], {}),
  lambda x, idx, axis, v: _np_index_add(x, idx, axis, v))
E("index_put", "index_put",
  lambda: ([_n(4, 3), [np.int64([0, 2]), np.int64([1, 2])],
            np.float32([9.0, 8.0])], {}),
  lambda x, idx, v: _np_index_put(x, idx, v))
E("index_sample", "index_sample",
  lambda: ([_n(3, 5), _i(0, 5, 3, 2)], {}),
  lambda x, idx: np.take_along_axis(x, idx, axis=1))
E("take_along_axis", "take_along_axis",
  lambda: ([_n(3, 5), _i(0, 5, 3, 2), 1], {}),
  lambda x, idx, axis: np.take_along_axis(x, idx, axis=axis))
E("put_along_axis", "put_along_axis",
  lambda: ([_n(3, 5), _i(0, 5, 3, 2), _n(3, 2) + 5, 1], {}),
  lambda x, idx, v, axis: _np_put_along_axis(x, idx, v, axis))
E("masked_select", "masked_select",
  lambda: ([_n(3, 4), _b(3, 4)], {}), lambda x, m: x[m])
E("nonzero", "nonzero",
  lambda: ([np.float32([[0, 1, 0], [2, 0, 3]])], {}),
  lambda x: np.argwhere(x != 0))
E("one_hot", "nn.functional.one_hot",
  lambda: ([_i(0, 5, 4)], {"num_classes": 5}),
  lambda x, num_classes: np.eye(num_classes, dtype=np.float32)[x])
E("shard_index", "shard_index",
  lambda: ([_i(0, 20, 6, 1)], {"index_num": 20, "nshards": 2,
                               "shard_id": 0, "ignore_value": -1}),
  lambda x, index_num, nshards, shard_id, ignore_value: np.where(
      (x >= 0) & (x < 10), x, ignore_value))
E("bincount", "bincount", lambda: ([_i(0, 6, 20)], {"minlength": 8}),
  lambda x, minlength: np.bincount(x, minlength=minlength))
E("histogram", "histogram",
  lambda: ([_u(0.0, 4.0, 30)], {"bins": 4, "min": 0, "max": 4}),
  lambda x, bins, min, max: np.histogram(x, bins=bins,
                                         range=(min, max))[0])
E("searchsorted", "searchsorted",
  lambda: ([np.float32([1, 3, 5, 7]), _u(0.0, 8.0, 6)], {}),
  lambda s, v: np.searchsorted(s, v).astype(np.int64))
E("unique_consecutive", "unique_consecutive",
  lambda: ([np.float32([1, 1, 2, 2, 2, 3, 1])], {}),
  lambda x: np.float32([1, 2, 3, 1]))
E("label_smooth", "nn.functional.label_smooth",
  lambda: ([np.eye(4, dtype=np.float32)], {"epsilon": 0.1}),
  lambda label, epsilon: (1 - epsilon) * label + epsilon / 4)


def _np_scatter(x, idx, upd):
    out = x.copy()
    out[idx] = upd
    return out


def _np_scatter_nd_add(x, idx, upd):
    out = x.copy()
    np.add.at(out, tuple(idx.T), upd)
    return out


def _np_index_add(x, idx, axis, v):
    out = x.copy()
    np.add.at(out, idx, v)
    return out


def _np_index_put(x, idx, v):
    out = x.copy()
    out[tuple(idx)] = v
    return out


def _np_put_along_axis(x, idx, v, axis):
    out = x.copy()
    np.put_along_axis(out, idx, v, axis)
    return out


# ===========================================================================
# creation
# ===========================================================================
E("ones", "ones", lambda: ([], {"shape": [3, 4]}),
  lambda shape: np.ones(shape, np.float32))
E("ones_like", "ones_like", lambda: ([_n(3, 4)], {}),
  lambda x: np.ones_like(x))
E("zeros", "zeros", lambda: ([], {"shape": [3, 4]}),
  lambda shape: np.zeros(shape, np.float32))
E("zeros_like", "zeros_like", lambda: ([_n(3, 4)], {}),
  lambda x: np.zeros_like(x))
E("eye", "eye", lambda: ([], {"num_rows": 3, "num_columns": 5}),
  lambda num_rows, num_columns: np.eye(3, 5, dtype=np.float32))
E("full", "full", lambda: ([], {"shape": [2, 3], "fill_value": 7.5}),
  lambda shape, fill_value: np.full(shape, fill_value, np.float32))
E("full_like", "full_like", lambda: ([_n(2, 3)], {"fill_value": 2.5}),
  lambda x, fill_value: np.full_like(x, fill_value))
E("full_int_array", "full",
  lambda: ([], {"shape": [4], "fill_value": 3, "dtype": "int64"}),
  lambda shape, fill_value, dtype: np.full(shape, 3, np.int64))
E("full_batch_size_like", "full_like",
  lambda: ([_n(2, 3)], {"fill_value": 1.5}),
  lambda x, fill_value: np.full_like(x, fill_value))
E("linspace", "linspace",
  lambda: ([], {"start": 0.0, "stop": 1.0, "num": 5}),
  lambda start, stop, num: np.linspace(0, 1, 5, dtype=np.float32))
E("logspace", "logspace",
  lambda: ([], {"start": 0.0, "stop": 3.0, "num": 4}),
  lambda start, stop, num: np.logspace(0, 3, 4, dtype=np.float32))
E("tril_indices", "tril_indices",
  lambda: ([], {"row": 4, "col": 4, "offset": 0}),
  lambda row, col, offset: np.stack(np.tril_indices(4, 0, 4)))
E("triu_indices", "triu_indices",
  lambda: ([], {"row": 4, "col": 4, "offset": 0}),
  lambda row, col, offset: np.stack(np.triu_indices(4, 0, 4)))
E("empty", "empty", lambda: ([], {"shape": [3, 4]}),
  check=lambda out, a, k: _check_shape_dtype(out, (3, 4), np.float32),
  note="values unspecified by contract; shape/dtype checked")
E("empty_like", "empty_like", lambda: ([_n(3, 4)], {}),
  check=lambda out, a, k: _check_shape_dtype(out, (3, 4), np.float32),
  note="values unspecified by contract; shape/dtype checked")
E("assign", "assign", lambda: ([_n(3, 4)], {}), lambda x: x)
E("assign_out_", "assign", lambda: ([_n(3, 4)], {}), lambda x: x)
E("assign_value_", "assign", lambda: ([_n(2, 2)], {}), lambda x: x)
E("share_data", "assign", lambda: ([_n(3)], {}), lambda x: x)
E("copy_to", "assign", lambda: ([_n(3)], {}), lambda x: x)


def _check_shape_dtype(out, shape, dtype):
    assert out.shape == tuple(shape), (out.shape, shape)
    assert out.dtype == dtype, (out.dtype, dtype)


# ===========================================================================
# nn: conv / pool / interp / shuffle (torch CPU as independent reference)
# ===========================================================================
def _torch():
    import torch
    return torch


def _t_ref(torch_fn):
    """Wrap a torch functional as a numpy-in/numpy-out reference."""
    def ref(*args, **kwargs):
        torch = _torch()
        targs = [torch.from_numpy(a) if isinstance(a, np.ndarray) else a
                 for a in args]
        out = torch_fn(torch, *targs, **kwargs)
        if isinstance(out, (tuple, list)):
            return tuple(o.numpy() for o in out)
        return out.numpy()
    return ref


E("conv2d", "nn.functional.conv2d",
  lambda: ([_n(2, 3, 6, 6), _n(4, 3, 3, 3), _n(4)],
           {"stride": 2, "padding": 1}),
  _t_ref(lambda t, x, w, b, stride, padding: t.nn.functional.conv2d(
      x, w, b, stride=stride, padding=padding)), atol=1e-4)
E("conv3d", "nn.functional.conv3d",
  lambda: ([_n(1, 2, 5, 5, 5), _n(3, 2, 3, 3, 3)], {"padding": 1}),
  _t_ref(lambda t, x, w, padding: t.nn.functional.conv3d(
      x, w, padding=padding)), atol=1e-4)
E("depthwise_conv2d", "nn.functional.conv2d",
  lambda: ([_n(1, 4, 6, 6), _n(4, 1, 3, 3)], {"groups": 4}),
  _t_ref(lambda t, x, w, groups: t.nn.functional.conv2d(
      x, w, groups=groups)), atol=1e-4)
E("conv2d_transpose", "nn.functional.conv2d_transpose",
  lambda: ([_n(1, 3, 4, 4), _n(3, 2, 3, 3)], {"stride": 2}),
  _t_ref(lambda t, x, w, stride: t.nn.functional.conv_transpose2d(
      x, w, stride=stride)), atol=1e-4)
E("conv2d_transpose_bias", "nn.functional.conv2d_transpose",
  lambda: ([_n(1, 3, 4, 4), _n(3, 2, 3, 3), _n(2)], {}),
  _t_ref(lambda t, x, w, b: t.nn.functional.conv_transpose2d(x, w, b)),
  atol=1e-4)
E("depthwise_conv2d_transpose", "nn.functional.conv2d_transpose",
  lambda: ([_n(1, 4, 4, 4), _n(4, 1, 3, 3)], {"groups": 4}),
  _t_ref(lambda t, x, w, groups: t.nn.functional.conv_transpose2d(
      x, w, groups=groups)), atol=1e-4)
E("conv3d_transpose", "nn.functional.conv3d_transpose",
  lambda: ([_n(1, 2, 3, 3, 3), _n(2, 3, 2, 2, 2)], {}),
  _t_ref(lambda t, x, w: t.nn.functional.conv_transpose3d(x, w)),
  atol=1e-4)
E("pool2d", "nn.functional.max_pool2d",
  lambda: ([_n(1, 2, 6, 6)], {"kernel_size": 2}),
  _t_ref(lambda t, x, kernel_size: t.nn.functional.max_pool2d(
      x, kernel_size)))
E("pool3d", "nn.functional.max_pool3d",
  lambda: ([_n(1, 2, 4, 4, 4)], {"kernel_size": 2}),
  _t_ref(lambda t, x, kernel_size: t.nn.functional.max_pool3d(
      x, kernel_size)))
E("bilinear_interp", "nn.functional.interpolate",
  lambda: ([_n(1, 2, 4, 4)], {"size": [8, 8], "mode": "bilinear"}),
  _t_ref(lambda t, x, size, mode: t.nn.functional.interpolate(
      x, size=size, mode=mode)), atol=1e-4)
E("nearest_interp", "nn.functional.interpolate",
  lambda: ([_n(1, 2, 4, 4)], {"size": [8, 8], "mode": "nearest"}),
  _t_ref(lambda t, x, size, mode: t.nn.functional.interpolate(
      x, size=size, mode=mode)))
E("bicubic_interp", "nn.functional.interpolate",
  lambda: ([_n(1, 2, 4, 4)], {"size": [8, 8], "mode": "bicubic"}),
  _t_ref(lambda t, x, size, mode: t.nn.functional.interpolate(
      x, size=size, mode=mode)), atol=1e-3)
E("trilinear_interp", "nn.functional.interpolate",
  lambda: ([_n(1, 2, 3, 3, 3)],
           {"size": [6, 6, 6], "mode": "trilinear",
            "data_format": "NCDHW"}),
  _t_ref(lambda t, x, size, mode, data_format: t.nn.functional
         .interpolate(x, size=size, mode=mode)), atol=1e-4)
E("linear_interp", "nn.functional.interpolate",
  lambda: ([_n(1, 2, 5)], {"size": [10], "mode": "linear",
                           "data_format": "NCW"}),
  _t_ref(lambda t, x, size, mode, data_format: t.nn.functional
         .interpolate(x, size=size, mode=mode)), atol=1e-4)
E("grid_sample", "grid_sample",
  lambda: ([_n(1, 2, 4, 4), _u(-0.9, 0.9, 1, 3, 3, 2)], {}),
  _t_ref(lambda t, x, g: t.nn.functional.grid_sample(
      x, g, align_corners=True)), atol=1e-4)
E("pixel_shuffle", "nn.functional.pixel_shuffle",
  lambda: ([_n(1, 8, 3, 3)], {"upscale_factor": 2}),
  _t_ref(lambda t, x, upscale_factor: t.nn.functional.pixel_shuffle(
      x, upscale_factor)))
E("pixel_unshuffle", "nn.functional.pixel_unshuffle",
  lambda: ([_n(1, 2, 6, 6)], {"downscale_factor": 2}),
  _t_ref(lambda t, x, downscale_factor: t.nn.functional.pixel_unshuffle(
      x, downscale_factor)))
E("channel_shuffle", "nn.functional.channel_shuffle",
  lambda: ([_n(1, 6, 3, 3)], {"groups": 2}),
  _t_ref(lambda t, x, groups: t.nn.functional.channel_shuffle(
      x, groups)))
E("unfold", "nn.functional.unfold",
  lambda: ([_n(1, 2, 4, 4)], {"kernel_sizes": 2, "strides": 2}),
  _t_ref(lambda t, x, kernel_sizes, strides: t.nn.functional.unfold(
      x, kernel_sizes, stride=strides)))
E("fold", "nn.functional.fold",
  lambda: ([_n(1, 8, 4)], {"output_sizes": [4, 4], "kernel_sizes": 2,
                           "strides": 2}),
  _t_ref(lambda t, x, output_sizes, kernel_sizes, strides:
         t.nn.functional.fold(x, output_sizes, kernel_sizes,
                              stride=strides)))
E("pad", "nn.functional.pad",
  lambda: ([_n(1, 2, 3, 4)], {"pad": [1, 0, 2, 1], "value": 1.5}),
  lambda x, pad, value: np.pad(
      x, ((0, 0), (0, 0), (2, 1), (1, 0)), constant_values=value))
E("bilinear", "nn.functional.bilinear",
  lambda: ([_n(5, 3), _n(5, 4), _n(6, 3, 4), _n(1, 6)], {}),
  lambda x1, x2, w, b: np.einsum("bi,oij,bj->bo", x1, w, x2) + b)
E("dropout", "nn.functional.dropout",
  lambda: ([_n(3, 4)], {"p": 0.5, "training": False}),
  lambda x, p, training: x)

# ===========================================================================
# nn: normalization
# ===========================================================================
E("rms_norm", "nn.functional.rms_norm",
  lambda: ([_n(3, 8), _u(0.5, 1.5, 8)], {}),
  lambda x, w: x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6) * w)
E("layer_norm", "nn.functional.layer_norm",
  lambda: ([_n(3, 8), 8, _u(0.5, 1.5, 8), _n(8)], {}),
  lambda x, ns, w, b: (x - x.mean(-1, keepdims=True))
  / np.sqrt(x.var(-1, keepdims=True) + 1e-5) * w + b)
E("group_norm", "nn.functional.group_norm",
  lambda: ([_n(2, 6, 3, 3), 2], {}),
  lambda x, g: _np_group_norm(x, g))
E("instance_norm", "nn.functional.instance_norm",
  lambda: ([_n(2, 3, 4, 4)], {}),
  lambda x: (x - x.mean((2, 3), keepdims=True))
  / np.sqrt(x.var((2, 3), keepdims=True) + 1e-5))
E("batch_norm", "nn.functional.batch_norm",
  lambda: ([_n(2, 3, 4, 4), np.float32([0.1, 0.2, 0.3]),
            _u(0.5, 1.5, 3), _u(0.5, 1.5, 3), _n(3)],
           {"training": False}),
  lambda x, m, v, w, b, training:
  (x - m.reshape(1, 3, 1, 1)) / np.sqrt(v.reshape(1, 3, 1, 1) + 1e-5)
  * w.reshape(1, 3, 1, 1) + b.reshape(1, 3, 1, 1))
E("sync_batch_norm_", "nn.functional.batch_norm",
  lambda: ([_n(2, 3, 4, 4), np.zeros(3, np.float32),
            np.ones(3, np.float32)], {"training": False}),
  lambda x, m, v, training: x / np.sqrt(1 + 1e-5))


def _np_group_norm(x, g):
    n, c, h, w = x.shape
    xg = x.reshape(n, g, c // g * h * w)
    mu = xg.mean(-1, keepdims=True)
    var = xg.var(-1, keepdims=True)
    return ((xg - mu) / np.sqrt(var + 1e-5)).reshape(x.shape)


# ===========================================================================
# nn: losses / softmax family / attention
# ===========================================================================
E("nll_loss", "nn.functional.nll_loss",
  lambda: ([np.log(_softmax(_n(5, 4))).astype(np.float32),
            _i(0, 4, 5)], {}),
  lambda x, y: -np.mean(x[np.arange(5), y]))
E("bce_loss", "nn.functional.binary_cross_entropy",
  lambda: ([_u(0.05, 0.95, 4, 3), _b(4, 3).astype(np.float32)], {}),
  lambda p, y: -np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))
E("kldiv_loss", "nn.functional.kl_div",
  lambda: ([np.log(_softmax(_n(4, 5))).astype(np.float32),
            _softmax(_n(4, 5)).astype(np.float32)], {}),
  lambda x, y: np.mean(y * (np.log(y) - x)))
E("log_loss", "nn.functional.log_loss",
  lambda: ([_u(0.05, 0.95, 6, 1), _b(6, 1).astype(np.float32)], {}),
  lambda p, y: -y * np.log(p + 1e-4) - (1 - y) * np.log(1 - p + 1e-4))
E("sigmoid_cross_entropy_with_logits",
  "nn.functional.binary_cross_entropy_with_logits",
  lambda: ([_n(4, 3), _b(4, 3).astype(np.float32)], {}),
  lambda x, y: np.mean(np.maximum(x, 0) - x * y + np.log1p(
      np.exp(-np.abs(x)))))
E("cross_entropy_with_softmax", "nn.functional.cross_entropy",
  lambda: ([_n(5, 4), _i(0, 4, 5)], {}),
  lambda x, y: -np.mean(np.log(_softmax(x)[np.arange(5), y])))
E("softmax_with_cross_entropy", "nn.functional.cross_entropy",
  lambda: ([_n(5, 4), _i(0, 4, 5)], {}),
  lambda x, y: -np.mean(np.log(_softmax(x)[np.arange(5), y])))
E("fused_softmax_mask", "nn.functional.softmax",
  lambda: ([_n(2, 3, 4, 4)], {}), lambda x: _softmax(x))
E("fused_softmax_mask_upper_triangle", "nn.functional.softmax",
  lambda: ([np.where(np.triu(np.ones((4, 4)), 1), -1e9,
                     _n(4, 4)).astype(np.float32)], {}),
  lambda x: _softmax(x))
E("gumbel_softmax", "nn.functional.gumbel_softmax",
  lambda: ([_n(6, 5)], {"hard": True}),
  check=lambda out, a, k: (
      np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5),
      np.testing.assert_array_equal(np.sort(np.unique(out)),
                                    np.float32([0.0, 1.0]))),
  note="stochastic; checks one-hot rows summing to 1")


def _np_sdpa(q, k, v, causal=False):
    # [b, s, h, d] paddle flash-attn layout
    qt, kt, vt = (np.moveaxis(a, 2, 1) for a in (q, k, v))
    s = np.einsum("bhqd,bhkd->bhqk", qt, kt) / np.sqrt(q.shape[-1])
    if causal:
        s = np.where(np.triu(np.ones(s.shape[-2:], bool), 1), -1e30, s)
    out = np.einsum("bhqk,bhkd->bhqd", _softmax(s), vt)
    return np.moveaxis(out, 1, 2).astype(np.float32)


E("flash_attn", "nn.functional.flash_attention",
  lambda: ([_n(2, 6, 2, 8), _n(2, 6, 2, 8), _n(2, 6, 2, 8)],
           {"causal": True}),
  lambda q, k, v, causal: _np_sdpa(q, k, v, causal), atol=1e-4, sel=0)
E("flash_attn_unpadded", "nn.functional.flash_attention",
  lambda: ([_n(1, 5, 2, 8), _n(1, 5, 2, 8), _n(1, 5, 2, 8)], {}),
  lambda q, k, v: _np_sdpa(q, k, v), atol=1e-4, sel=0)
E("flash_attn_qkvpacked", "nn.functional.flash_attention",
  lambda: ([_n(1, 4, 2, 8), _n(1, 4, 2, 8), _n(1, 4, 2, 8)], {}),
  lambda q, k, v: _np_sdpa(q, k, v), atol=1e-4, sel=0)
E("flash_attn_varlen_qkvpacked", "nn.functional.flash_attention",
  lambda: ([_n(1, 4, 1, 8), _n(1, 4, 1, 8), _n(1, 4, 1, 8)], {}),
  lambda q, k, v: _np_sdpa(q, k, v), atol=1e-4, sel=0)
E("flashmask_attention", "nn.functional.flash_attention",
  lambda: ([_n(1, 4, 2, 8), _n(1, 4, 2, 8), _n(1, 4, 2, 8)],
           {"causal": True}),
  lambda q, k, v, causal: _np_sdpa(q, k, v, causal), atol=1e-4, sel=0)
E("memory_efficient_attention", "nn.functional.flash_attention",
  lambda: ([_n(2, 4, 2, 8), _n(2, 4, 2, 8), _n(2, 4, 2, 8)], {}),
  lambda q, k, v: _np_sdpa(q, k, v), atol=1e-4, sel=0)
E("variable_length_memory_efficient_attention",
  "nn.functional.flash_attention",
  lambda: ([_n(1, 6, 2, 8), _n(1, 6, 2, 8), _n(1, 6, 2, 8)], {}),
  lambda q, k, v: _np_sdpa(q, k, v), atol=1e-4, sel=0)
E("calc_reduced_attn_scores", "nn.functional.flash_attention",
  lambda: ([_n(1, 6, 2, 8), _n(1, 6, 2, 8), _n(1, 6, 2, 8)],
           {"causal": True}),
  lambda q, k, v, causal: _np_sdpa(q, k, v, causal), atol=1e-4, sel=0)
E("swiglu", "incubate.nn.functional.swiglu",
  lambda: ([_n(3, 8)], {}),
  lambda x: (x[:, :4] * _sigmoid(x[:, :4])) * x[:, 4:])

# ===========================================================================
# rnn family (torch independent reference with copied weights)
# ===========================================================================


def _rnn_vs_torch(cls_name, torch_cls_name, gates):
    def custom():
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        torch = _torch()
        paddle.seed(7)
        m = getattr(nn, cls_name)(4, 6)
        tm = getattr(torch.nn, torch_cls_name)(4, 6, batch_first=True)
        sd = {k: np.asarray(v.value) for k, v in m.state_dict().items()}
        with torch.no_grad():
            tm.weight_ih_l0.copy_(torch.from_numpy(sd["cells_fw.0.weight_ih"]))
            tm.weight_hh_l0.copy_(torch.from_numpy(sd["cells_fw.0.weight_hh"]))
            tm.bias_ih_l0.copy_(torch.from_numpy(sd["cells_fw.0.bias_ih"]))
            tm.bias_hh_l0.copy_(torch.from_numpy(sd["cells_fw.0.bias_hh"]))
        x = _n(2, 5, 4)
        out, _ = m(paddle.to_tensor(x))
        with torch.no_grad():
            tout, _ = tm(torch.from_numpy(x))
        np.testing.assert_allclose(np.asarray(out.value), tout.numpy(),
                                   rtol=1e-4, atol=1e-4)
    return custom


E("rnn", "nn.SimpleRNN", custom=_rnn_vs_torch("SimpleRNN", "RNN", 1))
E("lstm", "nn.LSTM", custom=_rnn_vs_torch("LSTM", "LSTM", 4))
E("gru", "nn.GRU", custom=_rnn_vs_torch("GRU", "GRU", 3))


def _gru_unit_custom():
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    torch = _torch()
    paddle.seed(3)
    m = nn.GRUCell(4, 6)
    tm = torch.nn.GRUCell(4, 6)
    sd = {k: np.asarray(v.value) for k, v in m.state_dict().items()}
    with torch.no_grad():
        tm.weight_ih.copy_(torch.from_numpy(sd["weight_ih"]))
        tm.weight_hh.copy_(torch.from_numpy(sd["weight_hh"]))
        tm.bias_ih.copy_(torch.from_numpy(sd["bias_ih"]))
        tm.bias_hh.copy_(torch.from_numpy(sd["bias_hh"]))
    x, h = _n(3, 4), _n(3, 6)
    out, _ = m(paddle.to_tensor(x), paddle.to_tensor(h))
    with torch.no_grad():
        tout = tm(torch.from_numpy(x), torch.from_numpy(h))
    np.testing.assert_allclose(np.asarray(out.value), tout.numpy(),
                               rtol=1e-4, atol=1e-4)


E("gru_unit", "nn.GRUCell", custom=_gru_unit_custom)


# ===========================================================================
# linalg (property checks where the decomposition has sign/phase freedom)
# ===========================================================================
def _psd(n, seed=0):
    a = _rs(_seed_of("psd", n, seed)).randn(n, n).astype(np.float32)
    return a @ a.T + n * np.eye(n, dtype=np.float32)


E("cholesky", "linalg.cholesky", lambda: ([_psd(4)], {}),
  lambda a: np.linalg.cholesky(a), atol=1e-4)
E("cholesky_solve", "linalg.cholesky_solve",
  lambda: ([_n(4, 2), np.linalg.cholesky(_psd(4)).astype(np.float32)],
           {}),
  lambda b, l: np.linalg.solve(l @ l.T, b), atol=1e-3)
E("det", "linalg.det", lambda: ([_psd(4)], {}),
  lambda a: np.linalg.det(a), atol=1e-2)
E("slogdet", "linalg.slogdet", lambda: ([_psd(4)], {}),
  lambda a: np.stack(np.linalg.slogdet(a)), atol=1e-4)
E("inverse", "linalg.inv", lambda: ([_psd(4)], {}),
  lambda a: np.linalg.inv(a), atol=1e-4)
E("matrix_power", "linalg.matrix_power", lambda: ([_psd(3)], {"n": 3}),
  lambda a, n: np.linalg.matrix_power(a, n), atol=1e-2)
E("matrix_rank", "linalg.matrix_rank",
  lambda: ([np.float32([[1, 0, 0], [0, 1, 0], [1, 1, 0]])], {}),
  lambda a: np.int64(np.linalg.matrix_rank(a)))
E("matrix_rank_tol", "linalg.matrix_rank",
  lambda: ([np.diag(np.float32([1.0, 0.5, 1e-6]))], {"tol": 1e-3}),
  lambda a, tol: np.int64(2))
E("matrix_rank_atol_rtol", "linalg.matrix_rank",
  lambda: ([np.diag(np.float32([1.0, 0.5, 1e-6]))], {"tol": 1e-3}),
  lambda a, tol: np.int64(2))
E("multi_dot", "linalg.multi_dot",
  lambda: ([[_n(3, 4), _n(4, 5), _n(5, 2)]], {}),
  lambda xs: xs[0] @ xs[1] @ xs[2], atol=1e-4)
E("solve", "linalg.solve", lambda: ([_psd(4), _n(4, 2)], {}),
  lambda a, b: np.linalg.solve(a, b), atol=1e-3)
E("triangular_solve", "linalg.triangular_solve",
  lambda: ([np.triu(_psd(4)).astype(np.float32), _n(4, 2)], {}),
  lambda a, b: np.linalg.solve(a, b), atol=1e-3)
E("lstsq", "linalg.lstsq", lambda: ([_n(5, 3), _n(5, 2)], {}),
  lambda a, b: np.linalg.lstsq(a, b, rcond=None)[0], atol=1e-3,
  sel=0)
E("eigvalsh", "linalg.eigvalsh", lambda: ([_psd(4)], {}),
  lambda a: np.linalg.eigvalsh(a), atol=1e-3)


def _check_eigh(out, args, kwargs):
    w, v = out
    a = args[0].astype(np.float64)
    np.testing.assert_allclose(a @ v, v * w[None, :], atol=1e-3)
    np.testing.assert_allclose(v.T @ v, np.eye(4), atol=1e-4)


E("eigh", "linalg.eigh", lambda: ([_psd(4)], {}), check=_check_eigh,
  note="eigenvector sign freedom; checks A v = v diag(w), orthonormal")


def _check_eig(out, args, kwargs):
    w, v = out
    a = args[0].astype(np.complex128)
    np.testing.assert_allclose(a @ v, v * w[None, :], atol=1e-3)


E("eig", "linalg.eig", lambda: ([_n(4, 4)], {}), check=_check_eig,
  note="eigenvector phase freedom; checks A v = v diag(w)")


def _sorted_complex(w):
    return w[np.lexsort((w.imag.round(4), w.real.round(4)))]


E("eigvals", "linalg.eigvals", lambda: ([_n(4, 4)], {}),
  check=lambda out, a, k: np.testing.assert_allclose(
      _sorted_complex(out), _sorted_complex(np.linalg.eigvals(a[0])),
      atol=1e-3), note="unordered spectrum; compared after sorting")


def _check_qr(out, args, kwargs):
    q, r = out
    a = args[0]
    np.testing.assert_allclose(q @ r, a, atol=1e-4)
    np.testing.assert_allclose(q.T @ q, np.eye(q.shape[1]), atol=1e-4)
    np.testing.assert_allclose(r, np.triu(r), atol=1e-6)


E("qr", "linalg.qr", lambda: ([_n(5, 3)], {}), check=_check_qr,
  note="sign freedom; checks QR = A, Q orthonormal, R triangular")


def _check_svd(out, args, kwargs):
    u, s, vh = out
    a = args[0]
    np.testing.assert_allclose((u * s[None, :]) @ vh, a, atol=1e-4)  # VH convention
    assert np.all(np.diff(s) <= 1e-6)
    np.testing.assert_allclose(
        s, np.linalg.svd(a, compute_uv=False), atol=1e-4)


E("svd", "linalg.svd", lambda: ([_n(5, 3)], {}), check=_check_svd,
  note="sign freedom; checks USV=A and singular values vs numpy")


def _check_lu(out, args, kwargs):
    import scipy.linalg as sla
    lu, piv = out[0], out[1]
    a = args[0]
    slu, spiv = sla.lu_factor(a.astype(np.float64))
    np.testing.assert_allclose(lu, slu, atol=1e-4)


E("lu", "linalg.lu", lambda: ([_psd(4)], {}), check=_check_lu,
  note="packed LU vs scipy getrf (same LAPACK pivoting)")

# ===========================================================================
# fft / complex
# ===========================================================================
def _c(*shape):
    r = _rs(_seed_of("c", shape))
    return (r.randn(*shape) + 1j * r.randn(*shape)).astype(np.complex64)


E("fft_c2c", "fft.fft", lambda: ([_c(8)], {}),
  lambda x: np.fft.fft(x).astype(np.complex64), atol=1e-4)
E("fft_r2c", "fft.rfft", lambda: ([_n(8)], {}),
  lambda x: np.fft.rfft(x).astype(np.complex64), atol=1e-4)
E("fft_c2r", "fft.irfft", lambda: ([_c(5)], {}),
  lambda x: np.fft.irfft(x).astype(np.float32), atol=1e-4)
E("complex", "complex", lambda: ([_n(3, 4), _n(3, 4)], {}),
  lambda r, i: (r + 1j * i).astype(np.complex64))
E("as_complex", "as_complex", lambda: ([_n(3, 4, 2)], {}),
  lambda x: (x[..., 0] + 1j * x[..., 1]).astype(np.complex64))
E("as_real", "as_real", lambda: ([_c(3, 4)], {}),
  lambda x: np.stack([x.real, x.imag], -1).astype(np.float32))
E("real", "real", lambda: ([_c(3, 4)], {}),
  lambda x: x.real.astype(np.float32))
E("imag", "imag", lambda: ([_c(3, 4)], {}),
  lambda x: x.imag.astype(np.float32))

# ===========================================================================
# random / sampling (statistical + property checks, seeded)
# ===========================================================================
def _seeded(fn):
    def custom():
        import paddle_tpu as paddle
        paddle.seed(1234)
        fn(paddle)
    return custom


def _stat(out, mean, std=None, lo=None, hi=None, tol=0.1):
    m = float(np.mean(out))
    assert abs(m - mean) < tol, (m, mean)
    if std is not None:
        s = float(np.std(out))
        assert abs(s - std) < tol, (s, std)
    if lo is not None:
        assert np.min(out) >= lo
    if hi is not None:
        assert np.max(out) <= hi


E("bernoulli", "bernoulli",
  lambda: ([np.full((4000,), 0.3, np.float32)], {}),
  check=lambda out, a, k: (
      _stat(out, 0.3, tol=0.05),
      np.testing.assert_array_equal(np.unique(out), [0.0, 1.0])),
  note="stochastic; mean/support check at n=4000")
E("poisson", "poisson",
  lambda: ([np.full((4000,), 3.0, np.float32)], {}),
  check=lambda out, a, k: (
      _stat(out, 3.0, tol=0.15),
      _stat(np.square(out - 3.0), 3.0, tol=0.5)),
  note="stochastic; Poisson mean=var check")
E("binomial", "binomial",
  lambda: ([np.full((2000,), 10.0, np.float32),
            np.full((2000,), 0.4, np.float32)], {}),
  check=lambda out, a, k: (
      _stat(out, 4.0, tol=0.2),
      _stat(out, 4.0, lo=0, hi=10, tol=0.2)),
  note="stochastic; mean/support check")
E("standard_gamma", "standard_gamma",
  lambda: ([np.full((4000,), 2.0, np.float32)], {}),
  check=lambda out, a, k: _stat(out, 2.0, lo=0.0, tol=0.15),
  note="stochastic; Gamma(k) mean=k, positivity")
E("multinomial", "multinomial",
  lambda: ([_softmax(_n(6, 5)).astype(np.float32)],
           {"num_samples": 3, "replacement": False}),
  check=lambda out, a, k: (
      _stat(out, 2.0, lo=0, hi=4, tol=2.0),
      [[(lambda r: np.testing.assert_equal(len(np.unique(r)),
                                           len(r)))(r)] for r in out]),
  note="stochastic; support + no-replacement distinctness")
E("randint", "randint",
  lambda: ([], {"low": 3, "high": 11, "shape": [2000]}),
  check=lambda out, a, k: (
      _stat(out, 6.5, lo=3, hi=10, tol=0.3),
      [np.issubdtype(out.dtype, np.integer) or
       (_ for _ in ()).throw(AssertionError(out.dtype))]),
  note="stochastic; bounds/dtype/mean")
E("randperm", "randperm", lambda: ([], {"n": 64}),
  check=lambda out, a, k: np.testing.assert_array_equal(
      np.sort(out), np.arange(64)),
  note="stochastic; exact-permutation property")
E("uniform", "rand", lambda: ([], {"shape": [4000]}),
  check=lambda out, a, k: _stat(out, 0.5, std=1 / np.sqrt(12), lo=0.0,
                                hi=1.0, tol=0.05),
  note="stochastic; U[0,1) moments/bounds")
E("uniform_random_batch_size_like", "rand",
  lambda: ([], {"shape": [4000]}),
  check=lambda out, a, k: _stat(out, 0.5, lo=0.0, hi=1.0, tol=0.05),
  note="stochastic; alias capability = rand")
E("gaussian", "randn", lambda: ([], {"shape": [4000]}),
  check=lambda out, a, k: _stat(out, 0.0, std=1.0, tol=0.08),
  note="stochastic; N(0,1) moments")
E("truncated_gaussian_random", "randn", lambda: ([], {"shape": [4000]}),
  check=lambda out, a, k: _stat(out, 0.0, std=1.0, tol=0.08),
  note="stochastic; alias capability = randn")


def _inplace_rng(method, checker):
    def custom():
        import paddle_tpu as paddle
        paddle.seed(99)
        x = paddle.to_tensor(np.zeros(4000, np.float32))
        out = getattr(x, method)() if method != "exponential_" else \
            paddle.exponential_(x, lam=2.0)
        vals = np.asarray(x.value)
        checker(vals)
    return custom


E("exponential_", "exponential_",
  custom=_inplace_rng("exponential_",
                      lambda v: _stat(v, 0.5, lo=0.0, tol=0.05)),
  note="in-place; Exp(2) mean=0.5")
E("gaussian_inplace", "Tensor.normal_",
  custom=_inplace_rng("normal_",
                      lambda v: _stat(v, 0.0, std=1.0, tol=0.08)),
  note="in-place; N(0,1) moments")
E("uniform_inplace", "Tensor.uniform_",
  custom=_inplace_rng("uniform_",
                      lambda v: _stat(v, 0.0, tol=0.05)),
  note="in-place; U(-1,1) default mean 0")


def _full_inplace():
    import paddle_tpu as paddle
    x = paddle.to_tensor(np.zeros((2, 3), np.float32))
    x.fill_(4.5)
    np.testing.assert_array_equal(np.asarray(x.value),
                                  np.full((2, 3), 4.5, np.float32))


E("full_", "Tensor.fill_", custom=_full_inplace)


def _set_value_custom():
    import paddle_tpu as paddle
    x = paddle.to_tensor(np.zeros((3, 4), np.float32))
    y = paddle.assign(paddle.to_tensor(np.ones((3, 4), np.float32)), x)
    np.testing.assert_array_equal(np.asarray(x.value), 1.0)


E("set_value_with_tensor", "assign", custom=_set_value_custom)

# ===========================================================================
# graph / geometric
# ===========================================================================
E("send_u_recv", "geometric.send_u_recv",
  lambda: ([_n(4, 3), np.int64([0, 1, 2, 3, 0]),
            np.int64([1, 2, 1, 0, 0])], {}),
  lambda x, src, dst: _np_send_u_recv(x, src, dst, x.shape[0]))
E("send_ue_recv", "geometric.send_ue_recv",
  lambda: ([_n(4, 3), _n(5, 3), np.int64([0, 1, 2, 3, 0]),
            np.int64([1, 2, 1, 0, 0])], {}),
  lambda x, y, src, dst: _np_send_u_recv(x[src] + y, np.arange(5),
                                         dst, x.shape[0]))


def _np_send_u_recv(x, src, dst, rows):
    # reference (send_recv.py:101): out_size None → output keeps
    # x.shape[0] rows
    out = np.zeros((rows, x.shape[1]), np.float32)
    np.add.at(out, dst, x[src])
    return out


E("reindex_graph", "geometric.reindex_graph",
  lambda: ([np.int64([0, 1, 2]), np.int64([8, 9, 0, 4, 7, 6, 7]),
            np.int64([2, 3, 2])], {}),
  lambda x, nbr, cnt: (np.int64([3, 4, 0, 5, 6, 7, 6]),
                       np.int64([0, 0, 1, 1, 1, 2, 2]),
                       np.int64([0, 1, 2, 8, 9, 4, 7, 6])))


E("fill", "full", lambda: ([], {"shape": [3], "fill_value": 2.0}),
  lambda shape, fill_value: np.full(shape, 2.0, np.float32))
E("full_with_tensor", "full",
  lambda: ([], {"shape": [2, 2], "fill_value": 3.0}),
  lambda shape, fill_value: np.full(shape, 3.0, np.float32))
E("reduce_as", "reduce_as", lambda: ([_n(3, 4), _n(4)], {}),
  lambda x, t: x.sum(0))


# ===========================================================================
# sparse_ops.yaml (spec ids prefixed "sparse."): BCOO compute vs dense
# numpy with explicit zero-masking semantics
# ===========================================================================
def _sp_sample(key, lo=-0.9, hi=0.9, shape=(4, 5), density=0.5):
    rs = _rs(_seed_of("sp", key))
    d = np.zeros(shape, np.float32)
    mask = rs.rand(*shape) < density
    mask.flat[0] = True                      # at least one nonzero
    d[mask] = rs.uniform(lo, hi, int(mask.sum())).astype(np.float32)
    d[mask & (d == 0)] = 0.1                 # keep nnz = stored pattern
    return d


def _sp_of(d):
    import paddle_tpu.sparse as sp
    idx = np.argwhere(d != 0)
    return sp.sparse_coo_tensor(idx.T, d[tuple(idx.T)], d.shape)


def _sp_dense(st):
    return np.asarray(st.to_dense().value)


def _sp_unary(yaml_name, api_name, npf, lo=-0.9, hi=0.9):
    def custom():
        import paddle_tpu.sparse as sp
        d = _sp_sample(yaml_name, lo, hi)
        out = getattr(sp, api_name)(_sp_of(d))
        got = _sp_dense(out)
        want = np.where(d != 0, npf(d), 0).astype(got.dtype)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    E("sparse." + yaml_name, "sparse." + api_name, custom=custom)


_sp_unary("abs", "abs", np.abs)
_sp_unary("acos", "acos", np.arccos)
_sp_unary("acosh", "acosh", np.arccosh, lo=1.1, hi=3.0)
_sp_unary("asin", "asin", np.arcsin)
_sp_unary("asinh", "asinh", np.arcsinh)
_sp_unary("atan", "atan", np.arctan)
_sp_unary("atanh", "atanh", np.arctanh)
_sp_unary("expm1", "expm1", np.expm1)
_sp_unary("log1p", "log1p", np.log1p, lo=0.1, hi=2.0)


def _sp_isnan():
    import paddle_tpu.sparse as sp
    d = _sp_sample("isnan")
    st = sp.isnan(_sp_of(d))
    vals = np.asarray(st.values().value)
    np.testing.assert_array_equal(vals, np.zeros_like(vals, bool))


E("sparse.isnan", "sparse.isnan", custom=_sp_isnan)
_sp_unary("leaky_relu", "leaky_relu",
          lambda x: np.where(x > 0, x, 0.01 * x))
_sp_unary("relu", "relu", lambda x: np.maximum(x, 0))
_sp_unary("relu6", "relu6", lambda x: np.clip(x, 0, 6))
_sp_unary("sin", "sin", np.sin)
_sp_unary("sinh", "sinh", np.sinh)
_sp_unary("sqrt", "sqrt", np.sqrt, lo=0.1, hi=2.0)
_sp_unary("square", "square", np.square)
_sp_unary("tan", "tan", np.tan)
_sp_unary("tanh", "tanh", np.tanh)


def _sp_binary(yaml_name, api_name, npf):
    def custom():
        import paddle_tpu.sparse as sp
        a = _sp_sample(yaml_name + "a")
        b = _sp_sample(yaml_name + "b")
        out = getattr(sp, api_name)(_sp_of(a), _sp_of(b))
        got = _sp_dense(out) if not hasattr(out, "numpy") \
            else np.asarray(out.value)
        np.testing.assert_allclose(got, npf(a, b), rtol=1e-4, atol=1e-5)
    E("sparse." + yaml_name, "sparse." + api_name, custom=custom)


_sp_binary("add", "add", lambda a, b: a + b)
_sp_binary("subtract", "subtract", lambda a, b: a - b)
_sp_binary("multiply", "multiply", lambda a, b: a * b)


def _sp_misc_specs():
    import paddle_tpu as paddle
    import paddle_tpu.sparse as sp

    def divide():
        a, b = _sp_sample("dva"), _sp_sample("dvb", lo=0.5, hi=2.0)
        b = np.where(b == 0, 1.0, b).astype(np.float32)   # dense divisor
        out = sp.divide(_sp_of(a), _sp_of(b))
        np.testing.assert_allclose(np.asarray(out.value), a / b,
                                   rtol=1e-4, atol=1e-5)
    E("sparse.divide", "sparse.divide", custom=divide)
    E("sparse.divide_scalar", "sparse.divide_scalar", custom=lambda: (
        np.testing.assert_allclose(
            _sp_dense(sp.divide_scalar(_sp_of(_sp_sample("dvs")), 2.0)),
            _sp_sample("dvs") / 2.0, rtol=1e-5, atol=1e-6)))
    E("sparse.scale", "sparse.scale", custom=lambda: (
        np.testing.assert_allclose(
            _sp_dense(sp.scale(_sp_of(_sp_sample("sc")), 3.0)),
            _sp_sample("sc") * 3.0, rtol=1e-5, atol=1e-6)))
    E("sparse.pow", "sparse.pow", custom=lambda: (
        np.testing.assert_allclose(
            _sp_dense(sp.pow(_sp_of(_sp_sample("pw", 0.2, 1.5)), 2.0)),
            np.square(_sp_sample("pw", 0.2, 1.5)), rtol=1e-5,
            atol=1e-6)))
    E("sparse.cast", "sparse.cast", custom=lambda: (
        np.testing.assert_equal(
            _sp_dense(sp.cast(_sp_of(_sp_sample("ct")),
                              value_dtype="float64")).dtype,
            np.float64)))
    E("sparse.transpose", "sparse.transpose", custom=lambda: (
        np.testing.assert_allclose(
            _sp_dense(sp.transpose(_sp_of(_sp_sample("tp")), [1, 0])),
            _sp_sample("tp").T)))
    E("sparse.reshape", "sparse.reshape", custom=lambda: (
        np.testing.assert_allclose(
            _sp_dense(sp.reshape(_sp_of(_sp_sample("rs")), [2, 10])),
            _sp_sample("rs").reshape(2, 10))))

    def matmul():
        a = _sp_sample("mma")
        b = _n(5, 3)
        out = sp.matmul(_sp_of(a), paddle.to_tensor(b))
        np.testing.assert_allclose(np.asarray(out.value), a @ b,
                                   rtol=1e-4, atol=1e-5)
    E("sparse.matmul", "sparse.matmul", custom=matmul)

    def masked_matmul():
        a, b = _n(4, 6), _n(6, 5)
        m = _sp_sample("mmm")
        out = sp.masked_matmul(paddle.to_tensor(a), paddle.to_tensor(b),
                               _sp_of(m))
        want = np.where(m != 0, a @ b, 0)
        np.testing.assert_allclose(_sp_dense(out), want, rtol=1e-4,
                                   atol=1e-4)
    E("sparse.masked_matmul", "sparse.masked_matmul",
      custom=masked_matmul)

    def mv():
        a, v = _sp_sample("mv"), _n(5)
        out = sp.mv(_sp_of(a), paddle.to_tensor(v))
        np.testing.assert_allclose(np.asarray(out.value), a @ v,
                                   rtol=1e-4, atol=1e-5)
    E("sparse.mv", "sparse.mv", custom=mv)

    def addmm():
        inp, a, b = _n(4, 3), _sp_sample("am"), _n(5, 3)
        out = sp.addmm(paddle.to_tensor(inp), _sp_of(a),
                       paddle.to_tensor(b), beta=0.5, alpha=2.0)
        np.testing.assert_allclose(np.asarray(out.value),
                                   0.5 * inp + 2.0 * (a @ b),
                                   rtol=1e-4, atol=1e-5)
    E("sparse.addmm", "sparse.addmm", custom=addmm)

    def sum_():
        d = _sp_sample("sm")
        tot = sp.sum(_sp_of(d))          # sparse scalar (reference)
        np.testing.assert_allclose(
            float(np.asarray(tot.to_dense().value)), d.sum(), rtol=1e-4)
        kd = sp.sum(_sp_of(d), keepdim=True)
        assert tuple(kd.shape) == (1, 1), kd.shape
        ax = sp.sum(_sp_of(d), axis=1)
        np.testing.assert_allclose(_sp_dense(ax), d.sum(1), rtol=1e-4,
                                   atol=1e-5)
    E("sparse.sum", "sparse.sum", custom=sum_)

    def coalesce():
        st = sp.sparse_coo_tensor(
            np.int64([[0, 0, 1], [1, 1, 2]]),
            np.float32([1.0, 2.0, 3.0]), (2, 3))
        out = sp.coalesce(st)
        assert out.nnz == 2
        want = np.zeros((2, 3), np.float32)
        want[0, 1], want[1, 2] = 3.0, 3.0
        np.testing.assert_allclose(_sp_dense(out), want)
    E("sparse.coalesce", "sparse.coalesce", custom=coalesce)

    E("sparse.full_like", "sparse.full_like", custom=lambda: (
        np.testing.assert_allclose(
            _sp_dense(sp.full_like(_sp_of(_sp_sample("fl")), 2.5)),
            np.where(_sp_sample("fl") != 0, 2.5, 0.0))))

    def mask_as():
        d, m = _n(4, 5), _sp_sample("ma")
        out = sp.mask_as(paddle.to_tensor(d), _sp_of(m))
        np.testing.assert_allclose(_sp_dense(out),
                                   np.where(m != 0, d, 0), rtol=1e-5)
    E("sparse.mask_as", "sparse.mask_as", custom=mask_as)

    def slice_():
        d = _sp_sample("sl")
        out = sp.slice(_sp_of(d), [0, 1], [1, 1], [3, 4])
        np.testing.assert_allclose(_sp_dense(out), d[1:3, 1:4])
    E("sparse.slice", "sparse.slice", custom=slice_)

    def softmax():
        d = _sp_sample("sfm")
        out = sp.softmax(_sp_of(d))
        got = _sp_dense(out)
        for i in range(d.shape[0]):
            nz = d[i] != 0
            if nz.any():
                np.testing.assert_allclose(
                    got[i][nz], _softmax(d[i][nz][None])[0], rtol=1e-4,
                    atol=1e-5)
    E("sparse.softmax", "sparse.softmax", custom=softmax)

    def conversions():
        d = _sp_sample("cv")
        coo = sp.to_sparse_coo(paddle.to_tensor(d))
        np.testing.assert_allclose(_sp_dense(coo), d)
        csr = sp.to_sparse_csr(paddle.to_tensor(d))
        np.testing.assert_allclose(_sp_dense(csr), d)
        np.testing.assert_allclose(
            np.asarray(sp.to_dense(coo).value), d)
        idx = np.asarray(coo.indices().value)
        vals = np.asarray(coo.values().value)
        np.testing.assert_allclose(d[tuple(idx)], vals)
        st = sp.sparse_coo_tensor(idx, vals, d.shape)
        np.testing.assert_allclose(_sp_dense(st), d)
    for nm in ("to_sparse_coo", "to_sparse_csr", "to_dense", "values",
               "indices", "sparse_coo_tensor"):
        E("sparse." + nm, "sparse", custom=conversions)


_sp_misc_specs()


# ===========================================================================
# fused_ops.yaml (spec ids prefixed "fused.")
# ===========================================================================
E("fused.fused_bias_act", "incubate.nn.functional.fused_bias_act",
  lambda: ([_n(3, 8), _n(8)], {"act_method": "gelu"}),
  lambda x, b, act_method: (lambda z: z * 0.5 * (
      1 + sps.erf(z / np.sqrt(2))))(x + b), atol=1e-4)
E("fused.fused_bias_dropout_residual_layer_norm",
  "incubate.nn.functional.fused_bias_dropout_residual_layer_norm",
  lambda: ([_n(3, 8), _n(3, 8), _n(8)], {"dropout_rate": 0.0}),
  lambda x, res, b, dropout_rate: (lambda z: (
      z - z.mean(-1, keepdims=True))
      / np.sqrt(z.var(-1, keepdims=True) + 1e-5))(x + b + res),
  atol=1e-4)
E("fused.fused_bias_residual_layernorm",
  "incubate.nn.functional.fused_layer_norm",
  lambda: ([_n(3, 8), _u(0.5, 1.5, 8), _n(8)], {}),
  lambda x, w, b: (x - x.mean(-1, keepdims=True))
  / np.sqrt(x.var(-1, keepdims=True) + 1e-5) * w + b, atol=1e-4,
  sel=0)
E("fused.fused_dropout_add",
  "incubate.nn.functional.fused_dropout_add",
  lambda: ([_n(3, 4), _n(3, 4)], {"p": 0.3, "training": False}),
  lambda x, y, p, training: x + y)
E("fused.fused_dot_product_attention",
  "nn.functional.flash_attention",
  lambda: ([_n(1, 4, 2, 8), _n(1, 4, 2, 8), _n(1, 4, 2, 8)], {}),
  lambda q, k, v: _np_sdpa(q, k, v), atol=1e-4, sel=0)
E("fused.variable_length_memory_efficient_attention",
  "nn.functional.flash_attention",
  lambda: ([_n(1, 4, 2, 8), _n(1, 4, 2, 8), _n(1, 4, 2, 8)], {}),
  lambda q, k, v: _np_sdpa(q, k, v), atol=1e-4, sel=0)
E("fused.fused_elementwise_add", "add",
  lambda: ([_n(3, 4), _n(3, 4)], {}), lambda x, y: x + y)
E("fused.fused_elementwise_sub", "subtract",
  lambda: ([_n(3, 4), _n(3, 4)], {}), lambda x, y: x - y)
E("fused.fused_elementwise_mul", "multiply",
  lambda: ([_n(3, 4), _n(3, 4)], {}), lambda x, y: x * y)
E("fused.fused_elementwise_div", "divide",
  lambda: ([_n(3, 4), _u(0.5, 2.0, 3, 4)], {}), lambda x, y: x / y)
E("fused.max_pool2d_v2", "nn.functional.max_pool2d",
  lambda: ([_n(1, 2, 6, 6)], {"kernel_size": 2}),
  _t_ref(lambda t, x, kernel_size: t.nn.functional.max_pool2d(
      x, kernel_size)))


def _rope_custom():
    import paddle_tpu as paddle
    from paddle_tpu.incubate.nn.functional import \
        fused_rotary_position_embedding as rope
    q = _n(1, 6, 2, 8)
    k = _n(1, 6, 2, 8)
    outs = rope(paddle.to_tensor(q), paddle.to_tensor(k))
    # independent neox-style reference: rotate-half with theta_i =
    # base^(-2i/d)
    d = q.shape[-1]
    pos = np.arange(q.shape[1], dtype=np.float64)
    inv = 10000.0 ** (-np.arange(0, d, 2, dtype=np.float64) / d)
    ang = pos[:, None] * inv[None, :]         # [s, d/2]
    cos = np.cos(ang)[None, :, None, :]
    sin = np.sin(ang)[None, :, None, :]

    def apply(x):
        x1, x2 = x[..., : d // 2], x[..., d // 2:]
        return np.concatenate([x1 * cos - x2 * sin,
                               x2 * cos + x1 * sin],
                              -1).astype(np.float32)
    for got, want in zip(outs, (apply(q), apply(k))):
        np.testing.assert_allclose(np.asarray(got.value), want,
                                   rtol=1e-4, atol=1e-4)


E("fused.fused_rotary_position_embedding",
  "incubate.nn.functional.fused_rotary_position_embedding",
  custom=_rope_custom)


def _moe_custom():
    """fused_moe capability: MoELayer with a single expert must equal
    that expert MLP exactly (top-1 routing sends every token to it)."""
    import paddle_tpu as paddle
    from paddle_tpu.incubate.distributed.models.moe import MoELayer
    paddle.seed(11)
    layer = MoELayer(d_model=8, d_hidden=16, num_experts=1,
                     gate="naive", top_k=1)
    x = paddle.to_tensor(_n(4, 8))
    got = np.asarray(layer(x).value)
    w = {k: np.asarray(v.value) for k, v in layer.state_dict().items()}
    xw = np.asarray(x.value)
    import jax.nn as jnn
    h = np.asarray(jnn.gelu(xw @ w["w1"][0] + w["b1"][0]))
    want = h @ w["w2"][0] + w["b2"][0]
    np.testing.assert_allclose(got, want.astype(np.float32), rtol=1e-3,
                               atol=1e-3)


E("fused.fused_moe",
  "incubate.distributed.models.moe.MoELayer", custom=_moe_custom)


def _ctc_custom():
    """warpctc capability = nn.functional.ctc_loss; independent
    reference: torch.nn.functional.ctc_loss on the same inputs."""
    import paddle_tpu as paddle
    torch = _torch()
    T, B, C = 6, 2, 5
    logits = _n(T, B, C)
    log_probs = np.log(_softmax(logits)).astype(np.float32)
    labels = _i(1, C, B, 3, dtype=np.int32)
    in_len = np.int64([T, T])
    lb_len = np.int64([3, 2])
    # paddle takes LOGITS (softmax interlaced); torch takes log-probs
    out = paddle.nn.functional.ctc_loss(
        paddle.to_tensor(logits),
        paddle.to_tensor(labels),
        paddle.to_tensor(in_len),
        paddle.to_tensor(lb_len), blank=0, reduction="none")
    t = torch.nn.functional.ctc_loss(
        torch.from_numpy(log_probs), torch.from_numpy(labels.astype(np.int64)),
        torch.from_numpy(in_len), torch.from_numpy(lb_len),
        blank=0, reduction="none")
    np.testing.assert_allclose(np.asarray(out.value), t.numpy(),
                               rtol=1e-3, atol=1e-3)


E("warpctc", "nn.functional.ctc_loss", custom=_ctc_custom)
