"""Fused AdamW — single-pass Pallas TPU kernel over each parameter.

Reference: the reference fuses the AdamW update in CUDA
(`paddle/phi/kernels/gpu/adamw_kernel.cu`, `fused_adam_kernel.cu` multi
tensor) so one kernel reads grad + moments + master once.  TPU-native
equivalent: one Pallas pass that reads (grad, m, v, master) and writes
(param_half, m, v, master) with input/output aliasing, so the moments and
master update IN PLACE — the optimizer step's HBM traffic is exactly one
read + one write of the state, and XLA never materialises intermediate
fp32 copies of the parameter.

Bias corrections (1-βᵗ) are computed outside (scalar XLA) and passed in
SMEM; weight decay and betas are compile-time constants.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_adamw"]

# elements per grid step: in+out blocks (4 f32 + 2 bf16-ish each way)
# double-buffered must fit the ~16 MiB scoped VMEM → ~3.5 MiB per block set
_CHUNK = 128 * 1024


def _interpret():
    return jax.default_backend() != "tpu"


def _kernel(lr_ref, c1_ref, c2_ref, g_ref, m_ref, v_ref, mst_ref,
            p_out, m_out, v_out, mst_out, *, b1, b2, eps, wd, decoupled):
    g = g_ref[...].astype(jnp.float32)
    mst = mst_ref[...]
    if wd and not decoupled:
        g = g + jnp.float32(wd) * mst
    m = jnp.float32(b1) * m_ref[...] + jnp.float32(1 - b1) * g
    v = jnp.float32(b2) * v_ref[...] + jnp.float32(1 - b2) * g * g
    mhat = m / c1_ref[0]
    vhat = v / c2_ref[0]
    upd = mhat / (jnp.sqrt(vhat) + jnp.float32(eps))
    if wd and decoupled:
        upd = upd + jnp.float32(wd) * mst
    new_mst = mst - lr_ref[0] * upd
    p_out[...] = new_mst.astype(p_out.dtype)
    m_out[...] = m
    v_out[...] = v
    mst_out[...] = new_mst


def fused_adamw(grad, m, v, master, lr, step, *, b1=0.9, b2=0.999,
                eps=1e-8, wd=0.0, decoupled=True, out_dtype=jnp.bfloat16):
    """One fused AdamW step.  grad: any shape/dtype; m/v/master: fp32 of
    the same shape.  Returns (param(out_dtype), m, v, master); m, v and
    master alias their inputs (updated in place under jit donation).

    lr: scalar f32 (traced); step: scalar int (traced, 1-based).
    """
    shape = grad.shape
    n = int(np_prod(shape))
    stepf = jnp.asarray(step, jnp.float32)
    c1 = (1.0 - jnp.float32(b1) ** stepf).reshape(1)
    c2 = (1.0 - jnp.float32(b2) ** stepf).reshape(1)
    lr1 = jnp.asarray(lr, jnp.float32).reshape(1)

    g1 = grad.reshape(n)
    m1 = m.reshape(n)
    v1 = v.reshape(n)
    mst1 = master.reshape(n)
    chunk = min(_CHUNK, n)
    grid = ((n + chunk - 1) // chunk,)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    blk = pl.BlockSpec((chunk,), lambda i: (i,))
    with jax.enable_x64(False):
        p1, m1, v1, mst1 = pl.pallas_call(
            functools.partial(_kernel, b1=b1, b2=b2, eps=eps, wd=wd,
                              decoupled=decoupled),
            grid=grid,
            in_specs=[smem, smem, smem, blk, blk, blk, blk],
            out_specs=[blk, blk, blk, blk],
            out_shape=[
                jax.ShapeDtypeStruct((n,), out_dtype),
                jax.ShapeDtypeStruct((n,), jnp.float32),
                jax.ShapeDtypeStruct((n,), jnp.float32),
                jax.ShapeDtypeStruct((n,), jnp.float32),
            ],
            # m, v, master update in place (operand index counts the 3
            # scalar-prefetch SMEM refs first: grads are operand 3)
            input_output_aliases={4: 1, 5: 2, 6: 3},
            interpret=_interpret(),
        )(lr1, c1, c2, g1, m1, v1, mst1)
    return (p1.reshape(shape), m1.reshape(shape), v1.reshape(shape),
            mst1.reshape(shape))


def np_prod(shape):
    out = 1
    for s in shape:
        out *= int(s)
    return out
