"""Fused AdamW — single-pass Pallas TPU kernel over each parameter.

Reference: the reference fuses the AdamW update in CUDA
(`paddle/phi/kernels/gpu/adamw_kernel.cu`, `fused_adam_kernel.cu` multi
tensor) so one kernel reads grad + moments + master once.  TPU-native
equivalent: one Pallas pass that reads (grad, m, v, master) and writes
(param, m, v[, master]) with input/output aliasing, so the state updates
IN PLACE — the optimizer step's HBM traffic is exactly one read + one
write of the state, and XLA never materialises intermediate fp32 copies
of the parameter.

Two storage schemes:
  - half params + fp32 master (reference O2): outputs a fresh half param
    and the aliased fp32 master.
  - fp32 params (flax param_dtype idiom — the param IS the master):
    the param aliases in place; no separate half copy is written.
Moments may be stored in any dtype (bf16 halves state memory); update
math is fp32 regardless.

bf16 moments + error feedback (`ef` operand, FLAGS_bf16_adamw_moments):
plain bf16 storage of the SECOND moment stalls — its per-step increment
(1-β₂)·g² ≈ 1e-3·v sits below bf16's ~4e-3 relative resolution, so
v stops integrating and the effective LR drifts up.  The ef buffer
carries the rounding residual: v is reconstructed as v_bf16 + ef each
step, updated in fp32, and re-split into (bf16 value, bf16 residual).
The FIRST moment needs no residual — its (1-β₁)=0.1 increments are
representable — so the state is m+v+ef = 6 bytes/param vs fp32's 8:
the moments themselves halve (8→4 bytes) and the 2-byte residual rides
along.  The param update consumes the full-precision reconstruction,
keeping N-step trajectories within bf16-rounding distance of fp32
moments (tested).

Bias corrections (1-βᵗ) are computed outside (scalar XLA) and passed in
SMEM; weight decay and betas are compile-time constants.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from ._x64 import x64_off
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_adamw", "adamw_hostside"]

# elements per grid step: in+out blocks (up to 4 f32 + 2 bf16 each way)
# double-buffered must fit the ~16 MiB scoped VMEM
_CHUNK = 64 * 1024

# block-size budget: measured NOT to move throughput (178-201 GB/s at
# 8MB and 14MB alike — the kernel is bound elsewhere); 8MB stays safely
# under scoped VMEM for every moment dtype
try:
    _VMEM_BUDGET = int(os.environ.get("PDTPU_ADAMW_VMEM_BUDGET",
                                      8 * 1024 * 1024))
except ValueError:
    _VMEM_BUDGET = 8 * 1024 * 1024


def _interpret():
    return jax.default_backend() != "tpu"


def _step_math(g_ref, m_ref, v_ref, mst_ref, lr_ref, c1_ref, c2_ref, *,
               b1, b2, eps, wd, decoupled, ef_ref=None):
    g = g_ref[...].astype(jnp.float32)
    mst = mst_ref[...].astype(jnp.float32)
    if wd and not decoupled:
        g = g + jnp.float32(wd) * mst
    m = jnp.float32(b1) * m_ref[...].astype(jnp.float32) \
        + jnp.float32(1 - b1) * g
    v_prev = v_ref[...].astype(jnp.float32)
    if ef_ref is not None:
        # error feedback: the stored moment plus its rounding residual
        # IS the full-precision second moment
        v_prev = v_prev + ef_ref[...].astype(jnp.float32)
    v = jnp.float32(b2) * v_prev + jnp.float32(1 - b2) * g * g
    mhat = m / c1_ref[0]
    vhat = v / c2_ref[0]
    upd = mhat / (jnp.sqrt(vhat) + jnp.float32(eps))
    if wd and decoupled:
        upd = upd + jnp.float32(wd) * mst
    return mst - lr_ref[0] * upd, m, v


def _kernel_master(lr_ref, c1_ref, c2_ref, g_ref, m_ref, v_ref, mst_ref,
                   p_out, m_out, v_out, mst_out, *, b1, b2, eps, wd,
                   decoupled):
    new_mst, m, v = _step_math(g_ref, m_ref, v_ref, mst_ref, lr_ref,
                               c1_ref, c2_ref, b1=b1, b2=b2, eps=eps,
                               wd=wd, decoupled=decoupled)
    p_out[...] = new_mst.astype(p_out.dtype)
    m_out[...] = m.astype(m_out.dtype)
    v_out[...] = v.astype(v_out.dtype)
    mst_out[...] = new_mst


def _kernel_fp32(lr_ref, c1_ref, c2_ref, g_ref, m_ref, v_ref, p_ref,
                 p_out, m_out, v_out, *, b1, b2, eps, wd, decoupled):
    new_p, m, v = _step_math(g_ref, m_ref, v_ref, p_ref, lr_ref,
                             c1_ref, c2_ref, b1=b1, b2=b2, eps=eps,
                             wd=wd, decoupled=decoupled)
    p_out[...] = new_p
    m_out[...] = m.astype(m_out.dtype)
    v_out[...] = v.astype(v_out.dtype)


def _split_ef(v, v_out, ef_out):
    v_low = v.astype(v_out.dtype)
    v_out[...] = v_low
    ef_out[...] = (v - v_low.astype(jnp.float32)).astype(ef_out.dtype)


def _kernel_master_ef(lr_ref, c1_ref, c2_ref, g_ref, m_ref, v_ref,
                      mst_ref, ef_ref, p_out, m_out, v_out, mst_out,
                      ef_out, *, b1, b2, eps, wd, decoupled):
    new_mst, m, v = _step_math(g_ref, m_ref, v_ref, mst_ref, lr_ref,
                               c1_ref, c2_ref, b1=b1, b2=b2, eps=eps,
                               wd=wd, decoupled=decoupled, ef_ref=ef_ref)
    p_out[...] = new_mst.astype(p_out.dtype)
    m_out[...] = m.astype(m_out.dtype)
    mst_out[...] = new_mst
    _split_ef(v, v_out, ef_out)


def _kernel_fp32_ef(lr_ref, c1_ref, c2_ref, g_ref, m_ref, v_ref, p_ref,
                    ef_ref, p_out, m_out, v_out, ef_out, *, b1, b2, eps,
                    wd, decoupled):
    new_p, m, v = _step_math(g_ref, m_ref, v_ref, p_ref, lr_ref,
                             c1_ref, c2_ref, b1=b1, b2=b2, eps=eps,
                             wd=wd, decoupled=decoupled, ef_ref=ef_ref)
    p_out[...] = new_p
    m_out[...] = m.astype(m_out.dtype)
    _split_ef(v, v_out, ef_out)


def fused_adamw(grad, m, v, master, lr, step, *, b1=0.9, b2=0.999,
                eps=1e-8, wd=0.0, decoupled=True, out_dtype=jnp.bfloat16,
                ef=None):
    """One fused AdamW step.  grad: any shape/dtype; m/v: any float dtype
    of the same shape; master: fp32.  Returns (param(out_dtype), m, v,
    master); the state aliases its inputs (updated in place under jit
    donation).  When out_dtype is fp32 the param IS the master (one
    aliased output; the returned master is the new param).

    ef: optional error-feedback residual for low-precision moments (see
    module docstring) — when given, the second moment is reconstructed
    as v + ef, updated in fp32 and re-split; the return gains a fifth
    element (the new residual).

    lr: scalar f32 (traced); step: scalar int (traced, 1-based).
    """
    shape = grad.shape
    n = int(np_prod(shape))
    stepf = jnp.asarray(step, jnp.float32)
    c1 = (1.0 - jnp.float32(b1) ** stepf).reshape(1)
    c2 = (1.0 - jnp.float32(b2) ** stepf).reshape(1)
    lr1 = jnp.asarray(lr, jnp.float32).reshape(1)

    # big tensors: 2-D (rows, 1024) blocks — native (8,128)/(16,128)
    # tiling, large contiguous DMAs per grid step.  Fallback: flat 1-D
    # chunks for shapes that don't divide.
    lanes = 1024
    fp32_params_mode = jnp.dtype(out_dtype) == jnp.float32
    if n % lanes == 0 and (n // lanes) % 8 == 0:
        # Mosaic needs the sublane dim divisible by 8 (or the full
        # array); block rows sized so the double-buffered operand +
        # result set stays well under the ~16 MiB scoped VMEM
        rows = n // lanes
        esz = (jnp.dtype(grad.dtype).itemsize + 4  # g + master
               + 2 * jnp.dtype(m.dtype).itemsize)  # moments in
        esz += esz if fp32_params_mode else esz + 2  # outputs
        if ef is not None:
            esz += 2 * jnp.dtype(ef.dtype).itemsize  # ef in + out
        br = next((d for d in (256, 128, 64, 32, 16, 8)
                   if rows % d == 0
                   and 2 * d * lanes * esz <= _VMEM_BUDGET),
                  None)
        if br is None:
            br = next(d for d in (256, 128, 64, 32, 16, 8)
                      if rows % d == 0)
            br = min(br, 8)
            if rows % br:
                br = None
    else:
        br = None
    pad = 0
    if br is not None:
        work_shape = (rows, lanes)
        grid = (rows // br,)
        blk = pl.BlockSpec((br, lanes), lambda i: (i, 0))
    else:
        # flat path: pad to the packed-tile granule (bf16 packs (16,128)
        # sublane tiles = 2048 elems; also covers fp32 (8,128)=1024) so
        # every block offset AND the final partial block stay
        # sublane-aligned for Mosaic
        align = 2048
        n_pad = -n % align
        pad = n_pad
        work_shape = (n + n_pad,)
        chunk = min(_CHUNK, n + n_pad)
        grid = ((n + n_pad + chunk - 1) // chunk,)
        blk = pl.BlockSpec((chunk,), lambda i: (i,))

    def _flat(a):
        a = a.reshape((n,))
        return jnp.pad(a, (0, pad)) if pad else a

    def _pack(a):
        return _flat(a) if pad else a.reshape(work_shape)

    g1, m1, v1, mst1 = (_pack(grad), _pack(m), _pack(v), _pack(master))
    ef1 = _pack(ef) if ef is not None else None
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    fp32_params = jnp.dtype(out_dtype) == jnp.float32
    kw = dict(b1=b1, b2=b2, eps=eps, wd=wd, decoupled=decoupled)
    with x64_off():
        if fp32_params and ef is None:
            # operand index counts the 3 scalar SMEM refs first
            p1, m1, v1 = pl.pallas_call(
                functools.partial(_kernel_fp32, **kw),
                grid=grid,
                in_specs=[smem, smem, smem, blk, blk, blk, blk],
                out_specs=[blk, blk, blk],
                out_shape=[
                    jax.ShapeDtypeStruct(work_shape, jnp.float32),
                    jax.ShapeDtypeStruct(work_shape, m.dtype),
                    jax.ShapeDtypeStruct(work_shape, v.dtype),
                ],
                input_output_aliases={6: 0, 4: 1, 5: 2},
                interpret=_interpret(),
            )(lr1, c1, c2, g1, m1, v1, mst1)
            mst1 = p1
        elif fp32_params:
            p1, m1, v1, ef1 = pl.pallas_call(
                functools.partial(_kernel_fp32_ef, **kw),
                grid=grid,
                in_specs=[smem, smem, smem, blk, blk, blk, blk, blk],
                out_specs=[blk, blk, blk, blk],
                out_shape=[
                    jax.ShapeDtypeStruct(work_shape, jnp.float32),
                    jax.ShapeDtypeStruct(work_shape, m.dtype),
                    jax.ShapeDtypeStruct(work_shape, v.dtype),
                    jax.ShapeDtypeStruct(work_shape, ef.dtype),
                ],
                input_output_aliases={6: 0, 4: 1, 5: 2, 7: 3},
                interpret=_interpret(),
            )(lr1, c1, c2, g1, m1, v1, mst1, ef1)
            mst1 = p1
        elif ef is None:
            p1, m1, v1, mst1 = pl.pallas_call(
                functools.partial(_kernel_master, **kw),
                grid=grid,
                in_specs=[smem, smem, smem, blk, blk, blk, blk],
                out_specs=[blk, blk, blk, blk],
                out_shape=[
                    jax.ShapeDtypeStruct(work_shape, out_dtype),
                    jax.ShapeDtypeStruct(work_shape, m.dtype),
                    jax.ShapeDtypeStruct(work_shape, v.dtype),
                    jax.ShapeDtypeStruct(work_shape, jnp.float32),
                ],
                input_output_aliases={4: 1, 5: 2, 6: 3},
                interpret=_interpret(),
            )(lr1, c1, c2, g1, m1, v1, mst1)
        else:
            p1, m1, v1, mst1, ef1 = pl.pallas_call(
                functools.partial(_kernel_master_ef, **kw),
                grid=grid,
                in_specs=[smem, smem, smem, blk, blk, blk, blk, blk],
                out_specs=[blk, blk, blk, blk, blk],
                out_shape=[
                    jax.ShapeDtypeStruct(work_shape, out_dtype),
                    jax.ShapeDtypeStruct(work_shape, m.dtype),
                    jax.ShapeDtypeStruct(work_shape, v.dtype),
                    jax.ShapeDtypeStruct(work_shape, jnp.float32),
                    jax.ShapeDtypeStruct(work_shape, ef.dtype),
                ],
                input_output_aliases={4: 1, 5: 2, 6: 3, 7: 4},
                interpret=_interpret(),
            )(lr1, c1, c2, g1, m1, v1, mst1, ef1)
    outs = (p1, m1, v1, mst1) + ((ef1,) if ef is not None else ())
    if pad:
        outs = tuple(a[:n] for a in outs)
    return tuple(a.reshape(shape) for a in outs)


def adamw_hostside(grad, m, v, master, lr, step, *, b1=0.9, b2=0.999,
                   eps=1e-8, wd=0.0, decoupled=True,
                   out_dtype=jnp.bfloat16, ef=None):
    """Host-side twin of the fused kernel: the same single-pass AdamW
    math as `_step_math`, expressed in plain jnp so it can run where a
    Pallas launch cannot — off-TPU backends, and inside host-offload
    pipelines that apply each layer's update the moment its gradient
    lands (parallel/offload_pipeline.py backward scan).  Same signature
    and return convention as `fused_adamw` (incl. the optional `ef`
    error-feedback residual); numerics match the kernel (and the
    optimizer's pure `_update` rule) — fp32 update math, any grad/moment
    storage dtype.  When out_dtype is fp32 the param IS the master (the
    returned master is the new param)."""
    lrf = jnp.asarray(lr, jnp.float32)
    g = grad.astype(jnp.float32)
    mst = master.astype(jnp.float32)
    if wd and not decoupled:
        g = g + wd * mst
    mn = b1 * m.astype(jnp.float32) + (1 - b1) * g
    v_prev = v.astype(jnp.float32)
    if ef is not None:
        v_prev = v_prev + ef.astype(jnp.float32)
    vn = b2 * v_prev + (1 - b2) * g * g
    mhat = mn / (1 - b1 ** step)
    vhat = vn / (1 - b2 ** step)
    upd = mhat / (jnp.sqrt(vhat) + eps)
    if wd and decoupled:
        upd = upd + wd * mst
    new_mst = mst - lrf * upd
    out = (new_mst.astype(out_dtype), mn.astype(m.dtype),
           vn.astype(v.dtype), new_mst)
    if ef is not None:
        v_low = vn.astype(v.dtype)
        out += ((vn - v_low.astype(jnp.float32)).astype(ef.dtype),)
    return out


def np_prod(shape):
    out = 1
    for s in shape:
        out *= int(s)
    return out
