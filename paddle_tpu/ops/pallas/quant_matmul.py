"""Weight-only quantized matmul kernel — dequant-in-VMEM fused into
the decode matmul (ISSUE 11 tentpole).

Decode sits at 0.79x of the HBM roofline (BENCH_r05): per generated
token every weight byte crosses HBM once, so tokens/s is bytes/token-
bound.  This kernel reads the weight at its PACKED width — 1 byte per
element (int8) or half a byte (int4, two nibbles per byte) — and
dequantizes in VMEM right after the DMA, so the HBM traffic the matmul
pays is the packed traffic.  The activation [M, K] is tiny at decode
(M = slots x verify width) and rides along whole.

Layout contract (paddle_tpu.ops: pack_int4 / dequant_weight):

  int8   qw [K, N] int8, scales [N] fp — per-output-channel
  int4   qw [K//2, N] int8 — row k in the LOW nibble, row k + K//2 in
         the HIGH nibble (half-split: unpack is two nibble extractions
         and a concat, never a sublane interleave); scales
         [K//group, N] fp, groups never straddling the half boundary

Grid: (N // block_n,) — one pass over the output columns; the weight
tile [K(//2), block_n] is the only HBM-heavy operand.  Dequant math is
q_f32 * scale_f32 cast to the activation dtype, IDENTICAL to the jnp
twin (ops.xla_quant_matmul), so the two paths are bit-exact and tier-1
stays CPU-exact.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from ._x64 import x64_off
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel_int8(x_ref, w_ref, s_ref, o_ref):
    w = w_ref[...].astype(jnp.float32) * s_ref[...].astype(jnp.float32)
    x = x_ref[...]
    o_ref[...] = jax.lax.dot_general(
        x, w.astype(x.dtype), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(o_ref.dtype)


def _kernel_int4(x_ref, w_ref, s_ref, o_ref, *, group):
    p = w_ref[...].astype(jnp.int32)          # sign-extended bytes
    lo = ((p & 15) ^ 8) - 8                   # low nibble, rows < K/2
    hi = p >> 4                               # high nibble, rows >= K/2
    q = jnp.concatenate([lo, hi], axis=0).astype(jnp.float32)
    s = jnp.repeat(s_ref[...].astype(jnp.float32), group, axis=0)
    x = x_ref[...]
    w = (q * s).astype(x.dtype)
    o_ref[...] = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(o_ref.dtype)


def quant_matmul(x, qw, scales, fmt, group_size=None, block_n=512,
                 interpret=None):
    """x [..., K] @ packed weight → [..., N] in x.dtype.  Raises
    ValueError for shapes the TPU tiling cannot serve — the dispatcher
    (ops.quant_matmul) falls back to the jnp twin."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    lead = x.shape[:-1]
    K = x.shape[-1]
    N = qw.shape[1]
    M = 1
    for d in lead:
        M *= d
    x2 = x.reshape(M, K)
    if fmt == "int4":
        if group_size is None:
            raise ValueError("int4 quant_matmul needs group_size")
        g = int(group_size)
        if qw.shape[0] * 2 != K:
            raise ValueError(f"packed rows {qw.shape[0]} != K/2 "
                             f"({K}/2)")
        if (K // 2) % g:
            raise ValueError(f"group_size {g} must divide K/2 "
                             f"({K // 2})")
    elif fmt != "int8":
        raise ValueError(f"unknown weight-only format {fmt!r}")
    bn = min(int(block_n), N)
    if not interpret:
        # MXU/VPU tiling: lanes want N % 128, int8 sublanes want 32
        if N % bn or bn % 128 or K % 256 or M % 8:
            raise ValueError(
                f"quant_matmul tiling needs N % 128 == 0, K % 256 == 0 "
                f"and M % 8 == 0 (got M={M}, K={K}, N={N})")
    elif N % bn:
        bn = N                                 # interpret: one tile
    grid = (N // bn,)
    if fmt == "int8":
        kern = _kernel_int8
        w_spec = pl.BlockSpec((K, bn), lambda j: (0, j))
        s_spec = pl.BlockSpec((1, bn), lambda j: (0, j))
        s_in = scales.reshape(1, N)
    else:
        kern = functools.partial(_kernel_int4, group=int(group_size))
        w_spec = pl.BlockSpec((K // 2, bn), lambda j: (0, j))
        s_spec = pl.BlockSpec((K // int(group_size), bn),
                              lambda j: (0, j))
        s_in = scales
    with x64_off():
        out = pl.pallas_call(
            kern,
            grid=grid,
            in_specs=[pl.BlockSpec((M, K), lambda j: (0, 0)),
                      w_spec, s_spec],
            out_specs=pl.BlockSpec((M, bn), lambda j: (0, j)),
            out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
            interpret=interpret,
        )(x2, qw, s_in)
    return out.reshape(*lead, N)
