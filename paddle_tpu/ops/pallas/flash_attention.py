"""Flash attention — Pallas TPU kernel, forward + backward.

Reference: the reference wraps the external flash-attention CUDA library
(`cmake/external/flashattn.cmake`, `phi/kernels/gpu/flash_attn_kernel.cu`);
this is the TPU-native equivalent, written directly against the MXU:

  - online-softmax forward (one pass over K blocks per Q block, fp32
    running max/denominator in VMEM), returns out + logsumexp
  - recompute backward: dq kernel (loops K blocks per Q block) and dkv
    kernel (loops Q blocks per K block) — no s×s matrix ever hits HBM
  - causal masking skips whole K blocks past the diagonal (dynamic
    fori_loop bound on the Q-block index)

Layout contract: [b, s, h, d] at the API (paddle flash-attn layout),
transposed to [b*h, s, d] for contiguous sequence tiles.  Requires
s % block == 0 and d % 128 == 0 — callers (paddle_tpu.ops.attention) fall
back to the XLA path otherwise.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

INTERPRET = None  # resolved lazily: True off-TPU (CPU tests)


def _interpret():
    global INTERPRET
    if INTERPRET is None:
        INTERPRET = jax.default_backend() != "tpu"
    return INTERPRET


DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256
NEG_INF = -1e30


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_q, block_k, seq_k):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * jnp.float32(scale)          # [BQ, D]

    # all index arithmetic in int32: mosaic rejects mixed i32/i64 (python
    # ints are weak int64 under jax_enable_x64)
    i32 = lambda v: jnp.asarray(v, jnp.int32)
    num_kb = i32(seq_k // block_k)
    if causal:
        # K blocks through the diagonal of the block's LAST query row
        num_kb = jnp.minimum(
            num_kb,
            ((qi + i32(1)) * i32(block_q) - i32(1)) // i32(block_k) + i32(1))

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * i32(block_k), block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * i32(block_k), block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * i32(block_q) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * i32(block_k) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(NEG_INF))
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    d = q_ref.shape[-1]
    init = (jnp.zeros((block_q, d), jnp.float32),
            jnp.full((block_q,), NEG_INF, jnp.float32),
            jnp.zeros((block_q,), jnp.float32))
    acc, m, l = jax.lax.fori_loop(i32(0), num_kb, body, init)
    l = jnp.maximum(l, jnp.float32(1e-30))
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l))[:, None]


def _fwd(q3, k3, v3, scale, causal, block_q, block_k):
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    grid = (bh, sq // block_q)
    # mosaic rejects the i64/f64 weak constants x64 mode produces; trace the
    # kernel with x64 off (all operands are explicitly typed anyway)
    with jax.enable_x64(False):
        out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_k=sk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
            interpret=_interpret(),
        )(q3, k3, v3)
    return out, lse


# ---------------------------------------------------------------------------
# backward: dq  (grid over Q blocks, loop over K blocks)
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale, causal, block_q, block_k, seq_k):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * jnp.float32(scale)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, 0]
    delta = delta_ref[0][:, 0]

    i32 = lambda v: jnp.asarray(v, jnp.int32)
    num_kb = i32(seq_k // block_k)
    if causal:
        num_kb = jnp.minimum(
            num_kb,
            ((qi + i32(1)) * i32(block_q) - i32(1)) // i32(block_k) + i32(1))

    def body(j, dq):
        k = k_ref[0, pl.ds(j * i32(block_k), block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * i32(block_k), block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * i32(block_q) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * i32(block_k) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(NEG_INF))
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * jnp.float32(scale)
        return dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    d = q_ref.shape[-1]
    dq = jax.lax.fori_loop(i32(0), num_kb, body,
                           jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


# ---------------------------------------------------------------------------
# backward: dk/dv  (grid over K blocks, loop over Q blocks)
# ---------------------------------------------------------------------------
def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, block_q, block_k,
                    seq_q):
    kj = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)

    i32 = lambda v: jnp.asarray(v, jnp.int32)
    num_qb = i32(seq_q // block_q)
    if causal:
        start_qb = kj * i32(block_k) // i32(block_q)
    else:
        start_qb = i32(0)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * i32(block_q), block_q), :].astype(
            jnp.float32) * jnp.float32(scale)
        do = do_ref[0, pl.ds(i * i32(block_q), block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * i32(block_q), block_q), 0]
        delta = delta_ref[0, pl.ds(i * i32(block_q), block_q), 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = i * i32(block_q) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kj * i32(block_k) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(NEG_INF))
        p = jnp.exp(s - lse[:, None])                       # [BQ, BK]
        dv_new = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [BK, D]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        # q above is pre-multiplied by scale, so ds needs no extra factor:
        # dk_true = scale · dlᵀq = dsᵀ · (q·scale)
        ds = p * (dp - delta[:, None])                      # [BQ, BK]
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    d = k_ref.shape[-1]
    init = (jnp.zeros((block_k, d), jnp.float32),
            jnp.zeros((block_k, d), jnp.float32))
    dk, dv = jax.lax.fori_loop(start_qb, num_qb, body, init)
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(scale, causal, block_q, block_k, res, do3):
    q3, k3, v3, out, lse = res
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    delta = jnp.sum(do3.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)                 # [bh, sq, 1]

    with jax.enable_x64(False):
        dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_k=sk),
        grid=(bh, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q3.dtype),
            interpret=_interpret(),
        )(q3, k3, v3, do3, lse, delta)

        dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_q=sq),
        grid=(bh, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, sq, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, sq, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, sq, 1), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, sq, 1), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v3.dtype),
        ],
            interpret=_interpret(),
        )(q3, k3, v3, do3, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry (custom_vjp over [bh, s, d] tensors)
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash3(q3, k3, v3, scale, causal, block_q, block_k):
    out, _ = _fwd(q3, k3, v3, scale, causal, block_q, block_k)
    return out


def _flash3_fwd(q3, k3, v3, scale, causal, block_q, block_k):
    out, lse = _fwd(q3, k3, v3, scale, causal, block_q, block_k)
    return out, (q3, k3, v3, out, lse)


def _flash3_bwd(scale, causal, block_q, block_k, res, do3):
    return _bwd(scale, causal, block_q, block_k, res, do3)


_flash3.defvjp(_flash3_fwd, _flash3_bwd)


def flash_attention(q, k, v, causal=False, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """q/k/v: [b, s, h, d] (paddle layout).  Returns [b, s, h, d]."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    hk = k.shape[2]
    if hk != h:  # GQA: repeat kv heads
        k = jnp.repeat(k, h // hk, axis=2)
        v = jnp.repeat(v, h // hk, axis=2)
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k or d % 128 or sq % block_k:
        raise ValueError("unsupported shape for pallas flash attention")
    if causal and sq != sk:
        # the kernel masks top-left aligned; the framework convention
        # (ops.xla_attention) is bottom-right for cross lengths — refuse and
        # let dispatch fall back rather than silently diverge
        raise ValueError("causal cross-attention not supported by the "
                         "pallas kernel (sq != sk)")
    s = scale if scale is not None else 1.0 / math.sqrt(d)

    q3 = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    k3 = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    v3 = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    out = _flash3(q3, k3, v3, float(s), bool(causal), block_q, block_k)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
