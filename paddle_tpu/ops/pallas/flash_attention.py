"""Flash attention — Pallas TPU kernel, forward + backward.

Reference: the reference wraps the external flash-attention CUDA library
(`cmake/external/flashattn.cmake`, `phi/kernels/gpu/flash_attn_kernel.cu`);
this is the TPU-native equivalent, written directly against the MXU:

  - online-softmax forward over a 3-D grid (batch*head, q-block, k-block)
    with fp32 running max/denominator in VMEM scratch — only ONE K/V tile
    is resident per step, so VMEM use is O(block) and 32k+ contexts fit
  - GQA without materialising repeated KV: the K/V BlockSpec index maps
    fold the q-head → kv-head mapping, so HBM traffic is ∝ num_kv_heads
  - causal masking CLAMPS the K-block index map past the diagonal —
    Mosaic elides the DMA when the block index repeats, so masked blocks
    cost neither bandwidth nor (via pl.when) compute
  - recompute backward: dq kernel (grid over q blocks × k blocks) and
    dkv kernel (grid over kv blocks × (group × q blocks)) — the s×s
    matrix never hits HBM, and dk/dv accumulate over the query-head
    group in-kernel

Layout contract: [b, s, h, d] at the API (paddle flash-attn layout),
transposed to [b*h, s, d] (queries) / [b*h_kv, s, d] (keys, values).
Requires a block size dividing each sequence length (picked from
{512..8} automatically) and d a multiple of 64; callers
(paddle_tpu.ops.attention) fall back to the XLA path otherwise.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from ._x64 import x64_off
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

INTERPRET = None  # resolved lazily: True off-TPU (CPU tests)


def _interpret():
    global INTERPRET
    if INTERPRET is None:
        INTERPRET = jax.default_backend() != "tpu"
    return INTERPRET


DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
NEG_INF = -1e30


def _pick_block(seq, preferred):
    """Largest power-of-two divisor of seq, capped at preferred (min 8)."""
    b = 8
    while b * 2 <= min(preferred, seq):
        b *= 2
    while b >= 8:
        if seq % b == 0:
            return b
        b //= 2
    return None


def _kv_head_map(h, hk):
    """bh-grid-index (over b*h) → kv row (over b*hk)."""
    group = h // hk

    def m(bh):
        return (bh // h) * hk + (bh % h) // group
    return m


# ---------------------------------------------------------------------------
# resident-KV fast path (moderate context): the whole K/V for one kv head
# lives in VMEM and a fori_loop walks its blocks — causal skips trailing
# blocks entirely (dynamic loop bound) and there is no per-KV-block grid
# overhead.  ~2× faster than the blocked path at 2-8k context; selected
# by flash_attention() when the VMEM working set fits.
# ---------------------------------------------------------------------------
def _fwd_small_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                      block_q, block_k, seq_k):
    # dots keep the INPUT dtype (bf16 on the MXU — fp32 operands run at
    # ~1/8 the matmul rate); accumulation is fp32 via
    # preferred_element_type, softmax math is fp32, and the scale is
    # applied to the fp32 logits after the dot
    qi = pl.program_id(1)
    q = q_ref[0]                                                   # [BQ, D]

    # all index arithmetic in int32: mosaic rejects mixed i32/i64 (python
    # ints are weak int64 under jax_enable_x64)
    i32 = lambda v: jnp.asarray(v, jnp.int32)
    num_kb = i32(seq_k // block_k)
    if causal:
        # K blocks through the diagonal of the block's LAST query row
        num_kb = jnp.minimum(
            num_kb,
            ((qi + i32(1)) * i32(block_q) - i32(1)) // i32(block_k) + i32(1))

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * i32(block_k), block_k), :]
        v = v_ref[0, pl.ds(j * i32(block_k), block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) \
            * jnp.float32(scale)
        if causal:
            q_pos = qi * i32(block_q) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * i32(block_k) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(NEG_INF))
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    d = q_ref.shape[-1]
    init = (jnp.zeros((block_q, d), jnp.float32),
            jnp.full((block_q,), NEG_INF, jnp.float32),
            jnp.zeros((block_q,), jnp.float32))
    acc, m, l = jax.lax.fori_loop(i32(0), num_kb, body, init)
    l = jnp.maximum(l, jnp.float32(1e-30))
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l))[:, None]


def _fwd_small(q3, k2, v2, scale, causal, block_q, block_k, h, hk):
    bh, sq, d = q3.shape
    sk = k2.shape[1]
    kvm = _kv_head_map(h, hk)
    kv_spec = lambda b, i: (kvm(b), 0, 0)
    grid = (bh, sq // block_q)
    with x64_off():
        out, lse = pl.pallas_call(
            functools.partial(_fwd_small_kernel, scale=scale, causal=causal,
                              block_q=block_q, block_k=block_k, seq_k=sk),
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, sk, d), kv_spec),
                pl.BlockSpec((1, sk, d), kv_spec),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, sq, d), q3.dtype),
                jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
            ],
            interpret=_interpret(),
        )(q3, k2, v2)
    return out, lse


def _bwd_dq_small_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, scale, causal, block_q, block_k, seq_k):
    qi = pl.program_id(1)
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0][:, 0]
    delta = delta_ref[0][:, 0]

    i32 = lambda v: jnp.asarray(v, jnp.int32)
    num_kb = i32(seq_k // block_k)
    if causal:
        num_kb = jnp.minimum(
            num_kb,
            ((qi + i32(1)) * i32(block_q) - i32(1)) // i32(block_k) + i32(1))

    def body(j, dq):
        k = k_ref[0, pl.ds(j * i32(block_k), block_k), :]
        v = v_ref[0, pl.ds(j * i32(block_k), block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) \
            * jnp.float32(scale)
        if causal:
            q_pos = qi * i32(block_q) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * i32(block_k) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(NEG_INF))
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * jnp.float32(scale)
        return dq + jax.lax.dot_general(ds.astype(k.dtype), k,
                                        (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    d = q_ref.shape[-1]
    dq = jax.lax.fori_loop(i32(0), num_kb, body,
                           jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_small_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                          block_q, block_k, seq_q, group):
    """Grid (b*h_kv, group, kv blocks); each step holds ONE query head's
    q/do row resident (constant over the inner kv-block sweep) and
    accumulates that head's contribution to kv-block kj into full-row
    fp32 VMEM scratch; the last group head flushes scratch to the
    (1, sk, d) output rows."""
    g = pl.program_id(1)
    kj = pl.program_id(2)
    k = k_ref[0]
    v = v_ref[0]

    i32 = lambda v: jnp.asarray(v, jnp.int32)
    num_qb = i32(seq_q // block_q)
    if causal:
        start_qb = kj * i32(block_k) // i32(block_q)
    else:
        start_qb = i32(0)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * i32(block_q), block_q), :]
        do = do_ref[0, pl.ds(i * i32(block_q), block_q), :]
        lse = lse_ref[0, pl.ds(i * i32(block_q), block_q), 0]
        delta = delta_ref[0, pl.ds(i * i32(block_q), block_q), 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) \
            * jnp.float32(scale)
        if causal:
            q_pos = i * i32(block_q) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kj * i32(block_k) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(NEG_INF))
        p = jnp.exp(s - lse[:, None])                   # [BQ, BK]
        dv_new = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # [BK, D]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * jnp.float32(scale)  # [BQ, BK]
        dk_new = dk + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    d = k_ref.shape[-1]
    init = (jnp.zeros((block_k, d), jnp.float32),
            jnp.zeros((block_k, d), jnp.float32))
    dk, dv = jax.lax.fori_loop(start_qb, num_qb, body, init)

    sl = pl.ds(kj * i32(block_k), block_k)

    @pl.when(g == 0)
    def _set():
        dk_acc[sl, :] = dk
        dv_acc[sl, :] = dv

    @pl.when(g != 0)
    def _add():
        dk_acc[sl, :] = dk_acc[sl, :] + dk
        dv_acc[sl, :] = dv_acc[sl, :] + dv

    @pl.when(g == group - 1)
    def _flush():
        dk_ref[0, sl, :] = dk_acc[sl, :].astype(dk_ref.dtype)
        dv_ref[0, sl, :] = dv_acc[sl, :].astype(dv_ref.dtype)


def _bwd_small(scale, causal, block_q, block_k, h, hk, res, do3):
    q3, k2, v2, out, lse = res
    bh, sq, d = q3.shape
    bkv, sk, _ = k2.shape
    group = h // hk
    delta = jnp.sum(do3.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)                 # [bh, sq, 1]
    kvm = _kv_head_map(h, hk)
    kv_spec = lambda b, i: (kvm(b), 0, 0)

    with x64_off():
        dq = pl.pallas_call(
            functools.partial(_bwd_dq_small_kernel, scale=scale,
                              causal=causal, block_q=block_q,
                              block_k=block_k, seq_k=sk),
            grid=(bh, sq // block_q),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, sk, d), kv_spec),
                pl.BlockSpec((1, sk, d), kv_spec),
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            ],
            out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            out_shape=jax.ShapeDtypeStruct((bh, sq, d), q3.dtype),
            interpret=_interpret(),
        )(q3, k2, v2, do3, lse, delta)

        # row b*group + g of the [b*h, sq, ·] arrays is query head g of the
        # group sharing kv row b; full-row outputs + fp32 scratch let the
        # group accumulate across grid steps
        qg_spec = lambda b, g, j: (b * group + g, 0, 0)
        dk, dv = pl.pallas_call(
            functools.partial(_bwd_dkv_small_kernel, scale=scale,
                              causal=causal, block_q=block_q,
                              block_k=block_k, seq_q=sq, group=group),
            grid=(bkv, group, sk // block_k),
            in_specs=[
                pl.BlockSpec((1, sq, d), qg_spec),
                pl.BlockSpec((1, block_k, d), lambda b, g, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, g, j: (b, j, 0)),
                pl.BlockSpec((1, sq, d), qg_spec),
                pl.BlockSpec((1, sq, 1), qg_spec),
                pl.BlockSpec((1, sq, 1), qg_spec),
            ],
            out_specs=[
                pl.BlockSpec((1, sk, d), lambda b, g, j: (b, 0, 0)),
                pl.BlockSpec((1, sk, d), lambda b, g, j: (b, 0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bkv, sk, d), k2.dtype),
                jax.ShapeDtypeStruct((bkv, sk, d), v2.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((sk, d), jnp.float32),
                pltpu.VMEM((sk, d), jnp.float32),
            ],
            interpret=_interpret(),
        )(q3, k2, v2, do3, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# packed single-block path (short context, MHA): when the whole sequence
# fits in ONE block (sq == block_q, sk == block_k) and h == h_kv, the
# per-head work is tiny (s=512, d=64 → 67 MFLOP) and a (b*h,)-sized grid
# is dominated by per-instance overhead — BERT-base at s=512 ran its 12
# attention layers at ~4% MFU.  This path packs `gh` heads per grid
# instance (python-unrolled; refs are [gh, s, d]) and fuses the ENTIRE
# backward — dq, dk, dv — into one kernel so the s×s score matrix is
# recomputed once, not twice.
# ---------------------------------------------------------------------------
def _fwd_1b_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                   gh):
    for g in range(gh):
        q = q_ref[g]                                            # [SQ, D]
        k = k_ref[g]
        v = v_ref[g]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) \
            * jnp.float32(scale)
        if causal:
            q_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(NEG_INF))
        m = jnp.max(s, axis=1)
        p = jnp.exp(s - m[:, None])
        l = jnp.maximum(jnp.sum(p, axis=1), jnp.float32(1e-30))
        o = jax.lax.dot_general(p.astype(v.dtype), v,
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        o_ref[g] = (o / l[:, None]).astype(o_ref.dtype)
        lse_ref[g] = (m + jnp.log(l))[:, None]


def _fwd_1b(q3, k2, v2, scale, causal, gh):
    bh, sq, d = q3.shape
    sk = k2.shape[1]
    spec = lambda b: (b, 0, 0)
    with x64_off():
        out, lse = pl.pallas_call(
            functools.partial(_fwd_1b_kernel, scale=scale, causal=causal,
                              gh=gh),
            grid=(bh // gh,),
            in_specs=[
                pl.BlockSpec((gh, sq, d), spec),
                pl.BlockSpec((gh, sk, d), spec),
                pl.BlockSpec((gh, sk, d), spec),
            ],
            out_specs=[
                pl.BlockSpec((gh, sq, d), spec),
                pl.BlockSpec((gh, sq, 1), spec),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, sq, d), q3.dtype),
                jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
            ],
            interpret=_interpret(),
        )(q3, k2, v2)
    return out, lse


def _bwd_1b_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dk_ref, dv_ref, *, scale, causal, gh):
    for g in range(gh):
        q = q_ref[g]
        k = k_ref[g]
        v = v_ref[g]
        do = do_ref[g]
        lse = lse_ref[g][:, 0]
        delta = delta_ref[g][:, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) \
            * jnp.float32(scale)
        if causal:
            q_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(NEG_INF))
        p = jnp.exp(s - lse[:, None])                           # [SQ, SK]
        dv_ref[g] = jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dv_ref.dtype)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * jnp.float32(scale)
        dq_ref[g] = jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dq_ref.dtype)
        dk_ref[g] = jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dk_ref.dtype)


def _bwd_1b(scale, causal, gh, res, do3):
    q3, k2, v2, out, lse = res
    bh, sq, d = q3.shape
    sk = k2.shape[1]
    delta = jnp.sum(do3.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)
    spec = lambda b: (b, 0, 0)
    with x64_off():
        dq, dk, dv = pl.pallas_call(
            functools.partial(_bwd_1b_kernel, scale=scale, causal=causal,
                              gh=gh),
            grid=(bh // gh,),
            in_specs=[
                pl.BlockSpec((gh, sq, d), spec),
                pl.BlockSpec((gh, sk, d), spec),
                pl.BlockSpec((gh, sk, d), spec),
                pl.BlockSpec((gh, sq, d), spec),
                pl.BlockSpec((gh, sq, 1), spec),
                pl.BlockSpec((gh, sq, 1), spec),
            ],
            out_specs=[
                pl.BlockSpec((gh, sq, d), spec),
                pl.BlockSpec((gh, sk, d), spec),
                pl.BlockSpec((gh, sk, d), spec),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, sq, d), q3.dtype),
                jax.ShapeDtypeStruct((bh, sk, d), k2.dtype),
                jax.ShapeDtypeStruct((bh, sk, d), v2.dtype),
            ],
            interpret=_interpret(),
        )(q3, k2, v2, do3, lse, delta)
    return dq, dk, dv


# per-instance VMEM budget for the packed path: 7 [s,d] operand/result
# rows per head, DOUBLE-buffered by Mosaic, + ~4 concurrent fp32 s×s
# intermediates (scores, p, dp + spill); the scoped limit is 16M so
# leave real headroom
ONE_BLOCK_BUDGET = int(__import__('os').environ.get('PD_FLASH_1B_BUDGET', 9 * 1024 * 1024))


def _pick_gh(bh, sq, sk, d, esize):
    fixed = 4 * sq * sk * 4
    per_head = 2 * 7 * max(sq, sk) * d * esize
    if fixed + per_head > ONE_BLOCK_BUDGET:
        return 0
    cap = min(16, (ONE_BLOCK_BUDGET - fixed) // per_head)
    for g in range(int(cap), 0, -1):
        if bh % g == 0:
            return g
    return 0


# ---------------------------------------------------------------------------
# blocked path (long context): one K/V tile resident per grid step
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, causal, block_q, block_k, num_kb):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # past-diagonal K blocks are fully masked: skip compute (their DMA is
    # already elided by the clamped index map)
    live = (kj * block_k <= (qi + 1) * block_q - 1) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0]                                            # [BQ, D]
        k = k_ref[0]                                            # [BK, D]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) \
            * jnp.float32(scale)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(NEG_INF))
        m_prev = m_ref[...][:, 0]
        l_prev = l_ref[...][:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new[:, None]
        l_ref[...] = l_new[:, None]

    @pl.when(kj == num_kb - 1)
    def _finalize():
        m = m_ref[...][:, 0]
        l = jnp.maximum(l_ref[...][:, 0], jnp.float32(1e-30))
        o_ref[0] = (acc_ref[...] / l[:, None]).astype(o_ref.dtype)
        lse_ref[0] = (m + jnp.log(l))[:, None]


def _causal_clamp(block_q, block_k, num_kb):
    """K-block index for grid step (qi, kj): clamp past the diagonal so the
    repeated index elides the DMA."""
    def idx(qi, kj):
        last = ((qi + 1) * block_q - 1) // block_k  # last live K block
        return jnp.minimum(kj, jnp.minimum(last, num_kb - 1))
    return idx


def _fwd(q3, k2, v2, scale, causal, block_q, block_k, h, hk):
    bh, sq, d = q3.shape
    sk = k2.shape[1]
    num_kb = sk // block_k
    kvm = _kv_head_map(h, hk)
    if causal:
        kidx = _causal_clamp(block_q, block_k, num_kb)
        kv_spec = lambda b, i, j: (kvm(b), kidx(i, j), 0)
    else:
        kv_spec = lambda b, i, j: (kvm(b), j, 0)
    grid = (bh, sq // block_q, num_kb)
    # mosaic rejects the i64/f64 weak constants x64 mode produces; trace the
    # kernel with x64 off (all operands are explicitly typed anyway)
    with x64_off():
        out, lse = pl.pallas_call(
            functools.partial(_fwd_kernel, scale=scale, causal=causal,
                              block_q=block_q, block_k=block_k,
                              num_kb=num_kb),
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), kv_spec),
                pl.BlockSpec((1, block_k, d), kv_spec),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, sq, d), q3.dtype),
                jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, d), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
            ],
            interpret=_interpret(),
        )(q3, k2, v2)
    return out, lse


# ---------------------------------------------------------------------------
# backward: dq  (grid over q blocks × k blocks, accumulate dq in scratch)
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_ref, *, scale, causal, block_q, block_k, num_kb):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    live = (kj * block_k <= (qi + 1) * block_q - 1) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, 0]
        delta = delta_ref[0][:, 0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) \
            * jnp.float32(scale)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(NEG_INF))
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * jnp.float32(scale)
        acc_ref[...] = acc_ref[...] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == num_kb - 1)
    def _finalize():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


# ---------------------------------------------------------------------------
# backward: dk/dv  (grid over kv blocks × (group × q blocks); dk/dv
# accumulate over the whole query-head group in VMEM scratch)
# ---------------------------------------------------------------------------
def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    block_q, block_k, num_qb, num_t):
    kj = pl.program_id(1)
    t = pl.program_id(2)
    qi = t % num_qb

    @pl.when(t == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    # q blocks strictly above the diagonal contribute nothing
    live = ((qi + 1) * block_q - 1 >= kj * block_k) if causal else True

    @pl.when(live)
    def _compute():
        k = k_ref[0]
        v = v_ref[0]
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, 0]
        delta = delta_ref[0][:, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) \
            * jnp.float32(scale)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(NEG_INF))
        p = jnp.exp(s - lse[:, None])                       # [BQ, BK]
        dv_acc[...] = dv_acc[...] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [BK, D]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * jnp.float32(scale)  # [BQ, BK]
        dk_acc[...] = dk_acc[...] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(t == num_t - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd(scale, causal, block_q, block_k, h, hk, res, do3):
    q3, k2, v2, out, lse = res
    bh, sq, d = q3.shape
    bkv, sk, _ = k2.shape
    group = h // hk
    num_qb = sq // block_q
    num_kb = sk // block_k
    delta = jnp.sum(do3.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)                 # [bh, sq, 1]

    kvm = _kv_head_map(h, hk)
    if causal:
        kidx = _causal_clamp(block_q, block_k, num_kb)
        kv_spec = lambda b, i, j: (kvm(b), kidx(i, j), 0)
    else:
        kv_spec = lambda b, i, j: (kvm(b), j, 0)

    with x64_off():
        dq = pl.pallas_call(
            functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                              block_q=block_q, block_k=block_k,
                              num_kb=num_kb),
            grid=(bh, num_qb, num_kb),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), kv_spec),
                pl.BlockSpec((1, block_k, d), kv_spec),
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            ],
            out_specs=pl.BlockSpec((1, block_q, d),
                                   lambda b, i, j: (b, i, 0)),
            out_shape=jax.ShapeDtypeStruct((bh, sq, d), q3.dtype),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
            interpret=_interpret(),
        )(q3, k2, v2, do3, lse, delta)

        # dkv grid: minor axis t enumerates (g, qi) pairs — for each query
        # head g in the group, all q blocks.  Index maps fold the group
        # head offset into the q-row lookup.
        num_t = group * num_qb

        def q_row(b, j, t):
            g = t // num_qb
            return (b // hk) * h + (b % hk) * group + g

        if causal:
            def q_blk(b, j, t):
                qi = t % num_qb
                first = (j * block_k) // block_q   # first live q block
                return jnp.maximum(qi, first)
        else:
            def q_blk(b, j, t):
                return t % num_qb

        q_spec = lambda b, j, t: (q_row(b, j, t), q_blk(b, j, t), 0)

        dk, dv = pl.pallas_call(
            functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                              block_q=block_q, block_k=block_k,
                              num_qb=num_qb, num_t=num_t),
            grid=(bkv, num_kb, num_t),
            in_specs=[
                pl.BlockSpec((1, block_q, d), q_spec),
                pl.BlockSpec((1, block_k, d), lambda b, j, t: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, j, t: (b, j, 0)),
                pl.BlockSpec((1, block_q, d), q_spec),
                pl.BlockSpec((1, block_q, 1), q_spec),
                pl.BlockSpec((1, block_q, 1), q_spec),
            ],
            out_specs=[
                pl.BlockSpec((1, block_k, d), lambda b, j, t: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, j, t: (b, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bkv, sk, d), k2.dtype),
                jax.ShapeDtypeStruct((bkv, sk, d), v2.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, d), jnp.float32),
                pltpu.VMEM((block_k, d), jnp.float32),
            ],
            interpret=_interpret(),
        )(q3, k2, v2, do3, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry (custom_vjp over [b*h, s, d] / [b*h_kv, s, d] tensors)
# ---------------------------------------------------------------------------
def _run_fwd(q3, k2, v2, scale, causal, block_q, block_k, h, hk,
             small_fwd, gh1b):
    if gh1b:
        return _fwd_1b(q3, k2, v2, scale, causal, gh1b)
    fwd = _fwd_small if small_fwd else _fwd
    return fwd(q3, k2, v2, scale, causal, block_q, block_k, h, hk)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11))
def _flash3(q3, k2, v2, scale, causal, block_q, block_k, h, hk,
            small_fwd, small_bwd, gh1b):
    out, _ = _run_fwd(q3, k2, v2, scale, causal, block_q, block_k, h, hk,
                      small_fwd, gh1b)
    return out


def _flash3_fwd(q3, k2, v2, scale, causal, block_q, block_k, h, hk,
                small_fwd, small_bwd, gh1b):
    out, lse = _run_fwd(q3, k2, v2, scale, causal, block_q, block_k, h,
                        hk, small_fwd, gh1b)
    # the kernels use a trailing size-1 dim for lse (Mosaic-friendly
    # blocks), but a (bh, sq, 1) RESIDUAL would be stored 128-lane padded
    # (128x memory) between forward and backward — keep it dense 2D and
    # re-expand at the kernel boundary
    return out, (q3, k2, v2, out, lse.reshape(lse.shape[:2]))


def _flash3_bwd(scale, causal, block_q, block_k, h, hk, small_fwd,
                small_bwd, gh1b, res, do3):
    q3, k2, v2, out, lse2 = res
    res3 = (q3, k2, v2, out, lse2[..., None])
    if gh1b:
        return _bwd_1b(scale, causal, gh1b, res3, do3)
    bwd = _bwd_small if small_bwd else _bwd
    return bwd(scale, causal, block_q, block_k, h, hk, res3, do3)


_flash3.defvjp(_flash3_fwd, _flash3_bwd)

# resident-KV path budgets: the scoped VMEM limit is ~16 MiB and blocks
# are double-buffered, so the resident operands must stay well under half
SMALL_KV_BYTES = 4 * 1024 * 1024       # K+V for one kv head (fwd, dq)
SMALL_DKV_SCRATCH_BYTES = 4 * 1024 * 1024  # fp32 dk+dv row scratch (dkv)


def flash_attention(q, k, v, causal=False, scale=None,
                    block_q=None, block_k=None):
    """q/k/v: [b, s, h, d] (paddle layout; k/v may have fewer heads for
    GQA/MQA — h % h_kv == 0).  Returns [b, s, h, d]."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    hk = k.shape[2]
    if h % hk:
        raise ValueError("num q heads must be a multiple of num kv heads")
    # keep the working set (q, k, v tiles + fp32 acc) well under VMEM:
    # shrink blocks as head_dim grows
    pref = DEFAULT_BLOCK_Q if d <= 128 else max(128, 32768 // d)
    bq = _pick_block(sq, block_q or pref)
    bk = _pick_block(sk, block_k or pref)
    if bq is None or bk is None or d % 64:
        raise ValueError("unsupported shape for pallas flash attention")
    if causal and sq != sk:
        # the kernel masks top-left aligned; the framework convention
        # (ops.xla_attention) is bottom-right for cross lengths — refuse and
        # let dispatch fall back rather than silently diverge
        raise ValueError("causal cross-attention not supported by the "
                         "pallas kernel (sq != sk)")
    s = scale if scale is not None else 1.0 / math.sqrt(d)

    esize = jnp.dtype(q.dtype).itemsize
    group = h // hk
    small_fwd = 2 * sk * d * esize <= SMALL_KV_BYTES
    small_bwd = (small_fwd
                 and 8 * sk * d <= SMALL_DKV_SCRATCH_BYTES
                 and 2 * sq * d * esize <= SMALL_KV_BYTES)
    # packed whole-sequence path: MHA with the full sequence in one block
    gh1b = _pick_gh(b * h, sq, sk, d, esize) \
        if (group == 1 and bq == sq and bk == sk) else 0

    q3 = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    k2 = k.transpose(0, 2, 1, 3).reshape(b * hk, sk, d)
    v2 = v.transpose(0, 2, 1, 3).reshape(b * hk, sk, d)
    out = _flash3(q3, k2, v2, float(s), bool(causal), bq, bk, h, hk,
                  small_fwd, small_bwd, gh1b)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
