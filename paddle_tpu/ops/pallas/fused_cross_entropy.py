"""Fused chunked linear + cross-entropy — the logits never land in HBM.

Reference problem (PROFILE_r05): the causal-LM loss upcasts the full
[B, S, V] logits to fp32 — at the llama bench shape that single buffer
(256 MB) is the largest live allocation in the step, and the
log_softmax + gather over it is pure memory traffic on the non-matmul
side of the MFU gap.  The memory-efficient fusion popularized by
Liger-Kernel-style chunked losses computes the loss FROM THE HIDDEN
STATES, chunking over rows (tokens), so only a [chunk, V] slice of
logits ever exists:

  per row chunk c:
    logits_c = h_c @ W (+ b)            fp32 accumulation
    lse_c    = logsumexp(logits_c)      one VMEM pass (Pallas on TPU)
    dlog_c   = (softmax - onehot)/n     computed IN THE SAME PASS
    dh_c     = dlog_c @ W.T             written directly
    dW      += h_c.T @ dlog_c

The custom VJP therefore does all gradient work in the forward sweep
(the standard trick: d logits is known up to the scalar upstream
cotangent) and the backward is three scalar multiplies.  For vocabs too
large for a [chunk, V] fp32 tile, `vocab_chunk` switches the statistics
to an ONLINE log-softmax denominator (flash-attention-style running
max/sum folded over vocab chunks) with a second vocab sweep for the
gradients — no [chunk, V] buffer at all.

Vocab-sharded (reference ParallelCrossEntropy / mp_layers.py
c_softmax_with_cross_entropy): under shard_map with `axis_name`, each
shard computes its local max / denominator / picked logit and combines
them with one pmax + psum — the per-shard online-softmax merge — and
psums the hidden gradient (each shard's dlog_c @ W_local.T is a partial
sum over its vocab slice).

All paths share the same fp32 math; the Pallas kernel is used on TPU
(interpret mode in tests) and the jnp twin everywhere else.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from ._x64 import x64_off
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_linear_cross_entropy"]

# rows per scan chunk: bounds the transient fp32 logits slice to
# [_DEFAULT_CHUNK, V] (32 MB at V=8192) regardless of batch*seq
_DEFAULT_CHUNK = 1024

# the Pallas kernel walks the [rows, V] logits in row blocks of <=8, so
# its VMEM working set is ~4 fp32 [8, V] buffers, double-buffered across
# grid steps: at V=2^15 that is ~8 MB against ~16 MB of scoped VMEM —
# the safe ceiling.  Vocabs past it dispatch the jnp twin (XLA tiles the
# same math) instead of dying in a Mosaic VMEM error at compile time.
_KERNEL_MAX_VOCAB = 1 << 15


def _interpret():
    return jax.default_backend() != "tpu"


class _CEConfig(NamedTuple):
    ignore_index: Optional[int]
    chunk_rows: int
    vocab_chunk: Optional[int]
    axis_name: Optional[str]
    use_pallas: bool


# ---------------------------------------------------------------------------
# Pallas kernel: one VMEM pass over a [rows, V] logits chunk produces the
# per-row loss AND the (softmax - onehot) gradient — logits are read once.

def _ce_kernel(scale_ref, lg_ref, lbl_ref, loss_ref, dlg_ref):
    x = lg_ref[...].astype(jnp.float32)                 # [br, V]
    lbl = lbl_ref[...]                                  # [br] int32
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    s = jnp.sum(e, axis=-1, keepdims=True)
    lse = (m + jnp.log(s))[:, 0]
    onehot = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1) \
        == lbl[:, None]
    valid = lbl >= 0
    picked = jnp.sum(jnp.where(onehot, x, 0.0), axis=-1)
    scale = scale_ref[0]
    loss_ref[...] = jnp.where(valid, lse - picked, 0.0) * scale
    d = (e / s - onehot.astype(jnp.float32)) * scale
    dlg_ref[...] = jnp.where(valid[:, None], d, 0.0).astype(dlg_ref.dtype)


def _ce_rows_pallas(logits, labels, scale, out_dtype):
    """(loss_rows [C] f32, dlogits [C, V] out_dtype) for one chunk."""
    rows, v = logits.shape
    br = next((d for d in (8, 4, 2, 1) if rows % d == 0), 1)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    with x64_off():
        loss_rows, dlog = pl.pallas_call(
            _ce_kernel,
            grid=(rows // br,),
            in_specs=[smem,
                      pl.BlockSpec((br, v), lambda i: (i, 0)),
                      pl.BlockSpec((br,), lambda i: (i,))],
            out_specs=[pl.BlockSpec((br,), lambda i: (i,)),
                       pl.BlockSpec((br, v), lambda i: (i, 0))],
            out_shape=[jax.ShapeDtypeStruct((rows,), jnp.float32),
                       jax.ShapeDtypeStruct((rows, v), out_dtype)],
            interpret=_interpret(),
        )(scale.reshape(1), logits, labels)
    return loss_rows, dlog


def _ce_rows_jnp(logits, labels, scale, out_dtype):
    """jnp twin of `_ce_kernel` — identical math, XLA-fused."""
    x = logits.astype(jnp.float32)
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    s = jnp.sum(e, axis=-1, keepdims=True)
    lse = (m + jnp.log(s))[:, 0]
    valid = labels >= 0
    safe = jnp.maximum(labels, 0)
    picked = jnp.take_along_axis(x, safe[:, None], axis=-1)[:, 0]
    loss_rows = jnp.where(valid, lse - picked, 0.0) * scale
    onehot = jax.nn.one_hot(safe, x.shape[-1], dtype=jnp.float32)
    d = (e / s - onehot) * scale
    dlog = jnp.where(valid[:, None], d, 0.0).astype(out_dtype)
    return loss_rows, dlog


# ---------------------------------------------------------------------------
# chunk-level fused forward+grad (all sharding/vocab-chunk variants)

def _shard_offset(v_local, axis_name):
    return jax.lax.axis_index(axis_name) * v_local if axis_name else 0


def _vocab_chunked(cfg, v_local):
    # divisibility and the axis_name exclusion are validated at the
    # entry point; a chunk >= the vocab simply means "one chunk" — the
    # direct path already is that
    return bool(cfg.vocab_chunk) and v_local > cfg.vocab_chunk


def _chunk_fwdgrad(h_c, w, b, lbl_c, scale, cfg):
    """One row chunk: (loss_sum, dh_c, dW_partial, db_partial).

    dh/dW carry the 1/n_valid scale (upstream cotangent applied in the
    VJP's backward).  Under `axis_name` the stats are combined across
    vocab shards (pmax on the max, psum on denominator/picked) and dh is
    a psum of the per-shard partial products.
    """
    cd = w.dtype
    v_local = w.shape[1]
    off = _shard_offset(v_local, cfg.axis_name)

    if _vocab_chunked(cfg, v_local):
        return _chunk_fwdgrad_online(h_c, w, b, lbl_c, scale, cfg)

    logits = jnp.dot(h_c, w, preferred_element_type=jnp.float32)
    if b is not None:
        logits = logits + b.astype(jnp.float32)
    if cfg.axis_name:
        # per-shard online-softmax merge: local max → pmax, local
        # denominator/picked → psum.  The local gather hits only labels
        # that fall inside this shard's [off, off+v_local) slice.
        lbl_loc = lbl_c - off
        in_shard = (lbl_loc >= 0) & (lbl_loc < v_local)
        valid = lbl_c >= 0
        safe = jnp.clip(lbl_loc, 0, v_local - 1)
        m = jax.lax.pmax(jnp.max(logits, axis=-1), cfg.axis_name)
        e = jnp.exp(logits - m[:, None])
        s = jax.lax.psum(jnp.sum(e, axis=-1), cfg.axis_name)
        picked_loc = jnp.take_along_axis(logits, safe[:, None],
                                         axis=-1)[:, 0]
        picked = jax.lax.psum(
            jnp.where(in_shard, picked_loc, 0.0), cfg.axis_name)
        lse = m + jnp.log(s)
        loss_sum = jnp.sum(jnp.where(valid, lse - picked, 0.0)) * scale
        onehot = jax.nn.one_hot(safe, v_local, dtype=jnp.float32) \
            * in_shard[:, None].astype(jnp.float32)
        d = (e / s[:, None] - onehot) * scale
        dlog = jnp.where(valid[:, None], d, 0.0).astype(cd)
        dh = jax.lax.psum(
            jnp.dot(dlog, w.T, preferred_element_type=jnp.float32),
            cfg.axis_name)
    else:
        if cfg.use_pallas and v_local <= _KERNEL_MAX_VOCAB:
            loss_rows, dlog = _ce_rows_pallas(logits, lbl_c, scale, cd)
        else:
            loss_rows, dlog = _ce_rows_jnp(logits, lbl_c, scale, cd)
        loss_sum = jnp.sum(loss_rows)
        dh = jnp.dot(dlog, w.T, preferred_element_type=jnp.float32)
    dw = jnp.dot(h_c.T.astype(cd), dlog,
                 preferred_element_type=jnp.float32)
    db = jnp.sum(dlog.astype(jnp.float32), axis=0) if b is not None \
        else None
    return loss_sum, dh.astype(h_c.dtype), dw, db


def _online_logits_at(h_c, w, b, vc, j):
    wj = jax.lax.dynamic_slice_in_dim(w, j * vc, vc, axis=1)
    lg = jnp.dot(h_c, wj, preferred_element_type=jnp.float32)
    if b is not None:
        lg = lg + jax.lax.dynamic_slice_in_dim(
            b, j * vc, vc).astype(jnp.float32)
    return lg, wj


def _online_stats(h_c, w, b, lbl_c, vc):
    """Flash-attention-style running (max, denom, picked) folded over
    vocab chunks of size vc — never a [rows, V] buffer."""
    rows = h_c.shape[0]
    nvc = w.shape[1] // vc

    def pass1(carry, j):
        m, s, picked = carry
        lg, _ = _online_logits_at(h_c, w, b, vc, j)
        m_new = jnp.maximum(m, jnp.max(lg, axis=-1))
        s = s * jnp.exp(m - m_new) \
            + jnp.sum(jnp.exp(lg - m_new[:, None]), axis=-1)
        loc = lbl_c - j * vc
        hit = (loc >= 0) & (loc < vc)
        safe = jnp.clip(loc, 0, vc - 1)
        picked = picked + jnp.where(
            hit, jnp.take_along_axis(lg, safe[:, None], axis=-1)[:, 0],
            0.0)
        return (m_new, s, picked), None

    (m, s, picked), _ = jax.lax.scan(
        pass1, (jnp.full((rows,), -jnp.inf, jnp.float32),
                jnp.zeros((rows,), jnp.float32),
                jnp.zeros((rows,), jnp.float32)),
        jnp.arange(nvc))
    return m, s, picked


def _chunk_fwdgrad_online(h_c, w, b, lbl_c, scale, cfg):
    """Online-denominator variant: two folds over vocab chunks, never a
    [rows, V] buffer.  Pass 1 carries the running (max, denom, picked);
    pass 2 recomputes each logits slice to emit dh/dW per vocab chunk.
    """
    vc = cfg.vocab_chunk
    v = w.shape[1]
    nvc = v // vc
    rows = h_c.shape[0]
    cd = w.dtype
    valid = lbl_c >= 0

    m, s, picked = _online_stats(h_c, w, b, lbl_c, vc)
    lse = m + jnp.log(s)
    loss_sum = jnp.sum(jnp.where(valid, lse - picked, 0.0)) * scale

    def pass2(carry, j):
        dh, dw, db = carry
        lg, wj = _online_logits_at(h_c, w, b, vc, j)
        loc = lbl_c - j * vc
        hit = (loc >= 0) & (loc < vc)
        safe = jnp.clip(loc, 0, vc - 1)
        onehot = jax.nn.one_hot(safe, vc, dtype=jnp.float32) \
            * hit[:, None].astype(jnp.float32)
        d = (jnp.exp(lg - m[:, None]) / s[:, None] - onehot) * scale
        dlog = jnp.where(valid[:, None], d, 0.0).astype(cd)
        dh = dh + jnp.dot(dlog, wj.T,
                          preferred_element_type=jnp.float32)
        dw = jax.lax.dynamic_update_slice_in_dim(
            dw, jnp.dot(h_c.T.astype(cd), dlog,
                        preferred_element_type=jnp.float32),
            j * vc, axis=1)
        if b is not None:
            db = jax.lax.dynamic_update_slice_in_dim(
                db, jnp.sum(dlog.astype(jnp.float32), axis=0), j * vc,
                axis=0)
        return (dh, dw, db), None

    dh0 = jnp.zeros((rows, h_c.shape[1]), jnp.float32)
    dw0 = jnp.zeros((h_c.shape[1], v), jnp.float32)
    db0 = jnp.zeros((v,), jnp.float32) if b is not None else jnp.zeros(())
    (dh, dw, db), _ = jax.lax.scan(pass2, (dh0, dw0, db0),
                                   jnp.arange(nvc))
    return (loss_sum, dh.astype(h_c.dtype), dw,
            db if b is not None else None)


def _chunk_loss_only(h_c, w, b, lbl_c, scale, cfg):
    """Loss without gradient work (the primal when not differentiated).
    Honors vocab_chunk like the fwdgrad path: the online pass-1 stats
    alone give the loss with no [rows, V] buffer."""
    v_local = w.shape[1]
    off = _shard_offset(v_local, cfg.axis_name)
    if _vocab_chunked(cfg, v_local):
        m, s, picked = _online_stats(h_c, w, b, lbl_c, cfg.vocab_chunk)
        valid = lbl_c >= 0
        lse = m + jnp.log(s)
        return jnp.sum(jnp.where(valid, lse - picked, 0.0)) * scale
    logits = jnp.dot(h_c, w, preferred_element_type=jnp.float32)
    if b is not None:
        logits = logits + b.astype(jnp.float32)
    valid = lbl_c >= 0
    if cfg.axis_name:
        lbl_loc = lbl_c - off
        in_shard = (lbl_loc >= 0) & (lbl_loc < v_local)
        safe = jnp.clip(lbl_loc, 0, v_local - 1)
        m = jax.lax.pmax(jnp.max(logits, axis=-1), cfg.axis_name)
        s = jax.lax.psum(
            jnp.sum(jnp.exp(logits - m[:, None]), axis=-1),
            cfg.axis_name)
        picked = jax.lax.psum(jnp.where(
            in_shard,
            jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0],
            0.0), cfg.axis_name)
        lse = m + jnp.log(s)
    else:
        lse = jax.nn.logsumexp(logits, axis=-1)
        safe = jnp.maximum(lbl_c, 0)
        picked = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
    return jnp.sum(jnp.where(valid, lse - picked, 0.0)) * scale


# ---------------------------------------------------------------------------
# row-chunked scan + custom VJP

def _pad_rows(hidden, labels, chunk):
    n = hidden.shape[0]
    pad = -n % chunk
    if pad:
        hidden = jnp.pad(hidden, ((0, pad), (0, 0)))
        labels = jnp.pad(labels, (0, pad), constant_values=-1)
    return hidden, labels, n, pad


def _scan_chunks(fn, hidden, labels, chunk, init):
    h3 = hidden.reshape(-1, chunk, hidden.shape[1])
    l2 = labels.reshape(-1, chunk)
    return jax.lax.scan(fn, init, (h3, l2))


def _scale_of(labels, cfg):
    # NO psum under axis_name: vocab sharding replicates the rows (and
    # their labels) across shards — every shard sees the same count
    valid = (labels >= 0).astype(jnp.float32)
    return 1.0 / jnp.maximum(jnp.sum(valid), 1.0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _flce(hidden, weight, bias, labels, cfg):
    hidden, labels, _, _ = _pad_rows(hidden, labels, cfg.chunk_rows)
    scale = _scale_of(labels, cfg)

    def body(acc, xs):
        h_c, l_c = xs
        return acc + _chunk_loss_only(h_c, weight, bias, l_c, scale,
                                      cfg), None

    loss, _ = _scan_chunks(body, hidden, labels, cfg.chunk_rows,
                           jnp.zeros((), jnp.float32))
    return loss


def _flce_fwd(hidden, weight, bias, labels, cfg):
    hidden_p, labels_p, n, pad = _pad_rows(hidden, labels,
                                           cfg.chunk_rows)
    scale = _scale_of(labels_p, cfg)
    dw0 = jnp.zeros(weight.shape, jnp.float32)
    db0 = jnp.zeros(bias.shape, jnp.float32) if bias is not None else None

    def body(acc, xs):
        loss, dw, db = acc
        h_c, l_c = xs
        ls, dh_c, dw_c, db_c = _chunk_fwdgrad(h_c, weight, bias, l_c,
                                              scale, cfg)
        if db is not None:
            db = db + db_c
        return (loss + ls, dw + dw_c, db), dh_c

    (loss, dw, db), dh = _scan_chunks(
        body, hidden_p, labels_p, cfg.chunk_rows,
        (jnp.zeros((), jnp.float32), dw0, db0))
    dh = dh.reshape(-1, hidden.shape[1])[:n]
    return loss, (dh, dw.astype(weight.dtype),
                  None if db is None else db.astype(bias.dtype))


def _flce_bwd(cfg, res, g):
    dh, dw, db = res
    g = g.astype(jnp.float32)
    return (dh * g.astype(dh.dtype), dw * g.astype(dw.dtype),
            None if db is None else db * g.astype(db.dtype), None)


_flce.defvjp(_flce_fwd, _flce_bwd)


def fused_linear_cross_entropy(hidden, weight, labels, bias=None, *,
                               transpose_weight=False, ignore_index=None,
                               chunk_rows=None, vocab_chunk=None,
                               axis_name=None, use_pallas=None):
    """Mean cross entropy of `hidden @ weight (+ bias)` against integer
    `labels`, computed in row chunks so the full logits tensor is never
    materialized.  hidden: [N, H] (or [..., H], flattened); weight:
    [H, V] (or [V, H] with transpose_weight — the tied-embedding
    layout); labels: [N] int, rows with `ignore_index` (or any negative
    label) excluded from the masked mean.

    axis_name: vocab-sharded mode for shard_map callers — `weight` is
    this shard's [H, V/n] slice and the softmax statistics are combined
    with one pmax + psum per chunk (the reference ParallelCrossEntropy
    contract).  Gradients flow to hidden, weight and bias via a custom
    VJP whose work happens in the forward sweep.
    """
    h2 = hidden.reshape(-1, hidden.shape[-1])
    lbl = labels.reshape(-1).astype(jnp.int32)
    if ignore_index is not None and ignore_index >= 0:
        lbl = jnp.where(lbl == ignore_index, -1, lbl)
    if transpose_weight:
        weight = weight.T
    n = h2.shape[0]
    chunk = int(chunk_rows) if chunk_rows else min(_DEFAULT_CHUNK, n)
    chunk = max(1, min(chunk, n))
    if vocab_chunk:
        # loud validation beats a silent fall-through to the very
        # [chunk, V] materialization the option exists to avoid
        v = weight.shape[1]
        if axis_name is not None:
            raise ValueError(
                "vocab_chunk is not supported with axis_name: the vocab "
                "is already sharded; size the per-shard slice instead")
        if v % int(vocab_chunk) != 0:
            raise ValueError(
                f"vocab_chunk={vocab_chunk} must divide the vocab "
                f"dimension ({v})")
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    cfg = _CEConfig(ignore_index=ignore_index, chunk_rows=chunk,
                    vocab_chunk=vocab_chunk, axis_name=axis_name,
                    use_pallas=bool(use_pallas))
    return _flce(h2, weight, bias, lbl, cfg)
