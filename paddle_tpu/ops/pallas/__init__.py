"""Pallas TPU kernels — the hand-written hot set.

Reference equivalents: `paddle/phi/kernels/fusion/gpu/` (flash_attn via the
external flash-attention CUDA library, fused_rms_norm) and
`paddle/phi/kernels/gpu/flash_attn_kernel.cu`.

Kernels here follow the TPU playbook (/opt/skills/guides/pallas_guide.md):
block shapes aligned to (16,128) bf16 tiles, fp32 accumulation in VMEM
scratch, custom_vjp with Pallas backward kernels.
"""
