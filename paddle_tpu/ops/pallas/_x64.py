"""x64-off context compatible across jax versions.

paddle_tpu enables x64 globally (paddle int64/float64 semantics), but
the Pallas kernels must trace with x64 semantics disabled so weak
python constants stay 32-bit — Mosaic rejects 64-bit avals.  Newer jax
exposes `jax.enable_x64(False)` as a trace-safe context manager; this
environment's jax (0.4.37) removed it, and BOTH remaining spellings
are broken there:

  - `jax.experimental.disable_x64()` leaves `jax_enable_x64=True` on
    exit, flipping the whole process into x64 mode permanently;
  - toggling via `jax.config.update` mid-trace corrupts interpret-mode
    lowering (weak f32 literals in the traced kernel canonicalize to
    f64 at lowering time, outside the context — "expected tensor<f32>,
    provided tensor<f64>").

So: use the native context manager when it exists (every Mosaic-
capable jax), otherwise a no-op — the CPU interpret path tolerates
64-bit avals, and the kernels keep their accumulation math explicitly
typed (jnp.float32(...)) so ambient-x64 tracing computes identical
numerics.
"""
from __future__ import annotations

import contextlib

import jax

__all__ = ["x64_off"]


def x64_off():
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(False)
    return contextlib.nullcontext()
