"""Fused rotary-position-embedding application — Pallas TPU kernel.

Reference: `python/paddle/incubate/nn/functional/
fused_rotary_position_embedding.py` (NeoX rotate-half).  The XLA path
(ops.apply_rope) builds the rotation from concat/slice ops whose fp32
intermediates and layout shuffles sit on the non-matmul side of the MFU
gap (PROFILE_r05); this kernel applies the rotation to q AND k in one
VMEM pass per row block — each operand is read once, written once.

The q/k backward is the SAME kernel with sin negated — the rotation is
orthogonal (R(θ)ᵀ = R(−θ)): dq = rope(g_q, cos, −sin), dk likewise.
cos/sin cotangents (for learned/scaled caches) are computed in plain
jnp from the saved inputs; when nothing differentiates the cache, XLA
DCE prunes both the computation and the input residuals.  For the
half-split layout the transpose ALSO swaps which sin half multiplies
which gradient half (fwd: o1 = x1·c1 − x2·s1, o2 = x2·c2 + x1·s2 ⇒
adjoint: dx1 = g1·c1 + g2·s2, dx2 = g2·c2 − g1·s1), so the backward
feeds the kernel sin with its halves swapped — a no-op for the
standard NeoX cache (both halves identical) but required for any
user-supplied cache whose halves differ.

Layout: q [b, s, h, d] and k [b, s, hk, d] are viewed as [b·s, h, d]
row-major; cos/sin [s, d] (or [b, s, d]) broadcast to [b·s, d] rows so
one BlockSpec serves every head.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from ._x64 import x64_off
from jax.experimental import pallas as pl

__all__ = ["rope_apply"]

# fp32 working-set budget per grid step (q+k+outs+cos/sin+temps)
_VMEM_BUDGET = 6 * 1024 * 1024


def _interpret():
    return jax.default_backend() != "tpu"


def _rope_kernel(q_ref, k_ref, c_ref, s_ref, oq_ref, ok_ref, *, neg_sin):
    c = c_ref[...].astype(jnp.float32)[:, None, :]    # [br, 1, d]
    s = s_ref[...].astype(jnp.float32)[:, None, :]
    if neg_sin:
        s = -s
    half = c.shape[-1] // 2
    c1, c2 = c[..., :half], c[..., half:]
    s1, s2 = s[..., :half], s[..., half:]

    def rot(x_ref, o_ref):
        x = x_ref[...].astype(jnp.float32)            # [br, h, d]
        x1, x2 = x[..., :half], x[..., half:]
        # out = x*cos + rotate_half(x)*sin, rotate_half = [-x2, x1]
        o_ref[..., :half] = (x1 * c1 - x2 * s1).astype(o_ref.dtype)
        o_ref[..., half:] = (x2 * c2 + x1 * s2).astype(o_ref.dtype)

    rot(q_ref, oq_ref)
    rot(k_ref, ok_ref)


def _pick_rows(rows, per_row_f32):
    cap = max(8, (_VMEM_BUDGET // max(per_row_f32, 1) // 8) * 8)
    for br in (512, 256, 128, 64, 32, 16, 8):
        if br <= cap and rows % br == 0:
            return br
    raise ValueError(f"no sublane-aligned row block for {rows} rows")


def _rope3(q3, k3, c2, s2, neg_sin):
    rows, h, d = q3.shape
    hk = k3.shape[1]
    per_row = 4 * d * (3 * (h + hk) + 4)   # operands+outputs+temps, f32
    br = _pick_rows(rows, per_row)
    grid = (rows // br,)
    with x64_off():
        oq, ok = pl.pallas_call(
            functools.partial(_rope_kernel, neg_sin=neg_sin),
            grid=grid,
            in_specs=[pl.BlockSpec((br, h, d), lambda i: (i, 0, 0)),
                      pl.BlockSpec((br, hk, d), lambda i: (i, 0, 0)),
                      pl.BlockSpec((br, d), lambda i: (i, 0)),
                      pl.BlockSpec((br, d), lambda i: (i, 0))],
            out_specs=[pl.BlockSpec((br, h, d), lambda i: (i, 0, 0)),
                       pl.BlockSpec((br, hk, d), lambda i: (i, 0, 0))],
            out_shape=[jax.ShapeDtypeStruct(q3.shape, q3.dtype),
                       jax.ShapeDtypeStruct(k3.shape, k3.dtype)],
            interpret=_interpret(),
        )(q3, k3, c2, s2)
    return oq, ok


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _rope_core(q3, k3, c2, s2):
    return _rope3(q3, k3, c2, s2, neg_sin=False)


def _rope_fwd(q3, k3, c2, s2):
    return _rope3(q3, k3, c2, s2, neg_sin=False), (q3, k3, c2, s2)


def _cos_sin_cotangent(g, x, half):
    """d/dcos, d/dsin of `o1 = x1·c1 − x2·sA, o2 = x2·c2 + x1·sB` for
    one operand, summed over the head axis: dc = [Σ g1⊙x1, Σ g2⊙x2],
    ds = [−Σ g1⊙x2, Σ g2⊙x1]."""
    gf = g.astype(jnp.float32)
    xf = x.astype(jnp.float32)
    g1, g2 = gf[..., :half], gf[..., half:]
    x1, x2 = xf[..., :half], xf[..., half:]
    dc = jnp.concatenate([jnp.sum(g1 * x1, axis=1),
                          jnp.sum(g2 * x2, axis=1)], axis=-1)
    ds = jnp.concatenate([-jnp.sum(g1 * x2, axis=1),
                          jnp.sum(g2 * x1, axis=1)], axis=-1)
    return dc, ds


def _rope_bwd(res, g):
    q3, k3, c2, s2 = res
    gq, gk = g
    half = s2.shape[-1] // 2
    # true adjoint: dx1 needs s2's SECOND half, dx2 its first — swap
    # the halves before the neg_sin kernel (see module docstring)
    s_sw = jnp.concatenate([s2[:, half:], s2[:, :half]], axis=-1)
    dq, dk = _rope3(gq, gk, c2, s_sw, neg_sin=True)
    # cos/sin cotangents in plain jnp (elementwise+reduce — XLA fuses;
    # DCE prunes this AND the q3/k3 residual save when nothing
    # differentiates the cache, restoring the residual-light backward).
    # The XLA fallback path differentiates cos/sin, so the kernel must
    # too — zeros would silently freeze a learned cache on TPU only.
    dcq, dsq = _cos_sin_cotangent(gq, q3, half)
    dck, dsk = _cos_sin_cotangent(gk, k3, half)
    return dq, dk, (dcq + dck).astype(c2.dtype), \
        (dsq + dsk).astype(s2.dtype)


_rope_core.defvjp(_rope_fwd, _rope_bwd)


def rope_apply(q, k, cos, sin):
    """Pallas twin of ops.apply_rope: q [b, s, h, d], k [b, s, hk, d],
    cos/sin [s, d] or [b, s, d].  Raises ValueError for shapes the
    tiling cannot serve (caller falls back to the XLA path)."""
    b, s, h, d = q.shape
    hk = k.shape[2]
    if d % 2 or d < 2:
        raise ValueError("rope kernel needs an even head_dim")
    if k.shape[:2] != (b, s) or k.shape[3] != d:
        raise ValueError("q/k shape mismatch for the rope kernel")
    if cos.ndim == 2:
        c2 = jnp.broadcast_to(cos[None], (b, s, d)).reshape(b * s, d)
        s2 = jnp.broadcast_to(sin[None], (b, s, d)).reshape(b * s, d)
    elif cos.ndim == 3 and cos.shape == (b, s, d):
        c2 = cos.reshape(b * s, d)
        s2 = sin.reshape(b * s, d)
    else:
        raise ValueError(f"unsupported cos/sin shape {cos.shape}")
    oq, ok = _rope_core(q.reshape(b * s, h, d), k.reshape(b * s, hk, d),
                        c2, s2)
    return oq.reshape(q.shape), ok.reshape(k.shape)
