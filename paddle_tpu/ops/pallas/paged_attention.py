"""Paged-attention decode kernel — gather-by-page-table inside the
kernel (ISSUE 7 tentpole).

Reference design point: vLLM's PagedAttention, adapted to a statically
shaped XLA program the way TPU serving stacks do it: the page table is
a SCALAR-PREFETCH operand (pltpu.PrefetchScalarGridSpec), so the K/V
BlockSpec index maps read `page_table[b, j]` to pick WHICH physical
page the next grid step DMAs — the gather happens in the DMA engine,
and the [B, S_max] logical KV view is never materialized in HBM
(the jnp twin in paddle_tpu.ops does exactly that materializing
`take`-based gather, bit-matching this kernel's math off-TPU).

Layout contract (paddle_tpu.models.llama.init_paged_cache):

  k_pool/v_pool  [num_pages, page_size, layers, n_kv, head_dim]
  k/v scales     [num_pages, layers, n_kv] fp32  (int8 pools only)
  page_table     [B, pages_per_slot] int32; entry 0 is the reserved
                 null page (reads masked by position)
  pos            [B] int32 — per-slot write depth; query lane c of
                 slot b attends rows <= pos[b] + c

Grid: (B, n_kv, pages_per_slot) — the page walk is the innermost
(sequential) dimension, accumulating an online softmax per (slot,
kv head) in VMEM scratch, flash-attention style.  Pages past a slot's
frontier clamp their index map to the last useful page — Mosaic elides
the repeated-block DMA, so dead pages cost neither bandwidth nor
(via pl.when) compute.  int8 dequant is fused: the page's per-head
scale rides a (1,1,1) VMEM block and multiplies the tile right after
the DMA, so the HBM read stays 1 byte/element.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from ._x64 import x64_off
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

INTERPRET = None


def _interpret():
    global INTERPRET
    if INTERPRET is None:
        INTERPRET = jax.default_backend() != "tpu"
    return INTERPRET


def _kernel(pt_ref, pos_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
            o_ref, acc_ref, m_ref, l_ref, *, scale, page_size, group,
            q_len, quant):
    b = pl.program_id(0)
    j = pl.program_id(2)
    nj = pl.num_programs(2)
    ps = page_size
    pos = pos_ref[b]

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # pages whose first row is past the slot's last query frontier
    # contribute nothing — their DMA was already elided by the clamped
    # index map; skip the compute too
    @pl.when(j * ps <= pos + (q_len - 1))
    def _page():
        # q rows are pre-arranged [C*group, d] by the wrapper (row =
        # c*group + g) — no in-kernel reshape across sublanes
        q = q_ref[0, 0]
        k = k_ref[0, :, 0, 0, :]                          # [ps, d]
        v = v_ref[0, :, 0, 0, :]
        if quant:
            k = k.astype(jnp.float32) * ks_ref[0, 0, 0]
            v = v.astype(jnp.float32) * vs_ref[0, 0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * jnp.float32(scale)
        # query row r = c*group + g sits at global position pos + c;
        # key column r' sits at global position j*ps + r'
        qpos = pos + jax.lax.broadcasted_iota(
            jnp.int32, (q_len * group, ps), 0) // jnp.int32(group)
        kpos = j * jnp.int32(ps) + jax.lax.broadcasted_iota(
            jnp.int32, (q_len * group, ps), 1)
        s = jnp.where(kpos <= qpos, s, jnp.float32(NEG_INF))
        m_prev = m_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:, 0] = l_ref[:, 0] * alpha + jnp.sum(p, axis=1)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + \
            jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        m_ref[:, 0] = m_new

    @pl.when(j == nj - 1)
    def _done():
        l = jnp.maximum(l_ref[:, 0], jnp.float32(1e-30))
        o_ref[0, 0] = (acc_ref[...] / l[:, None]).astype(o_ref.dtype)


def paged_attention(q, k_pool, v_pool, page_table, pos, layer,
                    k_scale=None, v_scale=None, scale=None,
                    interpret=None):
    """q: [B, C, h, d]; pools [P, ps, L, n_kv, d]; page_table
    [B, P_slot] int32; pos [B] int32.  Returns [B, C, h, d] in
    q.dtype.  Raises ValueError for shapes the TPU tiling cannot
    serve — callers (ops.paged_attention) fall back to the jnp twin."""
    interp = _interpret() if interpret is None else interpret
    B, C, h, d = q.shape
    P, ps, L, n_kv, _ = k_pool.shape
    P_slot = page_table.shape[1]
    group = h // n_kv
    if h % n_kv:
        raise ValueError(f"q heads {h} not a multiple of kv heads "
                         f"{n_kv}")
    if not interp and (d % 128 or ps % 8):
        raise ValueError(
            f"paged_attention tiling needs head_dim % 128 == 0 and "
            f"page_size % 8 == 0 (got d={d}, page_size={ps})")
    quant = k_pool.dtype == jnp.int8
    if quant and (k_scale is None or v_scale is None):
        raise ValueError("int8 KV pool needs k_scale/v_scale")
    if not quant:
        # dummy (1,1,1)-blocked operand keeps ONE kernel signature;
        # never read when quant=False
        k_scale = jnp.ones((P, L, n_kv), jnp.float32)
        v_scale = k_scale
    s = scale if scale is not None else 1.0 / (d ** 0.5)

    pt = jnp.asarray(page_table, jnp.int32)
    posv = jnp.asarray(pos, jnp.int32)
    if posv.ndim == 0:
        posv = jnp.broadcast_to(posv, (B,))

    def page_ix(b, kvh, j, pt_ref, pos_ref):
        # clamp the walk to the slot's frontier page: repeated block
        # index => Mosaic elides the DMA for dead pages
        last = jnp.maximum(pos_ref[b] + (C - 1), 0) // ps
        return (pt_ref[b, jnp.minimum(j, last)], 0, layer, kvh, 0)

    def scale_ix(b, kvh, j, pt_ref, pos_ref):
        last = jnp.maximum(pos_ref[b] + (C - 1), 0) // ps
        return (pt_ref[b, jnp.minimum(j, last)], layer, kvh)

    # pre-arrange q per kv head with rows row = c*group + g — the
    # kernel then reads a ready [C*group, d] tile (an in-kernel
    # sublane reshape would be a Mosaic relayout)
    qr = q.reshape(B, C, n_kv, group, d).transpose(0, 2, 1, 3, 4) \
        .reshape(B, n_kv, C * group, d)
    grid = (B, n_kv, P_slot)
    kern = functools.partial(_kernel, scale=s, page_size=ps,
                             group=group, q_len=C, quant=quant)
    with x64_off():
        out = pl.pallas_call(
            kern,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=grid,
                in_specs=[
                    pl.BlockSpec((1, 1, C * group, d),
                                 lambda b, kvh, j, pt, pos:
                                 (b, kvh, 0, 0)),
                    pl.BlockSpec((1, ps, 1, 1, d), page_ix),
                    pl.BlockSpec((1, ps, 1, 1, d), page_ix),
                    pl.BlockSpec((1, 1, 1), scale_ix),
                    pl.BlockSpec((1, 1, 1), scale_ix),
                ],
                out_specs=pl.BlockSpec((1, 1, C * group, d),
                                       lambda b, kvh, j, pt, pos:
                                       (b, kvh, 0, 0)),
                scratch_shapes=[
                    pltpu.VMEM((C * group, d), jnp.float32),
                    pltpu.VMEM((C * group, 1), jnp.float32),
                    pltpu.VMEM((C * group, 1), jnp.float32),
                ],
            ),
            out_shape=jax.ShapeDtypeStruct((B, n_kv, C * group, d),
                                           q.dtype),
            interpret=interp,
        )(pt, posv, qr, k_pool, v_pool, k_scale, v_scale)
    return out.reshape(B, n_kv, C, group, d).transpose(0, 2, 1, 3, 4) \
        .reshape(B, C, h, d)
