"""Fused RMSNorm — Pallas TPU kernel with custom VJP.

Reference: `python/paddle/incubate/nn/functional/fused_rms_norm.py` → phi
fused CUDA kernel.  TPU-native: one VMEM pass per row block, fp32 stats;
backward recomputes the inverse rms (cheaper than saving it) and reduces
dw across row blocks with a fp32 accumulator output.

  y   = x * rsqrt(mean(x², -1) + eps) * w
  dx  = r*(g*w) - r³/H * x * Σ(g*w*x)      (r = rsqrt(mean x² + eps))
  dw  = Σ_rows g * x * r

`fused_add_rms_norm` extends the same kernel with the residual add that
always precedes the norm in pre-LN transformer blocks (PROFILE_r05: the
add and the norm are separate HBM round-trips at a fusion boundary):
one pass reads (x, y), writes the residual sum AND its norm — the sum
is never re-read.  The residual output is rounded to the storage dtype
BEFORE the statistics, so fused and unfused (`x + y` then `rms_norm`)
are bit-identical; the backward fuses the residual cotangent add into
the norm's dx kernel.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from ._x64 import x64_off
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK_ROWS = 256

INTERPRET = None


def _interpret():
    global INTERPRET
    if INTERPRET is None:
        INTERPRET = jax.default_backend() != "tpu"
    return INTERPRET


def _fwd_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    r = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True)
                      + jnp.float32(eps))
    o_ref[:] = (x * r * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _bwd_kernel(x_ref, w_ref, g_ref, dx_ref, dw_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    h = x.shape[-1]
    r = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True)
                      + jnp.float32(eps))
    gw = g * w
    dot = jnp.mean(gw * x, axis=-1, keepdims=True)
    dx = r * gw - (r * r * r) * x * dot
    dx_ref[:] = dx.astype(dx_ref.dtype)
    # per-block partial dw, reduced outside (grid dim 0 = row blocks)
    dw_ref[0, 0] = jnp.sum(g * x * r, axis=0)


def _pick_block_rows(rows, h):
    """Largest divisor of rows that is sublane-aligned (multiple of 8) and
    keeps the kernel's fp32 temporaries (~6 live [br, h] f32 buffers in
    the backward) inside scoped VMEM."""
    cap = min(BLOCK_ROWS, max(8, ((512 * 1024 // max(h, 1)) // 8) * 8))
    for br in range(min(cap, rows), 7, -1):
        if rows % br == 0 and br % 8 == 0:
            return br
    if rows <= cap:
        return rows
    raise ValueError(f"no tiling-compatible row block for {rows} rows")


def _rms2(x2, w, eps):
    rows, h = x2.shape
    br = _pick_block_rows(rows, h)
    grid = (rows // br,)
    with x64_off():
        out = pl.pallas_call(
            functools.partial(_fwd_kernel, eps=eps),
            grid=grid,
            in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                      pl.BlockSpec((h,), lambda i: (0,))],
            out_specs=pl.BlockSpec((br, h), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct(
                (rows, h), jnp.promote_types(x2.dtype, w.dtype)),
            interpret=_interpret(),
        )(x2, w)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rms_core(x2, w, eps):
    return _rms2(x2, w, eps)


def _rms_fwd(x2, w, eps):
    return _rms2(x2, w, eps), (x2, w)


def _rms_bwd(eps, res, g2):
    x2, w = res
    rows, h = x2.shape
    br = _pick_block_rows(rows, h)
    nblocks = rows // br
    with x64_off():
        dx, dw_part = pl.pallas_call(
            functools.partial(_bwd_kernel, eps=eps),
            grid=(nblocks,),
            in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                      pl.BlockSpec((h,), lambda i: (0,)),
                      pl.BlockSpec((br, h), lambda i: (i, 0))],
            out_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                       pl.BlockSpec((1, 1, h), lambda i: (i, 0, 0))],
            out_shape=[jax.ShapeDtypeStruct((rows, h), x2.dtype),
                       jax.ShapeDtypeStruct((nblocks, 1, h), jnp.float32)],
            interpret=_interpret(),
        )(x2, w, g2)
    dw = jnp.sum(dw_part, axis=(0, 1)).astype(w.dtype)
    return dx, dw


_rms_core.defvjp(_rms_fwd, _rms_bwd)


def rms_norm(x, weight, epsilon=1e-6):
    """x: [..., H]; weight: [H]."""
    shape = x.shape
    h = shape[-1]
    x2 = x.reshape(-1, h)
    out = _rms_core(x2, weight, float(epsilon))
    return out.reshape(shape)


# ---------------------------------------------------------------------------
# fused residual-add + RMSNorm

def _add_fwd_kernel(x_ref, y_ref, w_ref, r_ref, o_ref, *, eps):
    s = x_ref[:].astype(jnp.float32) + y_ref[:].astype(jnp.float32)
    # round to the residual storage dtype FIRST: the statistics then see
    # exactly what the unfused `x + y` produced → bit-identical paths
    s_low = s.astype(r_ref.dtype)
    r_ref[:] = s_low
    sf = s_low.astype(jnp.float32)
    r = jax.lax.rsqrt(jnp.mean(sf * sf, axis=-1, keepdims=True)
                      + jnp.float32(eps))
    o_ref[:] = (sf * r * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _add_bwd_kernel(x_ref, w_ref, g_ref, gr_ref, dx_ref, dw_ref, *, eps):
    """Norm backward over the saved residual + fused add of the residual
    cotangent (gr): d(resid) = rms_dx + gr, and dx == dy == d(resid)."""
    x = x_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    h = x.shape[-1]
    r = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True)
                      + jnp.float32(eps))
    gw = g * w
    dot = jnp.mean(gw * x, axis=-1, keepdims=True)
    dx = r * gw - (r * r * r) * x * dot + gr_ref[:].astype(jnp.float32)
    dx_ref[:] = dx.astype(dx_ref.dtype)
    dw_ref[0, 0] = jnp.sum(g * x * r, axis=0)


def _add_rms2(x2, y2, w, eps):
    rows, h = x2.shape
    br = _pick_block_rows(rows, h)
    res_dt = jnp.promote_types(x2.dtype, y2.dtype)
    with x64_off():
        resid, out = pl.pallas_call(
            functools.partial(_add_fwd_kernel, eps=eps),
            grid=(rows // br,),
            in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                      pl.BlockSpec((br, h), lambda i: (i, 0)),
                      pl.BlockSpec((h,), lambda i: (0,))],
            out_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                       pl.BlockSpec((br, h), lambda i: (i, 0))],
            out_shape=[jax.ShapeDtypeStruct((rows, h), res_dt),
                       jax.ShapeDtypeStruct(
                           (rows, h), jnp.promote_types(res_dt, w.dtype))],
            interpret=_interpret(),
        )(x2, y2, w)
    return resid, out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _add_rms_core(x2, y2, w, eps):
    return _add_rms2(x2, y2, w, eps)


def _add_rms_fwd(x2, y2, w, eps):
    resid, out = _add_rms2(x2, y2, w, eps)
    return (resid, out), (resid, w)


def _add_rms_bwd(eps, res, g):
    resid, w = res
    g_resid, g_out = g
    rows, h = resid.shape
    br = _pick_block_rows(rows, h)
    nblocks = rows // br
    with x64_off():
        dresid, dw_part = pl.pallas_call(
            functools.partial(_add_bwd_kernel, eps=eps),
            grid=(nblocks,),
            in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                      pl.BlockSpec((h,), lambda i: (0,)),
                      pl.BlockSpec((br, h), lambda i: (i, 0)),
                      pl.BlockSpec((br, h), lambda i: (i, 0))],
            out_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                       pl.BlockSpec((1, 1, h), lambda i: (i, 0, 0))],
            out_shape=[jax.ShapeDtypeStruct((rows, h), resid.dtype),
                       jax.ShapeDtypeStruct((nblocks, 1, h), jnp.float32)],
            interpret=_interpret(),
        )(resid, w, g_out, g_resid)
    dw = jnp.sum(dw_part, axis=(0, 1)).astype(w.dtype)
    return dresid, dresid, dw


_add_rms_core.defvjp(_add_rms_fwd, _add_rms_bwd)


def fused_add_rms_norm(x, y, weight, epsilon=1e-6):
    """(x + y, rms_norm(x + y) * weight) in one VMEM pass.
    x/y: [..., H]; weight: [H].  Returns (residual, normed), both shaped
    like x; the residual is in promote_types(x, y) — identical to the
    unfused `x + y`.  Mixed-dtype operands are cast to the common dtype
    outside the custom VJP (the cast's own autodiff restores each
    operand's gradient dtype)."""
    shape = x.shape
    h = shape[-1]
    if y.shape != shape:
        raise ValueError(f"residual shapes differ: {shape} vs {y.shape}")
    res_dt = jnp.promote_types(x.dtype, y.dtype)
    resid, out = _add_rms_core(x.reshape(-1, h).astype(res_dt),
                               y.reshape(-1, h).astype(res_dt),
                               weight, float(epsilon))
    return resid.reshape(shape), out.reshape(shape)
