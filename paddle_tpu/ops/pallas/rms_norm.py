"""Fused RMSNorm — Pallas TPU kernel with custom VJP.

Reference: `python/paddle/incubate/nn/functional/fused_rms_norm.py` → phi
fused CUDA kernel.  TPU-native: one VMEM pass per row block, fp32 stats;
backward recomputes the inverse rms (cheaper than saving it) and reduces
dw across row blocks with a fp32 accumulator output.

  y   = x * rsqrt(mean(x², -1) + eps) * w
  dx  = r*(g*w) - r³/H * x * Σ(g*w*x)      (r = rsqrt(mean x² + eps))
  dw  = Σ_rows g * x * r
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK_ROWS = 256

INTERPRET = None


def _interpret():
    global INTERPRET
    if INTERPRET is None:
        INTERPRET = jax.default_backend() != "tpu"
    return INTERPRET


def _fwd_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    r = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True)
                      + jnp.float32(eps))
    o_ref[:] = (x * r * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _bwd_kernel(x_ref, w_ref, g_ref, dx_ref, dw_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    h = x.shape[-1]
    r = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True)
                      + jnp.float32(eps))
    gw = g * w
    dot = jnp.mean(gw * x, axis=-1, keepdims=True)
    dx = r * gw - (r * r * r) * x * dot
    dx_ref[:] = dx.astype(dx_ref.dtype)
    # per-block partial dw, reduced outside (grid dim 0 = row blocks)
    dw_ref[0, 0] = jnp.sum(g * x * r, axis=0)


def _pick_block_rows(rows, h):
    """Largest divisor of rows that is sublane-aligned (multiple of 8) and
    keeps the kernel's fp32 temporaries (~6 live [br, h] f32 buffers in
    the backward) inside scoped VMEM."""
    cap = min(BLOCK_ROWS, max(8, ((512 * 1024 // max(h, 1)) // 8) * 8))
    for br in range(min(cap, rows), 7, -1):
        if rows % br == 0 and br % 8 == 0:
            return br
    if rows <= cap:
        return rows
    raise ValueError(f"no tiling-compatible row block for {rows} rows")


def _rms2(x2, w, eps):
    rows, h = x2.shape
    br = _pick_block_rows(rows, h)
    grid = (rows // br,)
    with jax.enable_x64(False):
        out = pl.pallas_call(
            functools.partial(_fwd_kernel, eps=eps),
            grid=grid,
            in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                      pl.BlockSpec((h,), lambda i: (0,))],
            out_specs=pl.BlockSpec((br, h), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct(
                (rows, h), jnp.promote_types(x2.dtype, w.dtype)),
            interpret=_interpret(),
        )(x2, w)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rms_core(x2, w, eps):
    return _rms2(x2, w, eps)


def _rms_fwd(x2, w, eps):
    return _rms2(x2, w, eps), (x2, w)


def _rms_bwd(eps, res, g2):
    x2, w = res
    rows, h = x2.shape
    br = _pick_block_rows(rows, h)
    nblocks = rows // br
    with jax.enable_x64(False):
        dx, dw_part = pl.pallas_call(
            functools.partial(_bwd_kernel, eps=eps),
            grid=(nblocks,),
            in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                      pl.BlockSpec((h,), lambda i: (0,)),
                      pl.BlockSpec((br, h), lambda i: (i, 0))],
            out_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                       pl.BlockSpec((1, 1, h), lambda i: (i, 0, 0))],
            out_shape=[jax.ShapeDtypeStruct((rows, h), x2.dtype),
                       jax.ShapeDtypeStruct((nblocks, 1, h), jnp.float32)],
            interpret=_interpret(),
        )(x2, w, g2)
    dw = jnp.sum(dw_part, axis=(0, 1)).astype(w.dtype)
    return dx, dw


_rms_core.defvjp(_rms_fwd, _rms_bwd)


def rms_norm(x, weight, epsilon=1e-6):
    """x: [..., H]; weight: [H]."""
    shape = x.shape
    h = shape[-1]
    x2 = x.reshape(-1, h)
    out = _rms_core(x2, weight, float(epsilon))
    return out.reshape(shape)
