"""paddle_tpu.ops — the hot-kernel layer.

Reference: `paddle/phi/kernels/fusion/gpu/` (fused_attention, fused_rms_norm,
fused_rope, flash_attn via external lib) — hand-written CUDA.

TPU-native: each op has an XLA reference implementation (jnp) and, where it
pays, a Pallas TPU kernel (paddle_tpu/ops/pallas/).  Dispatch picks Pallas on
TPU backends and XLA elsewhere; `set_attention_backend` forces a choice
(used by nn.functional.sdp_kernel).  All functions here take/return raw
jax.Arrays — the Tensor wrapper layer calls them through dispatch.run so
eager autograd and jit tracing both work.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["attention", "cached_attention", "rms_norm", "layer_norm",
           "fused_add_rms_norm", "xla_fused_add_rms_norm",
           "rope", "apply_rope",
           "swiglu", "get_attention_backend", "set_attention_backend",
           "gqa_scores", "gqa_weighted_v"]

_attention_backend = "auto"  # auto | pallas | xla


def get_attention_backend():
    return _attention_backend


def set_attention_backend(b):
    global _attention_backend
    _attention_backend = b


def _on_tpu(*arrays) -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# attention
# ---------------------------------------------------------------------------
def gqa_scores(q, k):
    """q·kᵀ logits [b, h, sq, sk] (fp32) for q [b, sq, h, d] against
    k [b, sk, hk, d] where hk may divide h (GQA/MQA) — WITHOUT
    materialising repeated KV: the group is folded into an extra q dim and
    the contraction batches over the kv head, so KV HBM traffic stays
    ∝ num_kv_heads."""
    b, sq, h, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    if hk == h:
        return jnp.einsum("bqhd,bkhd->bhqk", q, k,
                          preferred_element_type=jnp.float32)
    qg = q.reshape(b, sq, hk, h // hk, d)
    logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                        preferred_element_type=jnp.float32)
    return logits.reshape(b, h, sq, sk)


def gqa_weighted_v(w, v):
    """Σₖ w·v → [b, h, sq, d] for weights w [b, h, sq, sk] against
    v [b, sk, hk, d] with hk dividing h; GQA handled as in gqa_scores."""
    b, h, sq, sk = w.shape
    hk, d = v.shape[2], v.shape[3]
    if hk == h:
        return jnp.einsum("bhqk,bkhd->bhqd", w, v)
    wg = w.reshape(b, hk, h // hk, sq, sk)
    out = jnp.einsum("bhgqk,bkhd->bhgqd", wg, v)
    return out.reshape(b, h, sq, d)


def xla_attention(q, k, v, mask=None, causal=False, scale=None,
                  dropout_p=0.0):
    """Reference math of phi flash_attn kernel, XLA-fused.
    q/k/v: [b, s, h, d] (paddle flash-attn layout).  fp32 softmax."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    logits = gqa_scores(q, k) * s
    if causal:
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cm[None, None], logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(logits.dtype)
    w = jax.nn.softmax(logits, axis=-1)
    if dropout_p > 0.0:
        from ..framework.random import next_key
        keep = jax.random.bernoulli(next_key(), 1.0 - dropout_p, w.shape)
        w = w * keep / (1.0 - dropout_p)
    out = gqa_weighted_v(w.astype(v.dtype), v)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def cached_attention(q, k_cache, v_cache, q_pos0, scale=None):
    """Incremental-decode attention against a fixed-size KV ring buffer.

    q: [b, s_new, h, d] (queries for the tokens being appended);
    k_cache/v_cache: [b, S_max, h_kv, d] with positions < q_pos0 + s_new
    valid; q_pos0: int32 scalar — global position of q's first token —
    or a PER-SLOT [b] vector (continuous batching: each sequence sits
    at its own depth).  Query i of slot b attends cache slots
    j <= q_pos0[b] + i.

    The vector form with s_new > 1 is the CHUNKED-PREFILL contract
    (inference/serving.py): a mixed batch where some slots decode one
    token while others consume a multi-token prompt chunk shares this
    one call — each slot's causal frontier is its own pos[b]+lane.
    Lanes past a slot's valid count rely on the caller masking/
    overwriting their KV before any later query can attend them (the
    serving scan's pad-lane discipline).

    Reference: `python/paddle/incubate/nn/functional/
    block_multihead_attention.py` (paged-KV decode).  TPU-native
    design: a ring buffer with STATIC S_max (XLA needs static shapes)
    and one batched masked matmul — at q_len==1 a Pallas kernel would
    be per-instance-overhead-bound (the measured failure mode of small
    grids on v5e; see flash_attention._fwd_1b notes), while XLA lowers
    this to a single large batched GEMV at full HBM rate."""
    b, sq, h, d = q.shape
    sk = k_cache.shape[1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    logits = gqa_scores(q, k_cache) * s
    pos0 = jnp.asarray(q_pos0, jnp.int32)
    if pos0.ndim == 0:
        pos_q = pos0 + jnp.arange(sq, dtype=jnp.int32)[:, None]
        valid = jnp.arange(sk, dtype=jnp.int32)[None, :] <= pos_q
        logits = jnp.where(valid[None, None], logits, -1e30)
    else:
        # PER-SLOT positions ([b] vector): each sequence in the batch
        # sits at its own depth — the continuous-batching decode form
        pos_q = pos0[:, None] + jnp.arange(sq, dtype=jnp.int32)[None]
        valid = jnp.arange(sk, dtype=jnp.int32)[None, None, :] \
            <= pos_q[:, :, None]
        logits = jnp.where(valid[:, None], logits, -1e30)
    w = jax.nn.softmax(logits, axis=-1)
    out = gqa_weighted_v(w.astype(v_cache.dtype), v_cache)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def attention(q, k, v, mask=None, causal=False, scale=None, dropout_p=0.0):
    backend = _attention_backend
    if backend == "auto":
        backend = "pallas" if (_on_tpu() and mask is None
                               and dropout_p == 0.0) else "xla"
    if backend == "pallas" and mask is None and dropout_p == 0.0:
        from .pallas.flash_attention import flash_attention as _pfa
        try:
            return _pfa(q, k, v, causal=causal, scale=scale)
        except ValueError:
            pass  # unsupported shape → XLA path; real errors propagate
    return xla_attention(q, k, v, mask, causal, scale, dropout_p)


# ---------------------------------------------------------------------------
# rms_norm / layer_norm
# ---------------------------------------------------------------------------
def xla_rms_norm(x, weight=None, epsilon=1e-6):
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    out = xf * jax.lax.rsqrt(var + epsilon)
    out = out.astype(x.dtype)
    if weight is not None:
        out = out * weight
    return out


def rms_norm(x, weight=None, epsilon=1e-6):
    """Reference: incubate fused_rms_norm (phi fused kernel).  Pallas kernel
    on TPU for the [*, hidden] LLM case."""
    if _on_tpu() and weight is not None and x.ndim >= 2:
        from .pallas.rms_norm import rms_norm as _prn
        try:
            return _prn(x, weight, epsilon)
        except ValueError:
            pass  # tiling-incompatible shape → XLA path
    return xla_rms_norm(x, weight, epsilon)


def xla_fused_add_rms_norm(x, y, weight, epsilon=1e-6):
    """jnp twin of pallas.rms_norm.fused_add_rms_norm — the EXACT ops
    of the unfused path (add in the compute dtype, then xla_rms_norm),
    so threading the fused entry into a model changes nothing
    numerically off-TPU."""
    resid = x + y
    return resid, xla_rms_norm(resid, weight, epsilon)


def fused_add_rms_norm(x, y, weight, epsilon=1e-6):
    """Fused residual-add + RMSNorm: (x + y, rms_norm(x + y) * weight)
    in one Pallas VMEM pass on TPU (the residual sum is written once and
    never re-read — one fewer [tokens, H] HBM round-trip per transformer
    block, a PROFILE_r05 non-matmul gap item).  XLA twin elsewhere."""
    if _on_tpu() and weight is not None and x.ndim >= 2:
        from .pallas.rms_norm import fused_add_rms_norm as _parn
        try:
            return _parn(x, y, weight, epsilon)
        except ValueError:
            pass  # tiling-incompatible shape → XLA path
    return xla_fused_add_rms_norm(x, y, weight, epsilon)


def layer_norm(x, weight=None, bias=None, epsilon=1e-5):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    out = ((xf - mean) * jax.lax.rsqrt(var + epsilon)).astype(x.dtype)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


# ---------------------------------------------------------------------------
# rotary position embedding
# ---------------------------------------------------------------------------
def rope_cos_sin(seq_len, head_dim, base=10000.0, dtype=jnp.float32,
                 position_ids=None):
    inv_freq = 1.0 / (base ** (jnp.arange(0, head_dim, 2,
                                          dtype=jnp.float32) / head_dim))
    pos = (jnp.arange(seq_len, dtype=jnp.float32) if position_ids is None
           else position_ids.astype(jnp.float32))
    freqs = jnp.einsum("...s,d->...sd", pos, inv_freq)
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def _rotate_half(x):
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rope(q, k, cos, sin):
    """Reference: incubate fused_rotary_position_embedding (NeoX-style
    rotate-half, matching paddle's use_neox_rotary_style=True).
    q/k: [b, s, h, d]; cos/sin: [s, d] or [b, s, d].

    On TPU the q/k rotation runs as ONE Pallas pass per row block
    (pallas/rope.py — each operand read once, written once; the XLA
    path's concat/slice rotate-half shuffles are a PROFILE_r05
    non-matmul gap item); shapes its tiling cannot serve (e.g. the
    batch·seq < 8 decode case) fall back to XLA here."""
    if _on_tpu() and q.ndim == 4 and k.ndim == 4:
        from .pallas.rope import rope_apply as _prope
        try:
            return _prope(q, k, cos, sin)
        except ValueError:
            pass  # tiling-incompatible shape → XLA path
    if cos.ndim == 2:      # [s, d] → [1, s, 1, d]
        cos, sin = cos[None, :, None, :], sin[None, :, None, :]
    elif cos.ndim == 3:    # [b, s, d] → [b, s, 1, d]
        cos, sin = cos[:, :, None, :], sin[:, :, None, :]
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    cosf = cos.astype(jnp.float32)
    sinf = sin.astype(jnp.float32)
    q_out = (qf * cosf + _rotate_half(qf) * sinf).astype(q.dtype)
    k_out = (kf * cosf + _rotate_half(kf) * sinf).astype(k.dtype)
    return q_out, k_out


def rope(q, k, seq_len=None, base=10000.0, position_ids=None):
    sl = seq_len if seq_len is not None else q.shape[1]
    cos, sin = rope_cos_sin(sl, q.shape[-1], base,
                            position_ids=position_ids)
    return apply_rope(q, k, cos, sin)


# ---------------------------------------------------------------------------
# swiglu
# ---------------------------------------------------------------------------
def swiglu(x, gate=None):
    if gate is None:
        half = x.shape[-1] // 2
        x, gate = x[..., :half], x[..., half:]
    return jax.nn.silu(x) * gate
