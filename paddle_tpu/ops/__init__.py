"""paddle_tpu.ops — the hot-kernel layer.

Reference: `paddle/phi/kernels/fusion/gpu/` (fused_attention, fused_rms_norm,
fused_rope, flash_attn via external lib) — hand-written CUDA.

TPU-native: each op has an XLA reference implementation (jnp) and, where it
pays, a Pallas TPU kernel (paddle_tpu/ops/pallas/).  Dispatch picks Pallas on
TPU backends and XLA elsewhere; `set_attention_backend` forces a choice
(used by nn.functional.sdp_kernel).  All functions here take/return raw
jax.Arrays — the Tensor wrapper layer calls them through dispatch.run so
eager autograd and jit tracing both work.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["attention", "cached_attention", "rms_norm", "layer_norm",
           "fused_add_rms_norm", "xla_fused_add_rms_norm",
           "rope", "apply_rope",
           "paged_attention", "xla_paged_attention", "paged_kv_update",
           "swiglu", "get_attention_backend", "set_attention_backend",
           "gqa_scores", "gqa_weighted_v",
           "quant_matmul", "xla_quant_matmul",
           "pack_int4", "unpack_int4", "dequant_weight"]

_attention_backend = "auto"  # auto | pallas | xla


def get_attention_backend():
    return _attention_backend


def set_attention_backend(b):
    global _attention_backend
    _attention_backend = b


def _on_tpu(*arrays) -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# attention
# ---------------------------------------------------------------------------
def gqa_scores(q, k):
    """q·kᵀ logits [b, h, sq, sk] (fp32) for q [b, sq, h, d] against
    k [b, sk, hk, d] where hk may divide h (GQA/MQA) — WITHOUT
    materialising repeated KV: the group is folded into an extra q dim and
    the contraction batches over the kv head, so KV HBM traffic stays
    ∝ num_kv_heads."""
    b, sq, h, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    if hk == h:
        return jnp.einsum("bqhd,bkhd->bhqk", q, k,
                          preferred_element_type=jnp.float32)
    qg = q.reshape(b, sq, hk, h // hk, d)
    logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                        preferred_element_type=jnp.float32)
    return logits.reshape(b, h, sq, sk)


def gqa_weighted_v(w, v):
    """Σₖ w·v → [b, h, sq, d] for weights w [b, h, sq, sk] against
    v [b, sk, hk, d] with hk dividing h; GQA handled as in gqa_scores."""
    b, h, sq, sk = w.shape
    hk, d = v.shape[2], v.shape[3]
    if hk == h:
        return jnp.einsum("bhqk,bkhd->bhqd", w, v)
    wg = w.reshape(b, hk, h // hk, sq, sk)
    out = jnp.einsum("bhgqk,bkhd->bhgqd", wg, v)
    return out.reshape(b, h, sq, d)


def xla_attention(q, k, v, mask=None, causal=False, scale=None,
                  dropout_p=0.0):
    """Reference math of phi flash_attn kernel, XLA-fused.
    q/k/v: [b, s, h, d] (paddle flash-attn layout).  fp32 softmax."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    logits = gqa_scores(q, k) * s
    if causal:
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cm[None, None], logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(logits.dtype)
    w = jax.nn.softmax(logits, axis=-1)
    if dropout_p > 0.0:
        from ..framework.random import next_key
        keep = jax.random.bernoulli(next_key(), 1.0 - dropout_p, w.shape)
        w = w * keep / (1.0 - dropout_p)
    out = gqa_weighted_v(w.astype(v.dtype), v)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def cached_attention(q, k_cache, v_cache, q_pos0, scale=None):
    """Incremental-decode attention against a fixed-size KV ring buffer.

    q: [b, s_new, h, d] (queries for the tokens being appended);
    k_cache/v_cache: [b, S_max, h_kv, d] with positions < q_pos0 + s_new
    valid; q_pos0: int32 scalar — global position of q's first token —
    or a PER-SLOT [b] vector (continuous batching: each sequence sits
    at its own depth).  Query i of slot b attends cache slots
    j <= q_pos0[b] + i.

    The vector form with s_new > 1 is the CHUNKED-PREFILL contract
    (inference/serving.py): a mixed batch where some slots decode one
    token while others consume a multi-token prompt chunk shares this
    one call — each slot's causal frontier is its own pos[b]+lane.
    Lanes past a slot's valid count rely on the caller masking/
    overwriting their KV before any later query can attend them (the
    serving scan's pad-lane discipline).

    Reference: `python/paddle/incubate/nn/functional/
    block_multihead_attention.py` (paged-KV decode).  TPU-native
    design: a ring buffer with STATIC S_max (XLA needs static shapes)
    and one batched masked matmul — at q_len==1 a Pallas kernel would
    be per-instance-overhead-bound (the measured failure mode of small
    grids on v5e; see flash_attention._fwd_1b notes), while XLA lowers
    this to a single large batched GEMV at full HBM rate."""
    b, sq, h, d = q.shape
    sk = k_cache.shape[1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    logits = gqa_scores(q, k_cache) * s
    pos0 = jnp.asarray(q_pos0, jnp.int32)
    if pos0.ndim == 0:
        pos_q = pos0 + jnp.arange(sq, dtype=jnp.int32)[:, None]
        valid = jnp.arange(sk, dtype=jnp.int32)[None, :] <= pos_q
        logits = jnp.where(valid[None, None], logits, -1e30)
    else:
        # PER-SLOT positions ([b] vector): each sequence in the batch
        # sits at its own depth — the continuous-batching decode form
        pos_q = pos0[:, None] + jnp.arange(sq, dtype=jnp.int32)[None]
        valid = jnp.arange(sk, dtype=jnp.int32)[None, None, :] \
            <= pos_q[:, :, None]
        logits = jnp.where(valid[:, None], logits, -1e30)
    w = jax.nn.softmax(logits, axis=-1)
    out = gqa_weighted_v(w.astype(v_cache.dtype), v_cache)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


# ---------------------------------------------------------------------------
# paged KV (ISSUE 7): fixed-size page pool + per-slot page table
# ---------------------------------------------------------------------------
def _dequant_pages(pages, scales):
    """pages [..., ps, n_kv, hd] int8 × per-page per-head scales
    [..., n_kv] → fp32."""
    return pages.astype(jnp.float32) * scales[..., None, :, None]


def paged_kv_update(k_pool, v_pool, k_scale, v_scale, page_table, pos,
                    k_new, v_new, layer):
    """Write one step's K/V rows into the paged pool (the paged twin of
    the dense path's per-slot dynamic_update_slice).

    k_pool/v_pool: [P, ps, L, n_kv, hd] (int8 pools carry per-page
    per-head scales [P, L, n_kv] fp32; None otherwise); page_table
    [B, P_slot] int32 (entry 0 = reserved null page); pos [B] int32;
    k_new/v_new [B, C, n_kv, hd] in the compute dtype; layer: python
    int.  Returns (k_pool, v_pool, k_scale, v_scale).

    Only the WINDOW of pages overlapping rows [pos, pos+C) is gathered,
    row-updated (contiguous DUS — bit-identical rows to the dense
    cache write) and scattered back; untouched window pages scatter
    their ORIGINAL bytes, so shared/read-only pages are never
    re-encoded (int8 requant drift stays confined to pages actually
    being written).  int8 pages requantize against the page's new
    running amax, so a page's scale is always consistent with every
    row it holds."""
    P, ps, L, n_kv, hd = k_pool.shape
    B, C = k_new.shape[0], k_new.shape[1]
    P_slot = page_table.shape[1]
    n_t = -(-C // ps) + 1          # pages a C-row write can straddle
    quant = k_pool.dtype == jnp.int8
    pos = jnp.asarray(pos, jnp.int32)
    p0 = jnp.clip(pos // ps, 0, max(P_slot - n_t, 0))
    win = jnp.clip(p0[:, None] + jnp.arange(n_t, dtype=jnp.int32)[None],
                   0, P_slot - 1)                            # [B, n_t]
    ids = jnp.take_along_axis(page_table, win, axis=1)       # [B, n_t]
    rel0 = pos - p0 * ps
    start = win * ps                # window pages' first logical row
    touched = (start < (pos + C)[:, None]) \
        & ((start + ps) > pos[:, None])                      # [B, n_t]

    def upd(pool, scales, rows):
        layer_pool = pool[:, :, layer]                # [P, ps, n_kv, hd]
        raw = jnp.take(layer_pool, ids, axis=0)       # [B, n_t, ps, ...]
        if quant:
            sc = jnp.take(scales[:, layer], ids, axis=0)  # [B, n_t, n_kv]
            w = _dequant_pages(raw, sc).astype(rows.dtype)
        else:
            w = raw
        w = w.reshape(B, n_t * ps, n_kv, hd)

        def dus(buf, r, r0):
            return jax.lax.dynamic_update_slice(
                buf, r.astype(buf.dtype),
                (r0, jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32)))
        w = jax.vmap(dus)(w, rows, rel0)
        w = w.reshape(B, n_t, ps, n_kv, hd)
        if quant:
            amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=(2, 4))
            sc_new = jnp.maximum(amax, 1e-8) / 127.0      # [B, n_t, n_kv]
            q8 = jnp.clip(jnp.round(
                w.astype(jnp.float32) / sc_new[:, :, None, :, None]),
                -127, 127).astype(jnp.int8)
            m = touched[:, :, None, None, None]
            pages_out = jnp.where(m, q8, raw)
            sc_out = jnp.where(touched[..., None], sc_new, sc)
            sl = scales[:, layer].at[ids].set(sc_out)
            scales = scales.at[:, layer].set(sl)
        else:
            m = touched[:, :, None, None, None]
            pages_out = jnp.where(m, w.astype(pool.dtype), raw)
        layer_pool = layer_pool.at[ids].set(pages_out)
        return pool.at[:, :, layer].set(layer_pool), scales

    k_pool, k_scale = upd(k_pool, k_scale, k_new)
    v_pool, v_scale = upd(v_pool, v_scale, v_new)
    return k_pool, v_pool, k_scale, v_scale


def _check_paged_args(q, k_pool, k_scale, v_scale):
    """Shared argument validation for both paged-attention paths —
    raised HERE so a bad call fails identically on and off TPU (the
    kernel's tiling ValueError is the only fallback trigger)."""
    n_kv = k_pool.shape[3]
    if q.shape[2] % n_kv:
        raise ValueError(f"q heads {q.shape[2]} not a multiple of kv "
                         f"heads {n_kv}")
    if k_pool.dtype == jnp.int8 and (k_scale is None or v_scale is None):
        raise ValueError("int8 KV pool needs k_scale/v_scale")


def xla_paged_attention(q, k_pool, v_pool, page_table, pos, layer,
                        k_scale=None, v_scale=None, scale=None):
    """jnp twin of pallas.paged_attention: materialize each slot's
    logical KV view with a `take`-based gather over the page table,
    dequant (int8 pools), then EXACTLY the dense cached_attention math
    — masked rows exp to 0.0 exactly, so the padded logical depth
    (P_slot*ps vs the dense cache_len) cannot perturb the softmax and
    the paged path stays bit-identical to the dense one off-TPU."""
    _check_paged_args(q, k_pool, k_scale, v_scale)
    B = q.shape[0]
    P, ps, L, n_kv, hd = k_pool.shape
    P_slot = page_table.shape[1]
    quant = k_pool.dtype == jnp.int8

    def gather(pool, scales):
        lg = jnp.take(pool[:, :, layer], page_table, axis=0)
        if quant:
            sc = jnp.take(scales[:, layer], page_table, axis=0)
            lg = _dequant_pages(lg, sc).astype(q.dtype)
        return lg.reshape(B, P_slot * ps, n_kv, hd)

    return cached_attention(q, gather(k_pool, k_scale),
                            gather(v_pool, v_scale), pos, scale)


def paged_attention(q, k_pool, v_pool, page_table, pos, layer,
                    k_scale=None, v_scale=None, scale=None):
    """Decode attention against the paged KV pool: Pallas kernel on TPU
    (gather-by-page-table in the DMA index map, int8 dequant fused —
    see ops/pallas/paged_attention.py), `take`-gather twin elsewhere.
    Capability-gated like ops.attention: tiling-incompatible shapes
    fall back to the twin (argument errors are validated FIRST, so the
    fallback can never swallow them)."""
    _check_paged_args(q, k_pool, k_scale, v_scale)
    if _on_tpu():
        from .pallas.paged_attention import paged_attention as _ppa
        try:
            return _ppa(q, k_pool, v_pool, page_table, pos, layer,
                        k_scale, v_scale, scale)
        except ValueError:
            pass  # unsupported tiling → twin; real errors propagate
    return xla_paged_attention(q, k_pool, v_pool, page_table, pos,
                               layer, k_scale, v_scale, scale)


def attention(q, k, v, mask=None, causal=False, scale=None, dropout_p=0.0):
    backend = _attention_backend
    if backend == "auto":
        backend = "pallas" if (_on_tpu() and mask is None
                               and dropout_p == 0.0) else "xla"
    if backend == "pallas" and mask is None and dropout_p == 0.0:
        from .pallas.flash_attention import flash_attention as _pfa
        try:
            return _pfa(q, k, v, causal=causal, scale=scale)
        except ValueError:
            pass  # unsupported shape → XLA path; real errors propagate
    return xla_attention(q, k, v, mask, causal, scale, dropout_p)


# ---------------------------------------------------------------------------
# rms_norm / layer_norm
# ---------------------------------------------------------------------------
def xla_rms_norm(x, weight=None, epsilon=1e-6):
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    out = xf * jax.lax.rsqrt(var + epsilon)
    out = out.astype(x.dtype)
    if weight is not None:
        out = out * weight
    return out


def rms_norm(x, weight=None, epsilon=1e-6):
    """Reference: incubate fused_rms_norm (phi fused kernel).  Pallas kernel
    on TPU for the [*, hidden] LLM case."""
    if _on_tpu() and weight is not None and x.ndim >= 2:
        from .pallas.rms_norm import rms_norm as _prn
        try:
            return _prn(x, weight, epsilon)
        except ValueError:
            pass  # tiling-incompatible shape → XLA path
    return xla_rms_norm(x, weight, epsilon)


def xla_fused_add_rms_norm(x, y, weight, epsilon=1e-6):
    """jnp twin of pallas.rms_norm.fused_add_rms_norm — the EXACT ops
    of the unfused path (add in the compute dtype, then xla_rms_norm),
    so threading the fused entry into a model changes nothing
    numerically off-TPU."""
    resid = x + y
    return resid, xla_rms_norm(resid, weight, epsilon)


def fused_add_rms_norm(x, y, weight, epsilon=1e-6):
    """Fused residual-add + RMSNorm: (x + y, rms_norm(x + y) * weight)
    in one Pallas VMEM pass on TPU (the residual sum is written once and
    never re-read — one fewer [tokens, H] HBM round-trip per transformer
    block, a PROFILE_r05 non-matmul gap item).  XLA twin elsewhere."""
    if _on_tpu() and weight is not None and x.ndim >= 2:
        from .pallas.rms_norm import fused_add_rms_norm as _parn
        try:
            return _parn(x, y, weight, epsilon)
        except ValueError:
            pass  # tiling-incompatible shape → XLA path
    return xla_fused_add_rms_norm(x, y, weight, epsilon)


def layer_norm(x, weight=None, bias=None, epsilon=1e-5):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    out = ((xf - mean) * jax.lax.rsqrt(var + epsilon)).astype(x.dtype)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


# ---------------------------------------------------------------------------
# rotary position embedding
# ---------------------------------------------------------------------------
def rope_cos_sin(seq_len, head_dim, base=10000.0, dtype=jnp.float32,
                 position_ids=None):
    inv_freq = 1.0 / (base ** (jnp.arange(0, head_dim, 2,
                                          dtype=jnp.float32) / head_dim))
    pos = (jnp.arange(seq_len, dtype=jnp.float32) if position_ids is None
           else position_ids.astype(jnp.float32))
    freqs = jnp.einsum("...s,d->...sd", pos, inv_freq)
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def _rotate_half(x):
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rope(q, k, cos, sin):
    """Reference: incubate fused_rotary_position_embedding (NeoX-style
    rotate-half, matching paddle's use_neox_rotary_style=True).
    q/k: [b, s, h, d]; cos/sin: [s, d] or [b, s, d].

    On TPU the q/k rotation runs as ONE Pallas pass per row block
    (pallas/rope.py — each operand read once, written once; the XLA
    path's concat/slice rotate-half shuffles are a PROFILE_r05
    non-matmul gap item); shapes its tiling cannot serve (e.g. the
    batch·seq < 8 decode case) fall back to XLA here."""
    if _on_tpu() and q.ndim == 4 and k.ndim == 4:
        from .pallas.rope import rope_apply as _prope
        try:
            return _prope(q, k, cos, sin)
        except ValueError:
            pass  # tiling-incompatible shape → XLA path
    if cos.ndim == 2:      # [s, d] → [1, s, 1, d]
        cos, sin = cos[None, :, None, :], sin[None, :, None, :]
    elif cos.ndim == 3:    # [b, s, d] → [b, s, 1, d]
        cos, sin = cos[:, :, None, :], sin[:, :, None, :]
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    cosf = cos.astype(jnp.float32)
    sinf = sin.astype(jnp.float32)
    q_out = (qf * cosf + _rotate_half(qf) * sinf).astype(q.dtype)
    k_out = (kf * cosf + _rotate_half(kf) * sinf).astype(k.dtype)
    return q_out, k_out


def rope(q, k, seq_len=None, base=10000.0, position_ids=None):
    sl = seq_len if seq_len is not None else q.shape[1]
    cos, sin = rope_cos_sin(sl, q.shape[-1], base,
                            position_ids=position_ids)
    return apply_rope(q, k, cos, sin)


# ---------------------------------------------------------------------------
# weight-only quantized matmul (ISSUE 11): int8 per-channel / packed int4
# ---------------------------------------------------------------------------
# Packed-int4 layout contract (the ONE place it is defined; the Pallas
# kernel, the jnp twin and quantization.weight_only all follow it):
# a [K, N] weight is split into HALVES along K — rows 0..K/2-1 live in
# the LOW nibble of packed[K//2, N] int8, rows K/2..K-1 in the HIGH
# nibble.  Unpacking is therefore two nibble extractions and a
# concatenate (no sublane interleave — the kernel's halves feed two
# clean [K/2, N] tiles), and scale groups along K never straddle the
# half boundary (group_size must divide K/2).

def pack_int4(q):
    """Pack an int [K, N] array of int4 values (range [-8, 7]) into
    [K//2, N] int8 bytes: low nibble = row k, high nibble = row
    k + K//2 (the half-split layout above).  K must be even."""
    K = q.shape[0]
    if K % 2:
        raise ValueError(f"pack_int4 needs an even K (got {K})")
    qi = jnp.asarray(q, jnp.int32)
    lo = qi[: K // 2] & 15
    hi = qi[K // 2:] & 15
    return (lo | (hi << 4)).astype(jnp.int8)


def unpack_int4(packed):
    """Inverse of pack_int4: [K//2, N] int8 → [K, N] int32 in [-8, 7].
    Nibbles are two's-complement 4-bit values; sign-extension is the
    branch-free (x ^ 8) - 8 for the low nibble and an arithmetic shift
    for the high one — identical math in the Pallas kernel."""
    p = jnp.asarray(packed, jnp.int32)        # sign-extends the byte
    lo = ((p & 15) ^ 8) - 8
    hi = p >> 4                               # arithmetic: high nibble
    return jnp.concatenate([lo, hi], axis=0)


def dequant_weight(qw, scales, fmt, group_size=None):
    """fp32 [K, N] weight from its weight-only packed form.
    fmt='int8': qw [K, N] int8, scales [N] — per-output-channel.
    fmt='int4': qw [K//2, N] packed int8, scales [K//group, N] —
    group-wise along K (groups never straddle the pack halves).
    THE canonical dequant math — the twin and the kernel both compute
    q_f32 * scale_f32, so the two paths are bit-identical."""
    if fmt == "int8":
        return qw.astype(jnp.float32) * scales.astype(jnp.float32)[None]
    if fmt != "int4":
        raise ValueError(f"unknown weight-only format {fmt!r}")
    if group_size is None:
        raise ValueError("int4 dequant needs group_size")
    q = unpack_int4(qw).astype(jnp.float32)            # [K, N]
    s = jnp.repeat(scales.astype(jnp.float32), int(group_size), axis=0)
    return q * s


def xla_quant_matmul(x, qw, scales, fmt, group_size=None):
    """jnp twin of pallas.quant_matmul: dequantize to fp32, cast to the
    activation dtype (the decode matmuls run in the compute dtype, like
    the unquantized `x @ w.astype(x.dtype)` they replace), contract in
    fp32 accumulation.  Bit-identical to the kernel off-TPU."""
    w = dequant_weight(qw, scales, fmt, group_size).astype(x.dtype)
    lead = x.shape[:-1]
    x2 = x.reshape(-1, x.shape[-1])
    out = jax.lax.dot_general(
        x2, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(x.dtype)
    return out.reshape(*lead, w.shape[1])


def quant_matmul(x, qw, scales, fmt, group_size=None):
    """x [..., K] @ weight-only packed qw → [..., N] in x.dtype, the
    dequant fused into the matmul (the weight is read from HBM at 1
    byte (int8) or half a byte (int4) per element — the decode-path
    bandwidth multiplier).  Pallas kernel on TPU (dequant in VMEM right
    after the DMA), jnp twin elsewhere / for tiling-incompatible
    shapes."""
    if fmt not in ("int8", "int4"):
        raise ValueError(f"unknown weight-only format {fmt!r}")
    if fmt == "int4" and group_size is None:
        raise ValueError("int4 quant_matmul needs group_size")
    if _on_tpu():
        from .pallas.quant_matmul import quant_matmul as _pqm
        try:
            return _pqm(x, qw, scales, fmt, group_size)
        except ValueError:
            pass  # unsupported tiling → twin; real errors propagate
    return xla_quant_matmul(x, qw, scales, fmt, group_size)


# ---------------------------------------------------------------------------
# swiglu
# ---------------------------------------------------------------------------
def swiglu(x, gate=None):
    if gate is None:
        half = x.shape[-1] // 2
        x, gate = x[..., :half], x[..., half:]
    return jax.nn.silu(x) * gate
