"""Ring attention — context parallelism for long sequences.

The reference has NO ring/context-parallel attention (SURVEY §5.7 verified
absence); this exceeds it, as the build plan requires for the long-context
story.  Design follows the blockwise/ring attention pattern (Liu et al.)
expressed TPU-natively:

* the sequence is sharded over a mesh axis (default 'sep'); each device
  holds a q/k/v block [b, s/n, h, d];
* inside `shard_map`, K/V blocks rotate around the ring via
  `jax.lax.ppermute` (nearest-neighbor ICI hops) while each device
  accumulates its q-block's attention with an online-softmax
  (running max + sum) over the arriving blocks;
* causal masking uses global positions derived from `lax.axis_index`, so
  fully-masked (future) blocks contribute nothing — their compute is
  masked, not skipped (static schedule keeps XLA happy; skipping would be
  the load-imbalanced zigzag variant, a later optimization);
* the ring loop is a `lax.scan` wrapped in `jax.checkpoint`: reverse-mode
  AD replays the rotations instead of saving n KV copies, so activation
  memory stays O(local block).

Gradients come from jax AD through scan+ppermute (the transpose of a
rotation is the reverse rotation), which yields the standard ring-attention
backward comm pattern without a hand-written kernel.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import gqa_scores, gqa_weighted_v

__all__ = ["ring_attention", "ring_attention_local"]


def ring_attention_local(q, k, v, axis_name, causal=False, scale=None):
    """Per-device body; call inside shard_map. q/k/v: [b, s_loc, h, d]
    local blocks of a sequence sharded over `axis_name`."""
    b, s_loc, h, d = q.shape
    # GQA: kv stays at its own head count in the ring carry so each
    # ppermute moves only the original kv bytes; the group fold happens
    # per-step inside gqa_scores/gqa_weighted_v (compute, not comm)
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    sc = scale if scale is not None else 1.0 / (d ** 0.5)
    qf = q.astype(jnp.float32) * sc
    # rotate kv blocks "up" the ring: device i hands its block to i+1, so
    # at step t device i holds block (i - t) mod n
    perm = [(i, (i + 1) % n) for i in range(n)]
    q_pos = idx * s_loc + jnp.arange(s_loc)

    def body(carry, t):
        o, m, l, kc, vc = carry
        src = (idx - t) % n
        logits = gqa_scores(qf, kc.astype(jnp.float32))
        if causal:
            k_pos = src * s_loc + jnp.arange(s_loc)
            keep = (q_pos[:, None] >= k_pos[None, :])  # [sq, sk]
            logits = jnp.where(keep[None, None], logits, -jnp.inf)
            keep_f = keep[None, None].astype(jnp.float32)
        else:
            keep_f = jnp.ones((1, 1, s_loc, s_loc), jnp.float32)
        blk_max = jnp.max(logits, axis=-1)                 # [b,h,q]
        m_new = jnp.maximum(m, blk_max)
        # guard fully-masked rows: exp(-inf - -inf) would be nan
        safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(logits - safe_m[..., None]) * keep_f   # [b,h,q,k]
        corr = jnp.where(jnp.isneginf(m), 0.0,
                         jnp.exp(m - safe_m))
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + gqa_weighted_v(
            p, vc.astype(jnp.float32))
        k_nxt = jax.lax.ppermute(kc, axis_name, perm)
        v_nxt = jax.lax.ppermute(vc, axis_name, perm)
        return (o_new, m_new, l_new, k_nxt, v_nxt), None

    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    m0 = jnp.full((b, h, s_loc), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    (o, m, l, _, _), _ = jax.lax.scan(
        jax.checkpoint(body), (o0, m0, l0, k, v), jnp.arange(n))
    l = jnp.where(l == 0.0, 1.0, l)
    out = (o / l[..., None]).astype(q.dtype)
    return jnp.transpose(out, (0, 2, 1, 3))  # back to [b, s, h, d]


def ring_attention(q, k, v, mesh: Mesh, seq_axis: str = "sep",
                   causal: bool = False, scale=None):
    """Global entry: q/k/v [b, s, h, d] (sharded or shardable on
    `seq_axis` along dim 1); returns [b, s, h, d] sharded the same way."""
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    spec = P(None, seq_axis, None, None)
    body = functools.partial(ring_attention_local, axis_name=seq_axis,
                             causal=causal, scale=scale)
    try:
        fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    except TypeError:  # older shard_map API
        fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_rep=False)
    return fn(q, k, v)
