"""Op-registry extension: the ops.yaml long tail (round-4 audit close).

Reference: `paddle/phi/ops/yaml/ops.yaml` — each entry below names its
declaration.  Same single-source contract as registry.py: one OpSpec →
the paddle_tpu.* function, its `_C_ops` binding, and its generated
output+grad OpTests.  Selection driven by `tools/op_audit.py`'s `todo`
category (the genuinely missing, implementable ops).
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp


def build_extra(OpSpec, _n, _u, _rs, _seed_of):
    """Returns the extension OpSpec list.  Called from registry.py after
    OpSpec/helpers exist (avoids a circular import)."""

    def _ints(lo, hi, *shape, seed_key="i"):
        return _rs(_seed_of(seed_key, lo, hi, shape)).randint(
            lo, hi, shape).astype(np.int64)

    # -- vision ----------------------------------------------------------
    def affine_channel(x, scale, bias, data_format="NCHW"):
        if data_format == "NCHW":
            return x * scale[None, :, None, None] + bias[None, :, None, None]
        return x * scale + bias

    def affine_grid_j(theta, out_h, out_w, align_corners=True):
        n = theta.shape[0]
        xs = jnp.linspace(-1.0, 1.0, out_w)
        ys = jnp.linspace(-1.0, 1.0, out_h)
        if not align_corners:
            xs = xs * (out_w - 1) / out_w
            ys = ys * (out_h - 1) / out_h
        gx, gy = jnp.meshgrid(xs, ys)
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)        # [H, W, 3]
        grid = jnp.einsum("hwk,nck->nhwc", base, theta)  # [N, H, W, 2]
        return grid

    def affine_grid_np(theta, out_h, out_w, align_corners=True):
        xs = np.linspace(-1.0, 1.0, out_w)
        ys = np.linspace(-1.0, 1.0, out_h)
        if not align_corners:
            xs = xs * (out_w - 1) / out_w
            ys = ys * (out_h - 1) / out_h
        gx, gy = np.meshgrid(xs, ys)
        base = np.stack([gx, gy, np.ones_like(gx)], axis=-1)
        return np.einsum("hwk,nck->nhwc", base, theta).astype(np.float32)

    def _unnorm(coord, size, align_corners):
        if align_corners:
            return (coord + 1) * 0.5 * (size - 1)
        return ((coord + 1) * size - 1) * 0.5

    def grid_sample_j(x, grid, mode="bilinear", padding_mode="zeros",
                      align_corners=True):
        n, c, h, w = x.shape
        gx = _unnorm(grid[..., 0], w, align_corners)     # [N, Ho, Wo]
        gy = _unnorm(grid[..., 1], h, align_corners)

        def gather(ix, iy):
            inb = ((ix >= 0) & (ix < w) & (iy >= 0) & (iy < h))
            ixc = jnp.clip(ix, 0, w - 1)
            iyc = jnp.clip(iy, 0, h - 1)
            out = x[jnp.arange(n)[:, None, None], :, iyc, ixc]  # [N,Ho,Wo,C]
            return jnp.where(inb[..., None], out, 0.0)

        if mode == "nearest":
            out = gather(jnp.round(gx).astype(jnp.int32),
                         jnp.round(gy).astype(jnp.int32))
            return out.transpose(0, 3, 1, 2)
        x0 = jnp.floor(gx)
        y0 = jnp.floor(gy)
        wx = (gx - x0)[..., None]
        wy = (gy - y0)[..., None]
        x0i, y0i = x0.astype(jnp.int32), y0.astype(jnp.int32)
        out = (gather(x0i, y0i) * (1 - wx) * (1 - wy)
               + gather(x0i + 1, y0i) * wx * (1 - wy)
               + gather(x0i, y0i + 1) * (1 - wx) * wy
               + gather(x0i + 1, y0i + 1) * wx * wy)
        return out.transpose(0, 3, 1, 2)

    def grid_sample_np(x, grid, mode="bilinear", padding_mode="zeros",
                       align_corners=True):
        n, c, h, w = x.shape
        gx = _unnorm(grid[..., 0], w, align_corners)
        gy = _unnorm(grid[..., 1], h, align_corners)

        def gather(ix, iy):
            inb = ((ix >= 0) & (ix < w) & (iy >= 0) & (iy < h))
            ixc = np.clip(ix, 0, w - 1).astype(np.int64)
            iyc = np.clip(iy, 0, h - 1).astype(np.int64)
            out = x[np.arange(n)[:, None, None], :, iyc, ixc]
            return np.where(inb[..., None], out, 0.0)

        if mode == "nearest":
            return gather(np.round(gx).astype(np.int64),
                          np.round(gy).astype(np.int64)
                          ).transpose(0, 3, 1, 2).astype(np.float32)
        x0 = np.floor(gx)
        y0 = np.floor(gy)
        wx = (gx - x0)[..., None]
        wy = (gy - y0)[..., None]
        x0i, y0i = x0.astype(np.int64), y0.astype(np.int64)
        out = (gather(x0i, y0i) * (1 - wx) * (1 - wy)
               + gather(x0i + 1, y0i) * wx * (1 - wy)
               + gather(x0i, y0i + 1) * (1 - wx) * wy
               + gather(x0i + 1, y0i + 1) * wx * wy)
        return out.transpose(0, 3, 1, 2).astype(np.float32)

    def shuffle_channel(x, group=1):
        n, c, h, w = x.shape
        return x.reshape(n, group, c // group, h, w) \
                .swapaxes(1, 2).reshape(n, c, h, w)

    def temporal_shift_j(x, seg_num, shift_ratio=0.25,
                         data_format="NCHW"):
        nt, c, h, w = x.shape
        n = nt // seg_num
        v = x.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        back = jnp.concatenate(
            [v[:, 1:, :fold], jnp.zeros_like(v[:, :1, :fold])], axis=1)
        fwd = jnp.concatenate(
            [jnp.zeros_like(v[:, :1, fold:2 * fold]),
             v[:, :-1, fold:2 * fold]], axis=1)
        keep = v[:, :, 2 * fold:]
        return jnp.concatenate([back, fwd, keep], axis=2) \
                  .reshape(nt, c, h, w)

    def temporal_shift_np(x, seg_num, shift_ratio=0.25,
                          data_format="NCHW"):
        nt, c, h, w = x.shape
        n = nt // seg_num
        v = x.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        out = np.zeros_like(v)
        out[:, :-1, :fold] = v[:, 1:, :fold]
        out[:, 1:, fold:2 * fold] = v[:, :-1, fold:2 * fold]
        out[:, :, 2 * fold:] = v[:, :, 2 * fold:]
        return out.reshape(nt, c, h, w)

    # -- pooling ---------------------------------------------------------
    def _pool_patches(x, ksize, stride, pad):
        """[N, C, kh*kw, Ho, Wo] patch tensor (NCHW)."""
        n, c, h, w = x.shape
        kh, kw = ksize
        patches = jax.lax.conv_general_dilated_patches(
            x, (kh, kw), stride, [(pad, pad), (pad, pad)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        ho, wo = patches.shape[2], patches.shape[3]
        return patches.reshape(n, c, kh * kw, ho, wo), (h, w, ho, wo)

    def max_pool2d_with_index_j(x, kernel_size, stride=None, padding=0):
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        st = ks if stride is None else (
            (stride, stride) if isinstance(stride, int) else tuple(stride))
        p, (h, w, ho, wo) = _pool_patches(x, ks, st, padding)
        out = p.max(axis=2)
        within = p.argmax(axis=2)
        dh, dw = within // ks[1], within % ks[1]
        oy = jnp.arange(ho)[:, None] * st[0] - padding
        ox = jnp.arange(wo)[None, :] * st[1] - padding
        idx = (oy[None, None] + dh) * w + (ox[None, None] + dw)
        return out, idx.astype(jnp.int32)

    def max_pool2d_with_index_np(x, kernel_size, stride=None, padding=0):
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        st = ks if stride is None else (
            (stride, stride) if isinstance(stride, int) else tuple(stride))
        n, c, h, w = x.shape
        hp = h + 2 * padding
        wp = w + 2 * padding
        xp = np.full((n, c, hp, wp), -np.inf, np.float32)
        xp[:, :, padding:padding + h, padding:padding + w] = x
        ho = (hp - ks[0]) // st[0] + 1
        wo = (wp - ks[1]) // st[1] + 1
        out = np.zeros((n, c, ho, wo), np.float32)
        idx = np.zeros((n, c, ho, wo), np.int32)
        for i in range(ho):
            for j in range(wo):
                win = xp[:, :, i * st[0]:i * st[0] + ks[0],
                         j * st[1]:j * st[1] + ks[1]].reshape(n, c, -1)
                a = win.argmax(-1)
                out[:, :, i, j] = win.max(-1)
                dh, dw = a // ks[1], a % ks[1]
                idx[:, :, i, j] = ((i * st[0] - padding + dh) * w
                                   + (j * st[1] - padding + dw))
        return out, idx

    def unpool_j(x, indices, output_size):
        indices = indices.astype(jnp.int32)
        n, c, ho, wo = x.shape
        h, w = output_size
        flat = jnp.zeros((n, c, h * w), x.dtype)
        ni = jnp.arange(n)[:, None, None]
        ci = jnp.arange(c)[None, :, None]
        flat = flat.at[ni, ci, indices.reshape(n, c, -1)].set(
            x.reshape(n, c, -1))
        return flat.reshape(n, c, h, w)

    def unpool_np(x, indices, output_size):
        indices = indices.astype(np.int64)
        n, c, ho, wo = x.shape
        h, w = output_size
        flat = np.zeros((n, c, h * w), np.float32)
        for b in range(n):
            for ch in range(c):
                flat[b, ch, indices[b, ch].reshape(-1)] = \
                    x[b, ch].reshape(-1)
        return flat.reshape(n, c, h, w)

    def lp_pool2d_j(x, norm_type, kernel_size, stride=None):
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        st = ks if stride is None else (
            (stride, stride) if isinstance(stride, int) else tuple(stride))
        p, _ = _pool_patches(jnp.abs(x) ** norm_type, ks, st, 0)
        return p.sum(axis=2) ** (1.0 / norm_type)

    def lp_pool2d_np(x, norm_type, kernel_size, stride=None):
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        st = ks if stride is None else (
            (stride, stride) if isinstance(stride, int) else tuple(stride))
        n, c, h, w = x.shape
        ho = (h - ks[0]) // st[0] + 1
        wo = (w - ks[1]) // st[1] + 1
        out = np.zeros((n, c, ho, wo), np.float32)
        for i in range(ho):
            for j in range(wo):
                win = np.abs(x[:, :, i * st[0]:i * st[0] + ks[0],
                             j * st[1]:j * st[1] + ks[1]]) ** norm_type
                out[:, :, i, j] = win.sum((-1, -2)) ** (1.0 / norm_type)
        return out

    def _frac_bounds(n_in, n_out, u):
        """Fractional pooling region bounds (Graham 2014): row i covers
        [ceil(a*(i+u))-ceil(a*u), ceil(a*(i+1+u))-ceil(a*u))."""
        a = n_in / n_out
        base = math.ceil(a * u)
        return [(min(n_in - 1, math.ceil(a * (i + u)) - base),
                 max(1, math.ceil(a * (i + 1 + u)) - base))
                for i in range(n_out)]

    def fractional_max_pool2d_j(x, output_size, random_u=0.5):
        oh, ow = output_size
        hbs = _frac_bounds(x.shape[2], oh, random_u)
        wbs = _frac_bounds(x.shape[3], ow, random_u)
        rows = []
        for (h0, h1) in hbs:
            cols = [x[:, :, h0:max(h1, h0 + 1), w0:max(w1, w0 + 1)]
                    .max(axis=(2, 3)) for (w0, w1) in wbs]
            rows.append(jnp.stack(cols, axis=-1))
        return jnp.stack(rows, axis=-2)

    def fractional_max_pool2d_np(x, output_size, random_u=0.5):
        oh, ow = output_size
        hbs = _frac_bounds(x.shape[2], oh, random_u)
        wbs = _frac_bounds(x.shape[3], ow, random_u)
        out = np.zeros(x.shape[:2] + (oh, ow), np.float32)
        for i, (h0, h1) in enumerate(hbs):
            for j, (w0, w1) in enumerate(wbs):
                out[:, :, i, j] = x[:, :, h0:max(h1, h0 + 1),
                                    w0:max(w1, w0 + 1)].max((2, 3))
        return out

    # -- signal ----------------------------------------------------------
    def frame_j(x, frame_length, hop_length, axis=-1):
        n = x.shape[-1]
        nf = 1 + (n - frame_length) // hop_length
        idx = (jnp.arange(frame_length)[:, None]
               + hop_length * jnp.arange(nf)[None, :])
        return x[..., idx]                       # [..., frame_len, n_frames]

    def frame_np(x, frame_length, hop_length, axis=-1):
        n = x.shape[-1]
        nf = 1 + (n - frame_length) // hop_length
        idx = (np.arange(frame_length)[:, None]
               + hop_length * np.arange(nf)[None, :])
        return x[..., idx].astype(np.float32)

    def overlap_add_j(x, hop_length, axis=-1):
        fl, nf = x.shape[-2], x.shape[-1]
        n = fl + hop_length * (nf - 1)
        out = jnp.zeros(x.shape[:-2] + (n,), x.dtype)
        for f in range(nf):                       # nf is static
            out = out.at[..., f * hop_length:f * hop_length + fl].add(
                x[..., f])
        return out

    def overlap_add_np(x, hop_length, axis=-1):
        fl, nf = x.shape[-2], x.shape[-1]
        n = fl + hop_length * (nf - 1)
        out = np.zeros(x.shape[:-2] + (n,), np.float32)
        for f in range(nf):
            out[..., f * hop_length:f * hop_length + fl] += x[..., f]
        return out

    def stft_j(x, n_fft, hop_length=None, center=True,
               pad_mode="reflect", onesided=True):
        hop = hop_length or n_fft // 4
        if center:
            pad = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            x = jnp.pad(x, pad, mode=pad_mode)
        frames = frame_j(x, n_fft, hop)          # [..., n_fft, nf]
        win = jnp.hanning(n_fft + 1)[:-1].astype(x.dtype)
        spec = jnp.fft.rfft(frames * win[:, None], axis=-2) if onesided \
            else jnp.fft.fft(frames * win[:, None], axis=-2)
        return spec

    def stft_np(x, n_fft, hop_length=None, center=True,
                pad_mode="reflect", onesided=True):
        hop = hop_length or n_fft // 4
        if center:
            pad = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            x = np.pad(x, pad, mode=pad_mode)
        frames = frame_np(x, n_fft, hop)
        win = np.hanning(n_fft + 1)[:-1].astype(np.float32)
        fn = np.fft.rfft if onesided else np.fft.fft
        return fn(frames * win[:, None], axis=-2)

    # -- losses / metrics ------------------------------------------------
    def hinge_loss(logits, labels):
        return jnp.maximum(0.0, 1.0 - logits * labels)

    def huber_loss_j(x, label, delta=1.0):
        r = jnp.abs(x - label)
        return jnp.where(r <= delta, 0.5 * r * r,
                         delta * (r - 0.5 * delta))

    def huber_loss_np(x, label, delta=1.0):
        r = np.abs(x - label)
        return np.where(r <= delta, 0.5 * r * r,
                        delta * (r - 0.5 * delta)).astype(np.float32)

    def margin_cross_entropy_j(logits, label, margin1=1.0, margin2=0.5,
                               margin3=0.0, scale=64.0):
        label = label.astype(jnp.int32)
        theta = jnp.arccos(jnp.clip(logits, -1 + 1e-6, 1 - 1e-6))
        adj = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(label, logits.shape[-1],
                                dtype=logits.dtype)
        z = scale * jnp.where(onehot > 0, adj, logits)
        logp = jax.nn.log_softmax(z, axis=-1)
        return -jnp.sum(logp * onehot, axis=-1)

    def margin_cross_entropy_np(logits, label, margin1=1.0, margin2=0.5,
                                margin3=0.0, scale=64.0):
        label = label.astype(np.int64)
        theta = np.arccos(np.clip(logits, -1 + 1e-6, 1 - 1e-6))
        adj = np.cos(margin1 * theta + margin2) - margin3
        onehot = np.eye(logits.shape[-1], dtype=np.float32)[label]
        z = scale * np.where(onehot > 0, adj, logits)
        z = z - z.max(-1, keepdims=True)
        logp = z - np.log(np.exp(z).sum(-1, keepdims=True))
        return (-(logp * onehot).sum(-1)).astype(np.float32)

    def accuracy_j(pred, label, k=1):
        label = label.astype(jnp.int32)
        topk = jnp.argsort(-pred, axis=-1)[..., :k]
        hit = (topk == label[:, None]).any(axis=-1)
        return hit.astype(jnp.float32).mean()

    def accuracy_np(pred, label, k=1):
        label = label.astype(np.int64)
        topk = np.argsort(-pred, axis=-1)[..., :k]
        return (topk == label[:, None]).any(-1).astype(np.float32).mean()

    def auc_j(pred, label):
        """ROC AUC via the rank formulation (functional form of the
        reference's streaming auc op)."""
        score = pred[:, 1] if pred.ndim == 2 else pred
        order = jnp.argsort(score)
        ranks = jnp.zeros_like(score).at[order].set(
            jnp.arange(1, score.shape[0] + 1, dtype=score.dtype))
        pos = (label > 0).astype(score.dtype)
        npos = pos.sum()
        nneg = pos.shape[0] - npos
        return (ranks * pos).sum() / jnp.maximum(npos * nneg, 1.0) \
            - (npos + 1) / (2.0 * jnp.maximum(nneg, 1.0))

    def auc_np(pred, label):
        from scipy.stats import rankdata
        score = pred[:, 1] if pred.ndim == 2 else pred
        ranks = rankdata(score, method="ordinal")
        pos = (label > 0).astype(np.float64)
        npos, nneg = pos.sum(), len(pos) - pos.sum()
        return np.float32((ranks * pos).sum() / max(npos * nneg, 1.0)
                          - (npos + 1) / (2.0 * max(nneg, 1.0)))

    # -- norm / numeric --------------------------------------------------
    def clip_by_norm_j(x, max_norm):
        nrm = jnp.sqrt(jnp.sum(x * x))
        return x * (max_norm / jnp.maximum(nrm, max_norm))

    def clip_by_norm_np(x, max_norm):
        nrm = np.sqrt((x * x).sum())
        return (x * (max_norm / max(nrm, max_norm))).astype(np.float32)

    def l1_norm(x):
        return jnp.abs(x).sum()

    def fill_diagonal_j(x, value=0.0, offset=0, wrap=False):
        n, m = x.shape
        i = jnp.arange(n)[:, None]
        j = jnp.arange(m)[None, :]
        return jnp.where(j - i == offset, jnp.asarray(value, x.dtype), x)

    def fill_diagonal_np(x, value=0.0, offset=0, wrap=False):
        out = x.copy()
        i = np.arange(out.shape[0])[:, None]
        j = np.arange(out.shape[1])[None, :]
        out[(j - i) == offset] = value
        return out

    def fill_diagonal_tensor_j(x, y, offset=0, dim1=0, dim2=1):
        n, m = x.shape
        k = min(n, m - offset) if offset >= 0 else min(n + offset, m)
        rows = jnp.arange(k) + (0 if offset >= 0 else -offset)
        cols = jnp.arange(k) + max(offset, 0)
        return x.at[rows, cols].set(y[:k])

    def fill_diagonal_tensor_np(x, y, offset=0, dim1=0, dim2=1):
        out = x.copy()
        n, m = x.shape
        k = min(n, m - offset) if offset >= 0 else min(n + offset, m)
        rows = np.arange(k) + (0 if offset >= 0 else -offset)
        cols = np.arange(k) + max(offset, 0)
        out[rows, cols] = y[:k]
        return out

    def spectral_norm_j(weight, u, v, dim=0, power_iters=1, eps=1e-12):
        w = weight if dim == 0 else jnp.moveaxis(weight, dim, 0)
        mat = w.reshape(w.shape[0], -1)
        for _ in range(power_iters):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ mat @ v
        out = mat / sigma
        return out.reshape(w.shape) if dim == 0 else \
            jnp.moveaxis(out.reshape(w.shape), 0, dim)

    def spectral_norm_np(weight, u, v, dim=0, power_iters=1, eps=1e-12):
        w = weight if dim == 0 else np.moveaxis(weight, dim, 0)
        mat = w.reshape(w.shape[0], -1)
        for _ in range(power_iters):
            v = mat.T @ u
            v = v / (np.linalg.norm(v) + eps)
            u = mat @ v
            u = u / (np.linalg.norm(u) + eps)
        sigma = u @ mat @ v
        out = (mat / sigma).reshape(w.shape)
        return (out if dim == 0 else
                np.moveaxis(out, 0, dim)).astype(np.float32)

    # -- positions / encodings ------------------------------------------
    def add_position_encoding_j(x, alpha=1.0, beta=1.0):
        n, s, e = x.shape
        half = e // 2
        pos = jnp.arange(s, dtype=jnp.float32)[:, None]
        div = jnp.power(10000.0,
                        jnp.arange(half, dtype=jnp.float32) / half)
        pe = jnp.concatenate([jnp.sin(pos / div), jnp.cos(pos / div)],
                             axis=-1)
        return alpha * x + beta * pe[None]

    def add_position_encoding_np(x, alpha=1.0, beta=1.0):
        n, s, e = x.shape
        half = e // 2
        pos = np.arange(s, dtype=np.float32)[:, None]
        div = np.power(10000.0,
                       np.arange(half, dtype=np.float32) / half)
        pe = np.concatenate([np.sin(pos / div), np.cos(pos / div)], -1)
        return (alpha * x + beta * pe[None]).astype(np.float32)

    # -- structured ------------------------------------------------------
    def gather_tree_j(ids, parents):
        ids = ids.astype(jnp.int32)
        parents = parents.astype(jnp.int32)
        t = ids.shape[0]

        def body(carry, inp):
            beams, = carry
            step_ids, step_parents = inp
            sel = jnp.take_along_axis(step_ids, beams, axis=-1)
            par = jnp.take_along_axis(step_parents, beams, axis=-1)
            return (par,), sel

        init = jnp.tile(jnp.arange(ids.shape[2],
                                   dtype=ids.dtype)[None, :],
                        (ids.shape[1], 1))
        (_,), out = jax.lax.scan(body, (init,),
                                 (ids[::-1], parents[::-1]))
        return out[::-1]

    def gather_tree_np(ids, parents):
        ids = ids.astype(np.int64)
        parents = parents.astype(np.int64)
        t, b, w = ids.shape
        out = np.zeros_like(ids)
        beams = np.tile(np.arange(w)[None, :], (b, 1))
        for step in range(t - 1, -1, -1):
            out[step] = np.take_along_axis(ids[step], beams, axis=-1)
            beams = np.take_along_axis(parents[step], beams, axis=-1)
        return out

    def segment_pool_j(x, segment_ids, pool_type="MEAN",
                       num_segments=None):
        segment_ids = segment_ids.astype(jnp.int32)
        # num_segments must be static under jit; eager callers can omit
        num = int(num_segments) if num_segments is not None \
            else int(segment_ids.max()) + 1
        if pool_type == "MEAN":
            s = jax.ops.segment_sum(x, segment_ids, num)
            c = jax.ops.segment_sum(jnp.ones_like(x[:, :1]),
                                    segment_ids, num)
            return s / jnp.maximum(c, 1.0)
        op = {"SUM": jax.ops.segment_sum,
              "MAX": jax.ops.segment_max,
              "MIN": jax.ops.segment_min}[pool_type]
        return op(x, segment_ids, num)

    def segment_pool_np(x, segment_ids, pool_type="MEAN",
                        num_segments=None):
        segment_ids = segment_ids.astype(np.int64)
        num = int(num_segments) if num_segments is not None \
            else int(segment_ids.max()) + 1
        out = np.zeros((num,) + x.shape[1:], np.float32)
        for seg in range(num):
            rows = x[segment_ids == seg]
            if len(rows) == 0:
                continue
            out[seg] = {"SUM": rows.sum(0), "MEAN": rows.mean(0),
                        "MAX": rows.max(0), "MIN": rows.min(0)}[pool_type]
        return out

    def pad3d_j(x, paddings, mode="constant", value=0.0,
                data_format="NCDHW"):
        l, r, t, b, f, bk = paddings
        pads = [(0, 0), (0, 0), (f, bk), (t, b), (l, r)]
        if mode == "constant":
            return jnp.pad(x, pads, constant_values=value)
        return jnp.pad(x, pads,
                       mode={"reflect": "reflect",
                             "replicate": "edge",
                             "circular": "wrap"}[mode])

    def pad3d_np(x, paddings, mode="constant", value=0.0,
                 data_format="NCDHW"):
        l, r, t, b, f, bk = paddings
        pads = [(0, 0), (0, 0), (f, bk), (t, b), (l, r)]
        if mode == "constant":
            return np.pad(x, pads, constant_values=value).astype(
                np.float32)
        return np.pad(x, pads,
                      mode={"reflect": "reflect", "replicate": "edge",
                            "circular": "wrap"}[mode]).astype(np.float32)

    def top_p_sampling_j(probs, ps=0.9):
        """Nucleus filter + sample.  Deterministic contract for the
        generated test: with ps below the top prob it reduces to
        argmax (the sampling path uses jax.random in decode)."""
        sort_idx = jnp.argsort(-probs, axis=-1)
        sorted_p = jnp.take_along_axis(probs, sort_idx, axis=-1)
        cum = jnp.cumsum(sorted_p, axis=-1)
        keep = cum - sorted_p <= ps
        filt = jnp.where(keep, sorted_p, 0.0)
        filt = filt / filt.sum(-1, keepdims=True)
        pick = jnp.argmax(filt, axis=-1)
        ids = jnp.take_along_axis(sort_idx, pick[:, None], axis=-1)
        val = jnp.take_along_axis(probs, ids, axis=-1)
        return val, ids.astype(jnp.int64)

    def top_p_sampling_np(probs, ps=0.9):
        sort_idx = np.argsort(-probs, axis=-1)
        sorted_p = np.take_along_axis(probs, sort_idx, axis=-1)
        cum = np.cumsum(sorted_p, axis=-1)
        keep = (cum - sorted_p) <= ps
        filt = np.where(keep, sorted_p, 0.0)
        filt = filt / filt.sum(-1, keepdims=True)
        pick = np.argmax(filt, axis=-1)
        ids = np.take_along_axis(sort_idx, pick[:, None], axis=-1)
        val = np.take_along_axis(probs, ids, axis=-1)
        return val.astype(np.float32), ids.astype(np.int64)

    def assign_pos_j(x, cum_count):
        """MoE dispatch helper (reference assign_pos op): token i with
        expert x[i] gets slot --cum_count[x[i]]; builds the
        expert-grouped position array."""
        x = x.astype(jnp.int32)
        n = x.shape[0]

        def body(carry, i):
            cc, pos = carry
            e = x[i]
            cc = cc.at[e].add(-1)
            pos = pos.at[cc[e]].set(i)
            return (cc, pos), ()

        init = (cum_count.astype(jnp.int32),
                jnp.zeros((n,), jnp.int32))
        (cc, pos), _ = jax.lax.scan(body, init,
                                    jnp.arange(n - 1, -1, -1))
        return pos

    def assign_pos_np(x, cum_count):
        x = x.astype(np.int64)
        cc = cum_count.astype(np.int64).copy()
        pos = np.zeros((x.shape[0],), np.int64)
        for i in range(x.shape[0] - 1, -1, -1):
            e = x[i]
            cc[e] -= 1
            pos[cc[e]] = i
        return pos

    # -- quantization ----------------------------------------------------
    def _qmax(bits):
        return float(2 ** (bits - 1) - 1)

    def fake_quantize_abs_max_j(x, bit_length=8):
        scale = jnp.max(jnp.abs(x))
        q = jnp.round(x / jnp.maximum(scale, 1e-12) * _qmax(bit_length))
        return q, scale.reshape(1)

    def fake_quantize_abs_max_np(x, bit_length=8):
        scale = np.abs(x).max()
        q = np.round(x / max(scale, 1e-12) * _qmax(bit_length))
        return q.astype(np.float32), np.float32([scale])

    def fake_quantize_dequantize_abs_max_j(x, bit_length=8):
        scale = jnp.max(jnp.abs(x))
        qmax = _qmax(bit_length)
        q = jnp.round(x / jnp.maximum(scale, 1e-12) * qmax)
        return q * scale / qmax, scale.reshape(1)

    def fake_quantize_dequantize_abs_max_np(x, bit_length=8):
        scale = np.abs(x).max()
        qmax = _qmax(bit_length)
        q = np.round(x / max(scale, 1e-12) * qmax)
        return (q * scale / qmax).astype(np.float32), np.float32([scale])

    def fake_channel_wise_quantize_abs_max_j(x, bit_length=8,
                                             quant_axis=0):
        red = tuple(i for i in range(x.ndim) if i != quant_axis)
        scale = jnp.max(jnp.abs(x), axis=red, keepdims=True)
        q = jnp.round(x / jnp.maximum(scale, 1e-12) * _qmax(bit_length))
        return q, scale.reshape(-1)

    def fake_channel_wise_quantize_abs_max_np(x, bit_length=8,
                                              quant_axis=0):
        red = tuple(i for i in range(x.ndim) if i != quant_axis)
        scale = np.abs(x).max(axis=red, keepdims=True)
        q = np.round(x / np.maximum(scale, 1e-12) * _qmax(bit_length))
        return q.astype(np.float32), scale.reshape(-1).astype(np.float32)

    def fake_channel_wise_quantize_dequantize_abs_max_j(
            x, bit_length=8, quant_axis=0):
        red = tuple(i for i in range(x.ndim) if i != quant_axis)
        scale = jnp.max(jnp.abs(x), axis=red, keepdims=True)
        qmax = _qmax(bit_length)
        q = jnp.round(x / jnp.maximum(scale, 1e-12) * qmax)
        return q * scale / qmax, scale.reshape(-1)

    def fake_channel_wise_quantize_dequantize_abs_max_np(
            x, bit_length=8, quant_axis=0):
        red = tuple(i for i in range(x.ndim) if i != quant_axis)
        scale = np.abs(x).max(axis=red, keepdims=True)
        qmax = _qmax(bit_length)
        q = np.round(x / np.maximum(scale, 1e-12) * qmax)
        return ((q * scale / qmax).astype(np.float32),
                scale.reshape(-1).astype(np.float32))

    def fake_dequantize_max_abs_j(x, scale, max_range=127.0):
        return x * scale / max_range

    def fake_quantize_moving_average_abs_max_j(x, in_scale, bit_length=8,
                                               moving_rate=0.9):
        cur = jnp.max(jnp.abs(x))
        scale = moving_rate * in_scale.reshape(()) + (1 - moving_rate) * cur
        q = jnp.round(x / jnp.maximum(scale, 1e-12) * _qmax(bit_length))
        return q, scale.reshape(1)

    def fake_quantize_moving_average_abs_max_np(x, in_scale,
                                                bit_length=8,
                                                moving_rate=0.9):
        cur = np.abs(x).max()
        scale = moving_rate * float(in_scale.reshape(())) \
            + (1 - moving_rate) * cur
        q = np.round(x / max(scale, 1e-12) * _qmax(bit_length))
        return q.astype(np.float32), np.float32([scale])

    def weight_quantize_j(w, algo="weight_only_int8"):
        scale = jnp.max(jnp.abs(w), axis=0) / 127.0
        q = jnp.clip(jnp.round(w / jnp.maximum(scale, 1e-12)),
                     -127, 127)
        return q.astype(jnp.int8), scale

    def weight_quantize_np(w, algo="weight_only_int8"):
        scale = np.abs(w).max(axis=0) / 127.0
        q = np.clip(np.round(w / np.maximum(scale, 1e-12)), -127, 127)
        return q.astype(np.int8), scale.astype(np.float32)

    def weight_dequantize_j(qw, scale, algo="weight_only_int8"):
        return qw.astype(scale.dtype) * scale

    def weight_only_linear_j(x, qw, scale, algo="weight_only_int8"):
        return x @ (qw.astype(x.dtype) * scale.astype(x.dtype))

    def weight_only_linear_np(x, qw, scale, algo="weight_only_int8"):
        return (x @ (qw.astype(np.float32) * scale)).astype(np.float32)

    def llm_int8_linear_j(x, qw, scale, threshold=6.0):
        """bitsandbytes-style outlier decomposition: columns of x with
        any |value| > threshold run at full precision, the rest through
        the int8 weight."""
        outlier = (jnp.abs(x) > threshold).any(axis=tuple(
            range(x.ndim - 1)))
        w = qw.astype(x.dtype) * scale.astype(x.dtype)
        x_reg = jnp.where(outlier[None, :], 0.0, x)
        x_out = jnp.where(outlier[None, :], x, 0.0)
        return x_reg @ w + x_out @ w

    def llm_int8_linear_np(x, qw, scale, threshold=6.0):
        w = qw.astype(np.float32) * scale
        return (x @ w).astype(np.float32)

    def sequence_mask_j(lengths, maxlen=None):
        lengths = lengths.astype(jnp.int32)
        m = int(maxlen) if maxlen is not None else int(lengths.max())
        return (jnp.arange(m, dtype=jnp.int32)[None, :]
                < lengths[:, None]).astype(jnp.int64)

    def sequence_mask_np(lengths, maxlen=None):
        lengths = lengths.astype(np.int64)
        m = int(maxlen) if maxlen is not None else int(lengths.max())
        return (np.arange(m)[None, :] < lengths[:, None]) \
            .astype(np.int64)

    def edit_distance_j(a, b, normalized=False):
        """Levenshtein over two id sequences (reference edit_distance
        op, per-pair form).  DP rows via lax.scan — compiled loop."""
        a = a.astype(jnp.int32)
        b = b.astype(jnp.int32)
        n = b.shape[0]
        row0 = jnp.arange(n + 1, dtype=jnp.float32)

        def step(prev, ai):
            def inner(carry, j):
                left, prev_row = carry
                sub = prev_row[j - 1] + (ai != b[j - 1])
                val = jnp.minimum(jnp.minimum(left + 1,
                                              prev_row[j] + 1), sub)
                return (val, prev_row), val
            (_, _), vals = jax.lax.scan(
                inner, (prev[0] + 1.0, prev),
                jnp.arange(1, n + 1))
            row = jnp.concatenate([(prev[0] + 1.0)[None], vals])
            return row, ()
        row, _ = jax.lax.scan(step, row0, a)
        d = row[-1]
        return d / n if normalized else d

    def edit_distance_np(a, b, normalized=False):
        a, b = a.astype(np.int64), b.astype(np.int64)
        m, n = len(a), len(b)
        d = np.zeros((m + 1, n + 1), np.float32)
        d[:, 0] = np.arange(m + 1)
        d[0, :] = np.arange(n + 1)
        for i in range(1, m + 1):
            for j in range(1, n + 1):
                d[i, j] = min(d[i - 1, j] + 1, d[i, j - 1] + 1,
                              d[i - 1, j - 1] + (a[i - 1] != b[j - 1]))
        out = d[m, n]
        return np.float32(out / n) if normalized else np.float32(out)

    def _roi_sample(x, roi, out_h, out_w, ratio):
        """Average-pooled bilinear samples inside one box of one image
        channelwise ([C, H, W] -> [C, out_h, out_w])."""
        x0, y0, x1, y1 = roi[0], roi[1], roi[2], roi[3]
        bh = (y1 - y0) / out_h
        bw = (x1 - x0) / out_w
        iy = jnp.arange(out_h, dtype=jnp.float32)
        ix = jnp.arange(out_w, dtype=jnp.float32)
        sy = jnp.arange(ratio, dtype=jnp.float32)
        ys = y0 + (iy[:, None] + (sy[None, :] + 0.5) / ratio) * bh
        xs = x0 + (ix[:, None] + (sy[None, :] + 0.5) / ratio) * bw
        ys = ys.reshape(-1)                     # [out_h*ratio]
        xs = xs.reshape(-1)
        h, w = x.shape[-2], x.shape[-1]

        def bilerp(yy, xx):
            # reference kernel semantics: points beyond [-1, H]/[-1, W]
            # contribute zero; in-range coords are CLAMPED before the
            # weights are derived (no extrapolated >1 weights)
            valid = ((yy > -1.0) & (yy < h) & (xx > -1.0) & (xx < w))
            yy = jnp.clip(yy, 0.0, h - 1)
            xx = jnp.clip(xx, 0.0, w - 1)
            yy0 = jnp.floor(yy)
            xx0 = jnp.floor(xx)
            yy1 = jnp.clip(yy0 + 1, 0, h - 1)
            xx1 = jnp.clip(xx0 + 1, 0, w - 1)
            wy = yy - yy0
            wx = xx - xx0
            g = lambda a, b_: x[:, a.astype(jnp.int32),
                                b_.astype(jnp.int32)]
            out = (g(yy0, xx0) * (1 - wy) * (1 - wx)
                   + g(yy0, xx1) * (1 - wy) * wx
                   + g(yy1, xx0) * wy * (1 - wx)
                   + g(yy1, xx1) * wy * wx)
            return jnp.where(valid[None, :], out, 0.0)
        grid_y, grid_x = jnp.meshgrid(ys, xs, indexing="ij")
        vals = bilerp(grid_y.reshape(-1), grid_x.reshape(-1))
        vals = vals.reshape(x.shape[0], out_h, ratio, out_w, ratio)
        return vals.mean(axis=(2, 4))

    def roi_align_j(x, boxes, boxes_num=None, output_size=2,
                    spatial_scale=1.0, sampling_ratio=2, aligned=True):
        """Reference vision/ops roi_align, single-image form: boxes
        [K, 4] on x [1, C, H, W].  Batched x + boxes_num (the
        reference's multi-image contract) is refused, not silently
        pooled from image 0."""
        if x.shape[0] != 1 or boxes_num is not None:
            raise NotImplementedError(
                "roi_align: single-image form only (x batch == 1, "
                "boxes_num=None); split the batch at the call site")
        off = 0.5 if aligned else 0.0
        rois = boxes * spatial_scale - off
        outs = jax.vmap(lambda r: _roi_sample(
            x[0], r, output_size, output_size, sampling_ratio))(rois)
        return outs                                # [K, C, oh, ow]

    def roi_align_np(x, boxes, boxes_num=None, output_size=2,
                     spatial_scale=1.0, sampling_ratio=2, aligned=True):
        if x.shape[0] != 1 or boxes_num is not None:
            raise NotImplementedError(
                "roi_align: single-image form only")
        off = 0.5 if aligned else 0.0
        k = boxes.shape[0]
        c, h, w = x.shape[1], x.shape[2], x.shape[3]
        out = np.zeros((k, c, output_size, output_size), np.float32)
        for bi in range(k):
            x0, y0, x1, y1 = boxes[bi] * spatial_scale - off
            bh = (y1 - y0) / output_size
            bw = (x1 - x0) / output_size
            for oy in range(output_size):
                for ox in range(output_size):
                    acc = np.zeros((c,), np.float64)
                    for sy in range(sampling_ratio):
                        for sx in range(sampling_ratio):
                            yy = y0 + (oy + (sy + 0.5) / sampling_ratio) * bh
                            xx = x0 + (ox + (sx + 0.5) / sampling_ratio) * bw
                            if yy <= -1.0 or yy >= h or \
                                    xx <= -1.0 or xx >= w:
                                continue
                            yy = min(max(yy, 0.0), h - 1)
                            xx = min(max(xx, 0.0), w - 1)
                            yy0 = int(np.floor(yy))
                            xx0 = int(np.floor(xx))
                            yy1 = min(yy0 + 1, h - 1)
                            xx1 = min(xx0 + 1, w - 1)
                            wy = yy - yy0
                            wx = xx - xx0
                            acc += (x[0, :, yy0, xx0] * (1 - wy) * (1 - wx)
                                    + x[0, :, yy0, xx1] * (1 - wy) * wx
                                    + x[0, :, yy1, xx0] * wy * (1 - wx)
                                    + x[0, :, yy1, xx1] * wy * wx)
                    out[bi, :, oy, ox] = acc / (sampling_ratio ** 2)
        return out

    def nms_j(boxes, scores, iou_threshold=0.5, max_out=None):
        """Greedy NMS, compiled form: fixed max_out iterations of
        argmax + suppress (reference vision/ops nms)."""
        n = boxes.shape[0]
        k = int(max_out) if max_out is not None else n
        area = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])

        def iou(i, js):
            x0 = jnp.maximum(boxes[i, 0], boxes[js, 0])
            y0 = jnp.maximum(boxes[i, 1], boxes[js, 1])
            x1 = jnp.minimum(boxes[i, 2], boxes[js, 2])
            y1 = jnp.minimum(boxes[i, 3], boxes[js, 3])
            inter = jnp.maximum(x1 - x0, 0) * jnp.maximum(y1 - y0, 0)
            return inter / jnp.maximum(area[i] + area[js] - inter, 1e-9)

        def body(carry, _):
            live, _scores = carry
            i = jnp.argmax(jnp.where(live, _scores, -jnp.inf))
            any_live = live.any()
            sel = jnp.where(any_live, i, -1)
            ious = iou(i, jnp.arange(n))
            live = live & (ious <= iou_threshold)
            live = live.at[i].set(False)
            live = live & any_live
            return (live, _scores), sel
        (_, _), picks = jax.lax.scan(
            body, (jnp.ones((n,), bool), scores), None, length=k)
        return picks.astype(jnp.int64)

    def nms_np(boxes, scores, iou_threshold=0.5, max_out=None):
        n = boxes.shape[0]
        k = int(max_out) if max_out is not None else n
        area = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
        live = np.ones(n, bool)
        out = []
        for _ in range(k):
            if not live.any():
                out.append(-1)
                continue
            i = int(np.argmax(np.where(live, scores, -np.inf)))
            out.append(i)
            x0 = np.maximum(boxes[i, 0], boxes[:, 0])
            y0 = np.maximum(boxes[i, 1], boxes[:, 1])
            x1 = np.minimum(boxes[i, 2], boxes[:, 2])
            y1 = np.minimum(boxes[i, 3], boxes[:, 3])
            inter = np.maximum(x1 - x0, 0) * np.maximum(y1 - y0, 0)
            ious = inter / np.maximum(area[i] + area - inter, 1e-9)
            live = live & (ious <= iou_threshold)
            live[i] = False
        return np.asarray(out, np.int64)

    def send_uv_j(x, y, src_index, dst_index, message_op="ADD"):
        """Graph per-edge message (reference geometric send_uv):
        out[e] = x[src[e]] (op) y[dst[e]]."""
        src_index = src_index.astype(jnp.int32)
        dst_index = dst_index.astype(jnp.int32)
        a = x[src_index]
        b = y[dst_index]
        return {"ADD": a + b, "SUB": a - b,
                "MUL": a * b, "DIV": a / b}[message_op.upper()]

    def send_uv_np(x, y, src_index, dst_index, message_op="ADD"):
        a = x[src_index.astype(np.int64)]
        b = y[dst_index.astype(np.int64)]
        return {"ADD": a + b, "SUB": a - b, "MUL": a * b,
                "DIV": a / b}[message_op.upper()].astype(np.float32)

    def lu_unpack_j(lu, pivots, unpack_ludata=True, unpack_pivots=True):
        pivots = pivots.astype(jnp.int32)
        n = lu.shape[0]
        low = jnp.tril(lu, -1) + jnp.eye(n, dtype=lu.dtype)
        up = jnp.triu(lu)
        perm = jnp.arange(n)
        for i in range(pivots.shape[0]):          # static small loop
            j = pivots[i] - 1
            pi, pj = perm[i], perm[j]
            perm = perm.at[i].set(pj).at[j].set(pi)
        p = jnp.eye(n, dtype=lu.dtype)[perm].T
        return p, low, up

    def lu_unpack_np(lu, pivots, unpack_ludata=True, unpack_pivots=True):
        pivots = pivots.astype(np.int64)
        n = lu.shape[0]
        low = np.tril(lu, -1) + np.eye(n, dtype=np.float32)
        up = np.triu(lu)
        perm = np.arange(n)
        for i in range(len(pivots)):
            j = pivots[i] - 1
            perm[i], perm[j] = perm[j], perm[i]
        p = np.eye(n, dtype=np.float32)[perm].T
        return p.astype(np.float32), low.astype(np.float32), \
            up.astype(np.float32)

    # -- detection suite (reference: phi/kernels/cpu/{box_coder,
    #    prior_box,yolo_box,generate_proposals}_kernel.cc) -------------
    def _expand_ars(aspect_ratios, flip):
        out = [1.0]
        for ar in aspect_ratios:
            if any(abs(ar - o) < 1e-6 for o in out):
                continue
            out.append(float(ar))
            if flip:
                out.append(1.0 / ar)
        return out

    def box_coder_j(prior_box, target_box, prior_box_var=None,
                    code_type="encode_center_size", box_normalized=True,
                    axis=0, variance=None):
        """Reference: phi/kernels/cpu/box_coder_kernel.cc.  The optional
        per-prior variance rides as the `prior_box_var` attr (array) —
        the capability of the reference's third tensor input."""
        add = 0.0 if box_normalized else 1.0
        pw = prior_box[:, 2] - prior_box[:, 0] + add
        ph = prior_box[:, 3] - prior_box[:, 1] + add
        pcx = prior_box[:, 0] + pw / 2
        pcy = prior_box[:, 1] + ph / 2
        if code_type == "encode_center_size":
            tw = target_box[:, 2] - target_box[:, 0] + add
            th = target_box[:, 3] - target_box[:, 1] + add
            tcx = (target_box[:, 2] + target_box[:, 0]) / 2
            tcy = (target_box[:, 3] + target_box[:, 1]) / 2
            ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
            oy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
            ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
            oh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
            out = jnp.stack([ox, oy, ow, oh], -1)
            if prior_box_var is not None:
                out = out / jnp.asarray(prior_box_var)[None, :, :]
            elif variance:
                out = out / jnp.asarray(variance, out.dtype)
            return out
        t = target_box            # decode: [row, col, 4]
        if prior_box_var is not None:
            v = jnp.asarray(prior_box_var)
            var = v[None, :, :] if axis == 0 else v[:, None, :]
        elif variance:
            var = jnp.asarray(variance, t.dtype).reshape(1, 1, 4)
        else:
            var = jnp.ones((1, 1, 4), t.dtype)
        if axis == 0:
            pw_, ph_, pcx_, pcy_ = (a[None, :] for a in
                                    (pw, ph, pcx, pcy))
        else:
            pw_, ph_, pcx_, pcy_ = (a[:, None] for a in
                                    (pw, ph, pcx, pcy))
        tcx = var[..., 0] * t[..., 0] * pw_ + pcx_
        tcy = var[..., 1] * t[..., 1] * ph_ + pcy_
        tw = jnp.exp(var[..., 2] * t[..., 2]) * pw_
        th = jnp.exp(var[..., 3] * t[..., 3]) * ph_
        return jnp.stack([tcx - tw / 2, tcy - th / 2,
                          tcx + tw / 2 - add, tcy + th / 2 - add], -1)

    def box_coder_np(prior_box, target_box, prior_box_var=None,
                     code_type="encode_center_size", box_normalized=True,
                     axis=0, variance=None):
        add = 0.0 if box_normalized else 1.0
        p = prior_box.astype(np.float64)
        t = target_box.astype(np.float64)
        if code_type == "encode_center_size":
            rows, cols = t.shape[0], p.shape[0]
            out = np.zeros((rows, cols, 4))
            for i in range(rows):
                for j in range(cols):
                    pw = p[j, 2] - p[j, 0] + add
                    ph = p[j, 3] - p[j, 1] + add
                    pcx = p[j, 0] + pw / 2
                    pcy = p[j, 1] + ph / 2
                    tw = t[i, 2] - t[i, 0] + add
                    th = t[i, 3] - t[i, 1] + add
                    tcx = (t[i, 2] + t[i, 0]) / 2
                    tcy = (t[i, 3] + t[i, 1]) / 2
                    o = [(tcx - pcx) / pw, (tcy - pcy) / ph,
                         np.log(abs(tw / pw)), np.log(abs(th / ph))]
                    for k in range(4):
                        if prior_box_var is not None:
                            o[k] /= prior_box_var[j, k]
                        elif variance:
                            o[k] /= variance[k]
                    out[i, j] = o
            return out.astype(np.float32)
        rows, cols = t.shape[0], t.shape[1]
        out = np.zeros((rows, cols, 4))
        for i in range(rows):
            for j in range(cols):
                pi = j if axis == 0 else i
                pw = p[pi, 2] - p[pi, 0] + add
                ph = p[pi, 3] - p[pi, 1] + add
                pcx = p[pi, 0] + pw / 2
                pcy = p[pi, 1] + ph / 2
                if prior_box_var is not None:
                    v = prior_box_var[pi]
                elif variance:
                    v = variance
                else:
                    v = [1.0] * 4
                cx = v[0] * t[i, j, 0] * pw + pcx
                cy = v[1] * t[i, j, 1] * ph + pcy
                w_ = np.exp(v[2] * t[i, j, 2]) * pw
                h_ = np.exp(v[3] * t[i, j, 3]) * ph
                out[i, j] = [cx - w_ / 2, cy - h_ / 2,
                             cx + w_ / 2 - add, cy + h_ / 2 - add]
        return out.astype(np.float32)

    def _prior_wh(min_sizes, max_sizes, ars, order):
        whs = []
        for s, ms in enumerate(min_sizes):
            if order:
                whs.append((ms / 2.0, ms / 2.0))
                if max_sizes:
                    d = math.sqrt(ms * max_sizes[s]) / 2.0
                    whs.append((d, d))
                for ar in ars:
                    if abs(ar - 1.0) < 1e-6:
                        continue
                    whs.append((ms * math.sqrt(ar) / 2.0,
                                ms / math.sqrt(ar) / 2.0))
            else:
                for ar in ars:
                    whs.append((ms * math.sqrt(ar) / 2.0,
                                ms / math.sqrt(ar) / 2.0))
                if max_sizes:
                    d = math.sqrt(ms * max_sizes[s]) / 2.0
                    whs.append((d, d))
        return whs

    def prior_box_j(input, image, min_sizes=(64.0,), max_sizes=(),
                    aspect_ratios=(1.0,), variances=(0.1, 0.1, 0.2, 0.2),
                    flip=False, clip=False, steps=(0.0, 0.0), offset=0.5,
                    min_max_aspect_ratios_order=False):
        """Reference: phi/kernels/cpu/prior_box_kernel.cc → (boxes,
        variances), both [H, W, num_priors, 4]."""
        fh, fw = input.shape[2], input.shape[3]
        ih, iw = image.shape[2], image.shape[3]
        sw = steps[0] or iw / fw
        sh = steps[1] or ih / fh
        ars = _expand_ars(aspect_ratios, flip)
        whs = _prior_wh(list(min_sizes), list(max_sizes), ars,
                        min_max_aspect_ratios_order)
        p = len(whs)
        cx = (jnp.arange(fw) + offset) * sw          # [W]
        cy = (jnp.arange(fh) + offset) * sh          # [H]
        bw = jnp.asarray([w for w, _ in whs])        # [P]
        bh = jnp.asarray([h for _, h in whs])
        x0 = (cx[None, :, None] - bw[None, None, :]) / iw
        y0 = (cy[:, None, None] - bh[None, None, :]) / ih
        x1 = (cx[None, :, None] + bw[None, None, :]) / iw
        y1 = (cy[:, None, None] + bh[None, None, :]) / ih
        boxes = jnp.stack(jnp.broadcast_arrays(
            x0, y0, x1, y1), -1).astype(jnp.float32)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                               (fh, fw, p, 4))
        return boxes, var

    def prior_box_np(input, image, min_sizes=(64.0,), max_sizes=(),
                     aspect_ratios=(1.0,),
                     variances=(0.1, 0.1, 0.2, 0.2), flip=False,
                     clip=False, steps=(0.0, 0.0), offset=0.5,
                     min_max_aspect_ratios_order=False):
        fh, fw = input.shape[2], input.shape[3]
        ih, iw = image.shape[2], image.shape[3]
        sw = steps[0] or iw / fw
        sh = steps[1] or ih / fh
        ars = _expand_ars(aspect_ratios, flip)
        whs = _prior_wh(list(min_sizes), list(max_sizes), ars,
                        min_max_aspect_ratios_order)
        boxes = np.zeros((fh, fw, len(whs), 4), np.float32)
        for h in range(fh):
            for w in range(fw):
                c_x = (w + offset) * sw
                c_y = (h + offset) * sh
                for k, (bw, bh) in enumerate(whs):
                    boxes[h, w, k] = [(c_x - bw) / iw, (c_y - bh) / ih,
                                      (c_x + bw) / iw, (c_y + bh) / ih]
        if clip:
            boxes = np.clip(boxes, 0.0, 1.0)
        var = np.broadcast_to(np.asarray(variances, np.float32),
                              boxes.shape).copy()
        return boxes, var

    def yolo_box_j(x, img_size, anchors=(10, 13, 16, 30),
                   class_num=2, conf_thresh=0.01, downsample_ratio=32,
                   clip_bbox=True, scale_x_y=1.0, iou_aware=False,
                   iou_aware_factor=0.5):
        """Reference: phi/kernels/cpu/yolo_box_kernel.cc → boxes
        [N, an*H*W, 4] (anchor-major), scores [N, an*H*W, class_num];
        sub-threshold entries are zeroed, matching the kernel's memset."""
        n, _, h, w = x.shape
        an = len(anchors) // 2
        anc = jnp.asarray(anchors, jnp.float32).reshape(an, 2)
        bias = -0.5 * (scale_x_y - 1.0)
        if iou_aware:
            iou = jax.nn.sigmoid(x[:, :an].reshape(n, an, h, w))
            xr = x[:, an:].reshape(n, an, 5 + class_num, h, w)
        else:
            xr = x.reshape(n, an, 5 + class_num, h, w)
        sig = jax.nn.sigmoid
        img_h = img_size[:, 0].astype(jnp.float32).reshape(n, 1, 1, 1)
        img_w = img_size[:, 1].astype(jnp.float32).reshape(n, 1, 1, 1)
        gx = jnp.arange(w).reshape(1, 1, 1, w)
        gy = jnp.arange(h).reshape(1, 1, h, 1)
        bx = (gx + sig(xr[:, :, 0]) * scale_x_y + bias) * img_w / w
        by = (gy + sig(xr[:, :, 1]) * scale_x_y + bias) * img_h / h
        bw = jnp.exp(xr[:, :, 2]) * anc[:, 0].reshape(1, an, 1, 1) \
            * img_w / (downsample_ratio * w)
        bh = jnp.exp(xr[:, :, 3]) * anc[:, 1].reshape(1, an, 1, 1) \
            * img_h / (downsample_ratio * h)
        conf = sig(xr[:, :, 4])
        if iou_aware:
            conf = conf ** (1.0 - iou_aware_factor) \
                * iou ** iou_aware_factor
        valid = conf >= conf_thresh
        x0, y0 = bx - bw / 2, by - bh / 2
        x1, y1 = bx + bw / 2, by + bh / 2
        if clip_bbox:
            x0 = jnp.maximum(x0, 0.0)
            y0 = jnp.maximum(y0, 0.0)
            x1 = jnp.minimum(x1, img_w - 1)
            y1 = jnp.minimum(y1, img_h - 1)
        boxes = jnp.stack([x0, y0, x1, y1], -1) * valid[..., None]
        cls = sig(xr[:, :, 5:])                     # [n, an, C, h, w]
        scores = conf[:, :, None] * cls * valid[:, :, None]
        return (boxes.reshape(n, an * h * w, 4),
                scores.transpose(0, 1, 3, 4, 2)
                .reshape(n, an * h * w, class_num))

    def yolo_box_np(x, img_size, anchors=(10, 13, 16, 30),
                    class_num=2, conf_thresh=0.01, downsample_ratio=32,
                    clip_bbox=True, scale_x_y=1.0, iou_aware=False,
                    iou_aware_factor=0.5):
        def s(v):
            return 1.0 / (1.0 + np.exp(-v))
        n, _, h, w = x.shape
        an = len(anchors) // 2
        bias = -0.5 * (scale_x_y - 1.0)
        boxes = np.zeros((n, an * h * w, 4), np.float32)
        scores = np.zeros((n, an * h * w, class_num), np.float32)
        for i in range(n):
            ihh, iww = float(img_size[i, 0]), float(img_size[i, 1])
            for j in range(an):
                off = an if iou_aware else 0
                for k in range(h):
                    for l in range(w):
                        e = lambda ent: x[i, off + j * (5 + class_num)
                                          + ent, k, l]
                        conf = s(e(4))
                        if iou_aware:
                            iou = s(x[i, j, k, l])
                            conf = conf ** (1 - iou_aware_factor) \
                                * iou ** iou_aware_factor
                        idx = j * h * w + k * w + l
                        if conf < conf_thresh:
                            continue
                        cx = (l + s(e(0)) * scale_x_y + bias) * iww / w
                        cy = (k + s(e(1)) * scale_x_y + bias) * ihh / h
                        bw = np.exp(e(2)) * anchors[2 * j] * iww \
                            / (downsample_ratio * w)
                        bh = np.exp(e(3)) * anchors[2 * j + 1] * ihh \
                            / (downsample_ratio * h)
                        b = [cx - bw / 2, cy - bh / 2,
                             cx + bw / 2, cy + bh / 2]
                        if clip_bbox:
                            b = [max(b[0], 0), max(b[1], 0),
                                 min(b[2], iww - 1), min(b[3], ihh - 1)]
                        boxes[i, idx] = b
                        for c in range(class_num):
                            scores[i, idx, c] = conf * s(e(5 + c))
        return boxes, scores

    def _gp_anchors():
        gy, gx = np.meshgrid(np.arange(4.0), np.arange(4.0),
                             indexing="ij")
        a = np.arange(3, dtype=np.float32).reshape(1, 1, 3)
        x0 = gx.astype(np.float32)[:, :, None] * 8.0 + 0.0 * a
        y0 = gy.astype(np.float32)[:, :, None] * 8.0 + 0.0 * a
        return np.stack([x0, y0, x0 + 6.0 + 2.0 * a,
                         y0 + 7.0 + 2.0 * a], -1).astype(np.float32)

    _BBOX_CLIP = float(np.log(1000.0 / 16.0))

    def generate_proposals_j(scores, bbox_deltas, im_shape, anchors,
                             variances=None, pre_nms_top_n=12,
                             post_nms_top_n=6, nms_thresh=0.5,
                             min_size=0.1, eta=1.0, pixel_offset=False):
        """Reference: phi/kernels/cpu/generate_proposals_kernel.cc,
        single-image form (N == 1).  TPU-native contract: STATIC output
        [post_nms_top_n, 4] padded with zeros + rois_num (XLA needs
        static shapes; the reference's variable-length LoD output maps
        to the padded form + count).  eta != 1 (adaptive NMS) is
        refused, not approximated."""
        assert scores.shape[0] == 1 and eta == 1.0
        a_num = scores.shape[1]
        s = scores[0].transpose(1, 2, 0).reshape(-1)
        d = bbox_deltas[0].transpose(1, 2, 0).reshape(-1, 4)
        anc = anchors.reshape(-1, 4)
        var = None if variances is None else variances.reshape(-1, 4)
        k = min(int(pre_nms_top_n), s.shape[0])
        topv, topi = jax.lax.top_k(s, k)
        d, anc = d[topi], anc[topi]
        if var is not None:
            var = var[topi]
        off = 1.0 if pixel_offset else 0.0
        aw = anc[:, 2] - anc[:, 0] + off
        ah = anc[:, 3] - anc[:, 1] + off
        acx = anc[:, 0] + 0.5 * aw
        acy = anc[:, 1] + 0.5 * ah
        v = var if var is not None else jnp.ones_like(anc)
        cx = v[:, 0] * d[:, 0] * aw + acx
        cy = v[:, 1] * d[:, 1] * ah + acy
        bw = jnp.exp(jnp.minimum(v[:, 2] * d[:, 2], _BBOX_CLIP)) * aw
        bh = jnp.exp(jnp.minimum(v[:, 3] * d[:, 3], _BBOX_CLIP)) * ah
        props = jnp.stack([cx - bw / 2, cy - bh / 2,
                           cx + bw / 2 - off, cy + bh / 2 - off], -1)
        im_h, im_w = im_shape[0, 0], im_shape[0, 1]
        props = jnp.stack(
            [jnp.clip(props[:, 0], 0.0, im_w - off),
             jnp.clip(props[:, 1], 0.0, im_h - off),
             jnp.clip(props[:, 2], 0.0, im_w - off),
             jnp.clip(props[:, 3], 0.0, im_h - off)], -1)
        ms = jnp.maximum(min_size, 1.0)
        ws = props[:, 2] - props[:, 0] + off
        hs = props[:, 3] - props[:, 1] + off
        keep = (ws >= ms) & (hs >= ms)
        if pixel_offset:
            keep = keep & (props[:, 0] + ws / 2 <= im_w) \
                & (props[:, 1] + hs / 2 <= im_h)
        live_scores = jnp.where(keep, topv, -jnp.inf)
        area = ws * hs

        def iou(i, js):
            xx0 = jnp.maximum(props[i, 0], props[js, 0])
            yy0 = jnp.maximum(props[i, 1], props[js, 1])
            xx1 = jnp.minimum(props[i, 2], props[js, 2])
            yy1 = jnp.minimum(props[i, 3], props[js, 3])
            inter = jnp.maximum(xx1 - xx0 + off, 0) \
                * jnp.maximum(yy1 - yy0 + off, 0)
            return inter / jnp.maximum(area[i] + area[js] - inter,
                                       1e-10)

        def body(carry, _):
            live = carry
            i = jnp.argmax(jnp.where(live, live_scores, -jnp.inf))
            ok = (live & (live_scores > -jnp.inf)).any()
            sel = jnp.where(ok, i, -1)
            supp = iou(i, jnp.arange(props.shape[0])) > nms_thresh
            live = live & ~supp
            live = live.at[i].set(False)
            return live, sel
        _, picks = jax.lax.scan(body, keep, None,
                                length=int(post_nms_top_n))
        valid = picks >= 0
        safe = jnp.maximum(picks, 0)
        rois = props[safe] * valid[:, None]
        probs = (topv[safe] * valid)[:, None]
        return rois, probs, jnp.sum(valid).astype(jnp.int32)[None]

    def generate_proposals_np(scores, bbox_deltas, im_shape, anchors,
                              variances=None, pre_nms_top_n=12,
                              post_nms_top_n=6, nms_thresh=0.5,
                              min_size=0.1, eta=1.0,
                              pixel_offset=False):
        s = scores[0].transpose(1, 2, 0).reshape(-1).astype(np.float64)
        d = bbox_deltas[0].transpose(1, 2, 0).reshape(-1, 4)
        anc = anchors.reshape(-1, 4).astype(np.float64)
        var = None if variances is None else variances.reshape(-1, 4)
        order = np.argsort(-s, kind="stable")[:int(pre_nms_top_n)]
        off = 1.0 if pixel_offset else 0.0
        im_h, im_w = float(im_shape[0, 0]), float(im_shape[0, 1])
        props, vals = [], []
        for i in order:
            aw = anc[i, 2] - anc[i, 0] + off
            ah = anc[i, 3] - anc[i, 1] + off
            acx = anc[i, 0] + 0.5 * aw
            acy = anc[i, 1] + 0.5 * ah
            v = var[i] if var is not None else np.ones(4)
            cx = v[0] * d[i, 0] * aw + acx
            cy = v[1] * d[i, 1] * ah + acy
            bw = np.exp(min(v[2] * d[i, 2], _BBOX_CLIP)) * aw
            bh = np.exp(min(v[3] * d[i, 3], _BBOX_CLIP)) * ah
            b = [cx - bw / 2, cy - bh / 2,
                 cx + bw / 2 - off, cy + bh / 2 - off]
            b = [min(max(b[0], 0), im_w - off),
                 min(max(b[1], 0), im_h - off),
                 min(max(b[2], 0), im_w - off),
                 min(max(b[3], 0), im_h - off)]
            props.append(b)
            vals.append(s[i])
        props = np.asarray(props)
        vals = np.asarray(vals)
        ms = max(min_size, 1.0)
        ws = props[:, 2] - props[:, 0] + off
        hs = props[:, 3] - props[:, 1] + off
        keep = (ws >= ms) & (hs >= ms)
        if pixel_offset:
            keep &= (props[:, 0] + ws / 2 <= im_w) \
                & (props[:, 1] + hs / 2 <= im_h)
        area = ws * hs
        live = keep.copy()
        picks = []
        for _ in range(int(post_nms_top_n)):
            if not live.any():
                picks.append(-1)
                continue
            i = int(np.argmax(np.where(live, vals, -np.inf)))
            picks.append(i)
            xx0 = np.maximum(props[i, 0], props[:, 0])
            yy0 = np.maximum(props[i, 1], props[:, 1])
            xx1 = np.minimum(props[i, 2], props[:, 2])
            yy1 = np.minimum(props[i, 3], props[:, 3])
            inter = np.maximum(xx1 - xx0 + off, 0) \
                * np.maximum(yy1 - yy0 + off, 0)
            ious = inter / np.maximum(area[i] + area - inter, 1e-10)
            live &= ious <= nms_thresh
            live[i] = False
        rois = np.zeros((int(post_nms_top_n), 4), np.float32)
        probs = np.zeros((int(post_nms_top_n), 1), np.float32)
        cnt = 0
        for j, p_ in enumerate(picks):
            if p_ >= 0:
                rois[j] = props[p_]
                probs[j, 0] = vals[p_]
                cnt += 1
        return rois, probs, np.asarray([cnt], np.int32)

    R = "paddle/phi/ops/yaml/ops.yaml"

    def S(name, fn, ref, samples, **kw):
        return OpSpec(name, fn, ref, samples, ref=f"{R}: op {name}", **kw)

    return [
        # vision
        S("affine_channel", affine_channel, affine_channel,
          lambda: ([_n(2, 3, 4, 4), _u(0.5, 1.5, 3), _n(3)], {}),
          n_tensors=3, grad_atol=5e-2),
        S("affine_grid", affine_grid_j, affine_grid_np,
          lambda: ([_n(2, 2, 3)], {"out_h": 4, "out_w": 5})),
        S("grid_sample", grid_sample_j, grid_sample_np,
          lambda: ([_n(1, 2, 4, 4), _u(-0.9, 0.9, 1, 3, 3, 2)], {}),
          n_tensors=2, grad_atol=2e-2),
        S("shuffle_channel", shuffle_channel, shuffle_channel,
          lambda: ([_n(2, 6, 3, 3)], {"group": 3})),
        S("temporal_shift", temporal_shift_j, temporal_shift_np,
          lambda: ([_n(3, 8, 2, 2)], {"seg_num": 3}), grad_atol=5e-2),
        # pooling
        S("max_pool2d_with_index", max_pool2d_with_index_j,
          max_pool2d_with_index_np,
          lambda: ([_n(2, 3, 6, 6)], {"kernel_size": 2})),
        S("unpool", unpool_j, unpool_np,
          lambda: (
              [_n(1, 2, 2, 2),
               np.array([[[[0, 3], [9, 14]], [[1, 5], [10, 15]]]],
                        np.int32)],
              {"output_size": (4, 4)}), n_tensors=2),
        S("lp_pool2d", lp_pool2d_j, lp_pool2d_np,
          lambda: ([_u(0.2, 2.0, 2, 3, 6, 6)],
                   {"norm_type": 2.0, "kernel_size": 2}),
          grad_atol=2e-2),
        S("fractional_max_pool2d", fractional_max_pool2d_j,
          fractional_max_pool2d_np,
          lambda: ([_n(1, 2, 7, 7)],
                   {"output_size": (3, 3), "random_u": 0.4})),
        # signal
        S("frame", frame_j, frame_np,
          lambda: ([_n(2, 32)], {"frame_length": 8, "hop_length": 4}),
          method=True),
        S("overlap_add", overlap_add_j, overlap_add_np,
          lambda: ([_n(1, 8, 4)], {"hop_length": 4})),
        S("stft", stft_j, stft_np,
          lambda: ([_n(2, 64)], {"n_fft": 16, "hop_length": 8}),
          grad=False),
        # losses / metrics
        S("hinge_loss", hinge_loss, hinge_loss,
          lambda: ([_n(8, 1), np.sign(_n(8, 1)).astype(np.float32)],
                   {}), n_tensors=2, grad=False),
        S("huber_loss", huber_loss_j, huber_loss_np,
          lambda: ([_n(8, 3), _n(8, 3)], {"delta": 1.0}),
          n_tensors=2, grad_atol=2e-2),
        S("margin_cross_entropy", margin_cross_entropy_j,
          margin_cross_entropy_np,
          lambda: ([_u(-0.9, 0.9, 4, 10),
                    _ints(0, 10, 4, seed_key="mce")], {}),
          n_tensors=2, grad=False),
        S("accuracy", accuracy_j, accuracy_np,
          lambda: ([_n(16, 5), _ints(0, 5, 16, seed_key="acc")],
                   {"k": 2}), n_tensors=2, grad=False),
        S("auc", auc_j, auc_np,
          lambda: ([_u(0.01, 0.99, 32),
                    _ints(0, 2, 32, seed_key="auc")], {}),
          n_tensors=2, grad=False),
        # norms / numeric
        S("clip_by_norm", clip_by_norm_j, clip_by_norm_np,
          lambda: ([_n(4, 5)], {"max_norm": 1.0})),
        S("l1_norm", l1_norm, lambda x: np.abs(x).sum(),
          lambda: ([_n(4, 5)], {}), grad=False),
        S("fill_diagonal", fill_diagonal_j, fill_diagonal_np,
          lambda: ([_n(4, 5)], {"value": 7.0}), method=True),
        S("fill_diagonal_tensor", fill_diagonal_tensor_j,
          fill_diagonal_tensor_np,
          lambda: ([_n(4, 5), _n(4)], {}), n_tensors=2, method=True),
        S("spectral_norm", spectral_norm_j, spectral_norm_np,
          lambda: ([_n(4, 6), _n(4), _n(6)], {"power_iters": 2}),
          n_tensors=3, grad=False),
        # encodings / structured
        S("add_position_encoding", add_position_encoding_j,
          add_position_encoding_np,
          lambda: ([_n(2, 6, 8)], {"alpha": 1.0, "beta": 0.5})),
        S("gather_tree", gather_tree_j, gather_tree_np,
          lambda: ([_ints(0, 9, 4, 2, 3, seed_key="gt_ids"),
                    _ints(0, 3, 4, 2, 3, seed_key="gt_par")], {}),
          n_tensors=2, grad=False),
        S("segment_pool", segment_pool_j, segment_pool_np,
          lambda: ([_n(8, 4),
                    np.sort(_ints(0, 3, 8, seed_key="seg"))],
                   {"pool_type": "MEAN"}), n_tensors=2, grad=False),
        S("pad3d", pad3d_j, pad3d_np,
          lambda: ([_n(2, 2, 3, 4, 5)],
                   {"paddings": (1, 1, 0, 1, 1, 0)}), grad_atol=5e-2),
        S("top_p_sampling", top_p_sampling_j, top_p_sampling_np,
          lambda: ([(lambda p: p / p.sum(-1, keepdims=True))(
              _u(0.01, 1.0, 4, 16))], {"ps": 0.2}), grad=False),
        S("assign_pos", assign_pos_j, assign_pos_np,
          lambda: ([_ints(0, 4, 10, seed_key="ap"),
                    np.cumsum(np.bincount(
                        _ints(0, 4, 10, seed_key="ap"),
                        minlength=4)).astype(np.int64)], {}),
          n_tensors=2, grad=False),
        S("sequence_mask", sequence_mask_j, sequence_mask_np,
          lambda: ([_ints(1, 7, 5, seed_key="sm")], {"maxlen": 8}),
          grad=False),
        S("edit_distance", edit_distance_j, edit_distance_np,
          lambda: ([_ints(0, 5, 7, seed_key="ed_a"),
                    _ints(0, 5, 9, seed_key="ed_b")], {}),
          n_tensors=2, grad=False),
        S("roi_align", roi_align_j, roi_align_np,
          lambda: ([_n(1, 2, 8, 8),
                    np.array([[1.0, 1.0, 6.0, 6.0],
                              [0.0, 2.0, 4.0, 7.0]], np.float32)],
                   {"output_size": 2}), n_tensors=2, grad=False,
          atol=1e-3),
        S("nms", nms_j, nms_np,
          lambda: ([np.array([[0, 0, 4, 4], [1, 1, 5, 5],
                              [8, 8, 12, 12]], np.float32),
                    np.array([0.9, 0.8, 0.7], np.float32)],
                   {"iou_threshold": 0.3}), n_tensors=2, grad=False),
        S("box_coder", box_coder_j, box_coder_np,
          lambda: ([np.array([[0., 0., 4., 4.], [2., 2., 8., 8.]],
                             np.float32),
                    np.array([[1., 1., 5., 5.], [0., 2., 6., 10.],
                              [2., 0., 3., 7.]], np.float32)],
                   {"variance": [0.1, 0.1, 0.2, 0.2]}),
          n_tensors=2, grad=False, atol=1e-4),
        S("prior_box", prior_box_j, prior_box_np,
          lambda: ([_n(1, 3, 4, 4), _n(1, 3, 32, 32)],
                   {"min_sizes": [4.0, 8.0], "max_sizes": [10.0, 16.0],
                    "aspect_ratios": [1.0, 2.0], "flip": True,
                    "clip": True, "offset": 0.5,
                    "min_max_aspect_ratios_order": True}),
          n_tensors=2, grad=False, atol=1e-5),
        S("yolo_box", yolo_box_j, yolo_box_np,
          lambda: ([_n(1, 14, 3, 3),
                    np.array([[96, 64]], np.float32)],
                   {"anchors": [10, 13, 16, 30], "class_num": 2,
                    "conf_thresh": 0.3, "downsample_ratio": 32}),
          n_tensors=2, grad=False, atol=1e-4),
        S("generate_proposals", generate_proposals_j,
          generate_proposals_np,
          lambda: ([_n(1, 3, 4, 4),
                    _n(1, 12, 4, 4) * 0.2,
                    np.array([[32.0, 32.0]], np.float32),
                    _gp_anchors()],
                   {"pre_nms_top_n": 12, "post_nms_top_n": 5,
                    "nms_thresh": 0.5, "min_size": 1.0,
                    "pixel_offset": True}),
          n_tensors=4, grad=False, atol=1e-3),
        S("send_uv", send_uv_j, send_uv_np,
          lambda: ([_n(5, 4), _n(5, 4),
                    _ints(0, 5, 7, seed_key="suv_s"),
                    _ints(0, 5, 7, seed_key="suv_d")],
                   {"message_op": "MUL"}), n_tensors=4, grad=False),
        S("lu_unpack", lu_unpack_j, lu_unpack_np,
          lambda: ([_n(4, 4),
                    np.array([2, 3, 3, 4], np.int32)], {}),
          n_tensors=2, grad=False),
        # quantization family
        S("fake_quantize_abs_max", fake_quantize_abs_max_j,
          fake_quantize_abs_max_np, lambda: ([_n(4, 6)], {}),
          grad=False),
        S("fake_quantize_dequantize_abs_max",
          fake_quantize_dequantize_abs_max_j,
          fake_quantize_dequantize_abs_max_np,
          lambda: ([_n(4, 6)], {}), grad=False),
        S("fake_channel_wise_quantize_abs_max",
          fake_channel_wise_quantize_abs_max_j,
          fake_channel_wise_quantize_abs_max_np,
          lambda: ([_n(4, 6)], {}), grad=False),
        S("fake_channel_wise_quantize_dequantize_abs_max",
          fake_channel_wise_quantize_dequantize_abs_max_j,
          fake_channel_wise_quantize_dequantize_abs_max_np,
          lambda: ([_n(4, 6)], {}), grad=False),
        S("fake_dequantize_max_abs", fake_dequantize_max_abs_j,
          fake_dequantize_max_abs_j,
          lambda: ([_n(4, 6), np.float32([0.5])], {}),
          n_tensors=2, grad=False),
        S("fake_channel_wise_dequantize_max_abs",
          lambda x, scale, quant_axis=0:
              x * scale.reshape([-1 if i == quant_axis else 1
                                 for i in range(x.ndim)]) / 127.0,
          lambda x, scale, quant_axis=0:
              (x * scale.reshape([-1 if i == quant_axis else 1
                                  for i in range(x.ndim)])
               / 127.0).astype(np.float32),
          lambda: ([_n(4, 6), _u(0.1, 1.0, 4)], {}),
          n_tensors=2, grad=False),
        S("fake_quantize_moving_average_abs_max",
          fake_quantize_moving_average_abs_max_j,
          fake_quantize_moving_average_abs_max_np,
          lambda: ([_n(4, 6), np.float32([0.8])], {}),
          n_tensors=2, grad=False),
        S("weight_quantize", weight_quantize_j, weight_quantize_np,
          lambda: ([_n(8, 4)], {}), grad=False),
        S("weight_dequantize", weight_dequantize_j, weight_dequantize_j,
          lambda: ([_ints(-127, 127, 8, 4,
                          seed_key="wq").astype(np.float32),
                    _u(0.001, 0.02, 4)], {}),
          n_tensors=2, grad=False),
        S("weight_only_linear", weight_only_linear_j,
          weight_only_linear_np,
          lambda: ([_n(3, 8),
                    _ints(-127, 127, 8, 4,
                          seed_key="wol").astype(np.float32),
                    _u(0.001, 0.02, 4)], {}),
          n_tensors=3, grad=False),
        S("llm_int8_linear", llm_int8_linear_j, llm_int8_linear_np,
          lambda: ([_n(3, 8),
                    _ints(-127, 127, 8, 4,
                          seed_key="l8").astype(np.float32),
                    _u(0.001, 0.02, 4)], {"threshold": 100.0}),
          n_tensors=3, grad=False),
    ]
