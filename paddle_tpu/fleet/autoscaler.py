"""SLO-driven elastic autoscaler (ISSUE 19, ROADMAP item 3).

The repo can *observe* everything (router `stats()` / `router_view()`
per-class attainment, queue depth, the ISSUE-19 sliding-window shed
rate, r14 fleet telemetry) and can *change shape* losslessly (r17
elastic re-form, r19 drain-and-requeue, the router's drain/undrain/
add/set_role surface) — this module connects the two: under a diurnal
load curve the fleet reshapes itself, and the reshaping machinery
itself survives crashes, races and flapping.

Three layers, strictly separated so each is testable alone:

  * **policy** — :func:`decide(view, policy, state) -> Action` is a
    PURE function over an aggregated fleet view (:func:`fleet_view`),
    a :class:`AutoscalePolicy` and a :class:`PolicyState`.  Hysteresis
    (``window`` consecutive pressured/idle ticks before acting) and
    per-action-kind cooldowns live in the state the caller threads
    through :func:`observe` / :func:`after_action` — oscillating load
    can never flap the fleet, and the whole state machine unit-tests
    with synthetic views, no fleet required.

  * **fencing + journal** — the :class:`AutoscalerDaemon` holds a KV
    lease (``<job>/autoscale/lease``, master-clock TTL) and claims a
    MONOTONIC EPOCH per action via ``put_new`` on
    ``<job>/autoscale/journal/<epoch>`` — the atomic put-if-absent is
    the true fence: two daemons (or one restarted mid-action) can
    never double-execute an epoch.  The journal record is written
    ``pending`` BEFORE execution and flipped ``done``/``rolled_back``
    after (the r9 tmp-then-commit idiom on KV keys): a daemon that
    crashes mid-action leaves a pending record the next incarnation
    observes in :meth:`AutoscalerDaemon.recover` and either completes
    or rolls back — never repeats.

  * **execution** — actions run through the EXISTING lossless elastic
    surface (`drain_replica` + retire-when-empty for scale-in, undrain
    or `add_replica` for scale-out, drain → `set_role` → undrain for a
    role flip), so zero requests are dropped by construction.  Every
    step rides a `FLAGS_fault_injection` point (``autoscale.decide`` /
    ``autoscale.drain`` / ``autoscale.reform``); a failed action is
    retried with bounded backoff, then ROLLED BACK: the target replica
    returns to rotation, an ``autoscaler.rollback`` event fires, and
    the journal records the failure.

With ``FLAGS_autoscale`` off (the single-replica default) ``tick()``
returns on one flag read — no KV traffic, no view aggregation, and the
serve-step HLO / program-cache keys are byte-identical (bench.py's
zero-overhead battery asserts all three).

:class:`DiurnalLoadSim` generates the deterministic load curve the
tier-1 end-to-end tests, ``chaos_check --autoscale`` and the
``llama_serve_autoscale`` bench leg share.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..framework.flags import define_flag, get_flag
from ..distributed import fault

__all__ = ["Action", "AutoscalePolicy", "PolicyState", "decide",
           "observe", "after_action", "fleet_view",
           "AutoscalerDaemon", "DiurnalLoadSim"]

define_flag("autoscale_queue_high", 1.5,
            "fleet occupancy ((active+queued)/slots) above which a "
            "tick counts as PRESSURED toward a scale-out")
define_flag("autoscale_queue_low", 0.25,
            "fleet occupancy below which a tick counts as IDLE toward "
            "a scale-in")
define_flag("autoscale_shed_high", 0.05,
            "max per-replica sliding-window shed rate above which a "
            "tick counts as pressured regardless of occupancy")
define_flag("autoscale_lease_ttl_s", 5.0,
            "autoscaler KV lease TTL (master-clock seconds); an "
            "expired lease is taken over by the next daemon tick")


# ---------------------------------------------------------------------------
# the decision — pure data in, pure data out
# ---------------------------------------------------------------------------

KINDS = ("scale_out", "scale_in", "role_flip", "none")


class Action:
    """One autoscaling decision. ``kind`` ∈ scale_out | scale_in |
    role_flip | none; ``replica`` names the target (the scale-in/flip
    victim, or the draining replica a scale-out revives — None means
    spawn fresh); ``role`` is the flip target / new-replica role;
    ``reason`` is the human-readable trigger."""

    __slots__ = ("kind", "replica", "role", "reason")

    def __init__(self, kind: str, replica: Optional[int] = None,
                 role: Optional[str] = None, reason: str = ""):
        if kind not in KINDS:
            raise ValueError(f"unknown action kind {kind!r}")
        self.kind = kind
        self.replica = replica
        self.role = role
        self.reason = reason

    def to_dict(self) -> dict:
        return {"kind": self.kind, "replica": self.replica,
                "role": self.role, "reason": self.reason}

    def __repr__(self):
        return (f"Action({self.kind}, replica={self.replica}, "
                f"role={self.role}, reason={self.reason!r})")


class AutoscalePolicy:
    """The policy knobs — constructor args win, flags fill the rest
    (so a daemon built bare follows the FLAGS_autoscale_* surface)."""

    __slots__ = ("min_replicas", "max_replicas", "queue_high",
                 "queue_low", "attainment_floor", "shed_high",
                 "window", "cooldown", "retry_budget", "backoff_s",
                 "lease_ttl_s", "target_roles", "role_imbalance")

    def __init__(self, min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 queue_high: Optional[float] = None,
                 queue_low: Optional[float] = None,
                 attainment_floor: Optional[float] = None,
                 shed_high: Optional[float] = None,
                 window: Optional[int] = None,
                 cooldown: Optional[int] = None,
                 retry_budget: int = 3, backoff_s: float = 0.0,
                 lease_ttl_s: Optional[float] = None,
                 target_roles: Optional[Dict[str, int]] = None,
                 role_imbalance: Optional[float] = None):
        def flag(name, fallback):
            v = get_flag(name)
            return fallback if v is None else v
        self.min_replicas = int(min_replicas if min_replicas is not None
                                else flag("autoscale_min_replicas", 1))
        self.max_replicas = int(max_replicas if max_replicas is not None
                                else flag("autoscale_max_replicas", 4))
        self.queue_high = float(queue_high if queue_high is not None
                                else flag("autoscale_queue_high", 1.5))
        self.queue_low = float(queue_low if queue_low is not None
                               else flag("autoscale_queue_low", 0.25))
        self.attainment_floor = float(
            attainment_floor if attainment_floor is not None
            else flag("router_attainment_floor", 0.0))
        self.shed_high = float(shed_high if shed_high is not None
                               else flag("autoscale_shed_high", 0.05))
        self.window = max(1, int(window if window is not None
                                 else flag("autoscale_window", 2)))
        self.cooldown = max(0, int(cooldown if cooldown is not None
                                   else flag("autoscale_cooldown", 4)))
        self.retry_budget = max(1, int(retry_budget))
        self.backoff_s = float(backoff_s)
        self.lease_ttl_s = float(
            lease_ttl_s if lease_ttl_s is not None
            else flag("autoscale_lease_ttl_s", 5.0))
        self.target_roles = dict(target_roles) if target_roles else None
        # ISSUE 20: dynamic role repair — how many times MORE pressure
        # one side of a disaggregated fleet must carry (sustained for
        # `window` ticks) before a replica of the relaxed role flips
        # over.  0 disables; only acts when the fleet actually has
        # both prefill and decode replicas
        self.role_imbalance = float(
            role_imbalance if role_imbalance is not None
            else flag("autoscale_role_imbalance", 2.0))


class PolicyState:
    """The hysteresis state threaded between ticks: consecutive
    pressured/idle streaks and per-action-kind cooldown counters
    (ticks remaining).  Mutated only by `observe`/`after_action` —
    `decide` reads it and stays pure."""

    __slots__ = ("pressure_streak", "idle_streak", "prefill_streak",
                 "decode_streak", "cooldowns")

    def __init__(self):
        self.pressure_streak = 0
        self.idle_streak = 0
        # consecutive ticks of one-sided role pressure in a
        # disaggregated fleet (ISSUE 20): prefill_streak counts ticks
        # the prefill side out-pressured decode by policy.role_imbalance
        self.prefill_streak = 0
        self.decode_streak = 0
        self.cooldowns: Dict[str, int] = {}

    def cooling(self, kind: str) -> bool:
        return self.cooldowns.get(kind, 0) > 0


def _pressured(view: dict, policy: AutoscalePolicy) -> bool:
    if float(view.get("occupancy") or 0.0) > policy.queue_high:
        return True
    if float(view.get("shed_rate_window") or 0.0) > policy.shed_high:
        return True
    att = view.get("attainment_interactive")
    if policy.attainment_floor > 0 and att is not None \
            and att < policy.attainment_floor:
        return True
    return False


def observe(state: PolicyState, view: dict,
            policy: AutoscalePolicy) -> PolicyState:
    """Fold one tick's fleet view into the hysteresis state: cooldowns
    count down, the pressure/idle streaks advance (mutually exclusive;
    a neutral tick clears both — 'consecutive' means consecutive)."""
    for k in list(state.cooldowns):
        if state.cooldowns[k] > 0:
            state.cooldowns[k] -= 1
    if _pressured(view, policy):
        state.pressure_streak += 1
        state.idle_streak = 0
    elif float(view.get("occupancy") or 0.0) < policy.queue_low:
        state.idle_streak += 1
        state.pressure_streak = 0
    else:
        state.pressure_streak = 0
        state.idle_streak = 0
    # role-imbalance streaks (ISSUE 20): only meaningful when the
    # fleet view carries BOTH sides' pressure signals (a unified
    # fleet publishes neither) and the policy enables repair
    pp = view.get("prefill_pressure")
    dp = view.get("decode_pressure")
    ratio = policy.role_imbalance
    if ratio > 0 and pp is not None and dp is not None:
        if pp > dp * ratio and pp > 0:
            state.prefill_streak += 1
            state.decode_streak = 0
        elif dp > pp * ratio and dp > 0:
            state.decode_streak += 1
            state.prefill_streak = 0
        else:
            state.prefill_streak = 0
            state.decode_streak = 0
    else:
        state.prefill_streak = 0
        state.decode_streak = 0
    return state


_OPPOSITE = {"scale_out": "scale_in", "scale_in": "scale_out"}


def after_action(state: PolicyState, action: Action,
                 policy: AutoscalePolicy) -> PolicyState:
    """Commit an EXECUTED action into the state: its kind AND its
    opposite enter cooldown (the stabilization window — a scale-in
    immediately undone by a scale-out is exactly the flap the policy
    must forbid) and both streaks reset (the fleet just changed shape —
    old evidence is stale)."""
    if action.kind != "none":
        state.cooldowns[action.kind] = policy.cooldown
        opp = _OPPOSITE.get(action.kind)
        if opp:
            state.cooldowns[opp] = policy.cooldown
        state.pressure_streak = 0
        state.idle_streak = 0
        state.prefill_streak = 0
        state.decode_streak = 0
    return state


def decide(view: dict, policy: AutoscalePolicy,
           state: Optional[PolicyState] = None) -> Action:
    """THE decision — a pure function of (fleet view, policy,
    hysteresis state); nothing here touches a router, the KV plane or
    a clock.  Priority order (first match wins):

      1. **floor repair** — routable < min_replicas: scale out NOW
         (no hysteresis, no cooldown: a fleet below its floor is an
         availability incident, not an optimization).
      2. **role repair** — `policy.target_roles` set and the routable
         role counts mismatch it: flip the least-loaded replica of an
         over-represented role (cooldown-gated).
      3. **scale-out** — pressured for >= `window` consecutive ticks,
         routable < max_replicas, not cooling.  Prefers REVIVING a
         draining replica (its device state is intact — undrain is
         free) over spawning fresh.
      4. **scale-in** — idle for >= `window` consecutive ticks,
         routable > min_replicas, not cooling.  Victim: the routable
         replica with the least work, newest id on ties (LIFO — the
         longest-lived replicas hold the warmest prefix caches).
      5. otherwise ``none``.
    """
    state = state if state is not None else PolicyState()
    reps: List[dict] = list(view.get("replicas") or [])
    routable = [r for r in reps if not r.get("draining")]
    draining = [r for r in reps if r.get("draining")]
    n = len(routable)

    if n < policy.min_replicas:
        revive = min((r["replica"] for r in draining), default=None)
        return Action("scale_out", replica=revive,
                      reason=f"floor: {n} < min {policy.min_replicas}")

    if policy.target_roles:
        have: Dict[str, int] = {}
        for r in routable:
            have[r.get("role") or "serve"] = \
                have.get(r.get("role") or "serve", 0) + 1
        want = policy.target_roles
        over = [k for k in have if have[k] > want.get(k, 0)]
        under = [k for k in want if want[k] > have.get(k, 0)]
        if over and under and not state.cooling("role_flip"):
            donors = [r for r in routable
                      if (r.get("role") or "serve") == over[0]]
            victim = min(donors, key=lambda r: (
                float(r.get("queued") or 0)
                + float(r.get("active") or 0), -int(r["replica"])))
            return Action("role_flip", replica=int(victim["replica"]),
                          role=under[0],
                          reason=f"roles: {have} -> {want}")

    if not policy.target_roles and policy.role_imbalance > 0 \
            and not state.cooling("role_flip"):
        # dynamic role repair (ISSUE 20): sustained one-sided pressure
        # in a disaggregated fleet flips the least-loaded replica of
        # the relaxed role — never below one replica per role (a fleet
        # with no prefill worker admits nothing; one with no decode
        # worker deadlocks its hand-offs into the unfreeze fallback)
        pre = [r for r in routable if r.get("role") == "prefill"]
        dec = [r for r in routable if r.get("role") == "decode"]
        if pre and dec:
            def load(r):
                return (float(r.get("queued") or 0)
                        + float(r.get("active") or 0),
                        -int(r["replica"]))
            if state.prefill_streak >= policy.window and len(dec) > 1:
                victim = min(dec, key=load)
                return Action(
                    "role_flip", replica=int(victim["replica"]),
                    role="prefill",
                    reason=f"prefill pressure x{state.prefill_streak} "
                           f"(pp={view.get('prefill_pressure')} "
                           f"dp={view.get('decode_pressure')})")
            if state.decode_streak >= policy.window and len(pre) > 1:
                victim = min(pre, key=load)
                return Action(
                    "role_flip", replica=int(victim["replica"]),
                    role="decode",
                    reason=f"decode pressure x{state.decode_streak} "
                           f"(pp={view.get('prefill_pressure')} "
                           f"dp={view.get('decode_pressure')})")

    if state.pressure_streak >= policy.window \
            and n < policy.max_replicas \
            and not state.cooling("scale_out"):
        revive = min((r["replica"] for r in draining), default=None)
        return Action("scale_out", replica=revive,
                      reason=f"pressure x{state.pressure_streak} "
                             f"(occ={view.get('occupancy')})")

    if state.idle_streak >= policy.window \
            and n > policy.min_replicas \
            and not state.cooling("scale_in"):
        victim = min(routable, key=lambda r: (
            float(r.get("queued") or 0) + float(r.get("active") or 0),
            -int(r["replica"])))
        return Action("scale_in", replica=int(victim["replica"]),
                      reason=f"idle x{state.idle_streak} "
                             f"(occ={view.get('occupancy')})")

    return Action("none", reason="steady")


def fleet_view(router) -> dict:
    """Aggregate a `ServeRouter`'s live per-replica `router_view()`s
    into THE dict `decide` consumes — occupancy over routable slots,
    the WORST interactive attainment and sliding-window shed rate
    (one failing replica is a fleet problem), per-replica summaries.
    Pure aggregation: nothing here mutates the router."""
    views = router._views()
    routable = [v for v in views if not v.get("draining")]
    slots = sum(int(v.get("slots") or 0) for v in routable)
    queued = sum(int(v.get("queued") or 0) for v in views)
    active = sum(int(v.get("active") or 0) for v in views)
    work = queued + active
    occ = round(work / slots, 4) if slots \
        else (99.0 if work else 0.0)
    atts = [(v.get("attainment") or {}).get("interactive")
            for v in routable]
    atts = [a for a in atts if a is not None]
    sheds = [float(v.get("shed_rate_window") or 0.0) for v in routable]
    reps = []
    for v in views:
        reps.append({
            "replica": int(v["replica"]),
            "role": v.get("role") or "serve",
            "draining": bool(v.get("draining")),
            "queued": int(v.get("queued") or 0),
            "active": int(v.get("active") or 0),
            "handoff_ready": int(v.get("handoff_ready") or 0),
            "attainment_interactive":
                (v.get("attainment") or {}).get("interactive"),
        })
    out = {
        "replicas": reps,
        "routable": len(routable),
        "slots": slots,
        "queued": queued,
        "active": active,
        "occupancy": occ,
        "attainment_interactive": min(atts) if atts else None,
        "shed_rate_window": round(max(sheds), 4) if sheds else 0.0,
    }
    # disaggregated split (ISSUE 20): prefill demand is queued work
    # plus live prompt chunks — a slot FROZEN for hand-off is finished
    # prefill waiting on a decode slot, so it leaves the prefill side
    # and counts toward DECODE demand (the hand-off backlog) instead
    pre = [v for v in routable if v.get("role") == "prefill"]
    dec = [v for v in routable if v.get("role") == "decode"]
    if pre and dec:
        frozen = sum(int(v.get("handoff_ready") or 0) for v in pre)
        pre_work = sum(int(v.get("queued") or 0)
                       + int(v.get("active") or 0) for v in pre) - frozen
        pre_slots = sum(int(v.get("slots") or 0) for v in pre)
        dec_work = sum(int(v.get("queued") or 0)
                       + int(v.get("active") or 0)
                       for v in dec) + frozen
        dec_slots = sum(int(v.get("slots") or 0) for v in dec)
        out.update(
            handoff_ready=frozen,
            prefill_pressure=round(pre_work / pre_slots, 4)
            if pre_slots else (99.0 if pre_work else 0.0),
            decode_pressure=round(dec_work / dec_slots, 4)
            if dec_slots else (99.0 if dec_work else 0.0),
        )
    return out


def _view_brief(view: dict) -> dict:
    """The journal-sized slice of a fleet view (before/after per
    action): enough for autoscale_report's attainment table without
    dragging per-replica records into every record."""
    out = {"routable": view.get("routable"),
           "occupancy": view.get("occupancy"),
           "queued": view.get("queued"),
           "attainment_interactive":
               view.get("attainment_interactive"),
           "shed_rate_window": view.get("shed_rate_window")}
    if view.get("prefill_pressure") is not None:
        out["prefill_pressure"] = view["prefill_pressure"]
        out["decode_pressure"] = view["decode_pressure"]
        out["handoff_ready"] = view.get("handoff_ready")
    return out


# ---------------------------------------------------------------------------
# the daemon — lease-fenced, journaled, crash-recoverable
# ---------------------------------------------------------------------------

class _LocalKV:
    """In-process stand-in for `launch.master.KVClient` (same verb
    surface: put/put_new/get/delete/prefix/stamp/time) so a single-
    process fleet runs the identical lease/journal protocol without a
    KVServer — tier-1 tests and the bench leg ride this."""

    def __init__(self):
        self._d: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._t0 = time.monotonic()

    def time(self) -> float:
        return time.monotonic() - self._t0

    def put(self, key: str, value: str) -> bool:
        with self._lock:
            self._d[key] = str(value)
        return True

    def put_new(self, key: str, value: str) -> bool:
        with self._lock:
            if key in self._d:
                return False
            self._d[key] = str(value)
            return True

    def get(self, key: str) -> Optional[str]:
        with self._lock:
            return self._d.get(key)

    def delete(self, key: str) -> bool:
        with self._lock:
            return self._d.pop(key, None) is not None

    def prefix(self, p: str) -> Dict[str, str]:
        with self._lock:
            return {k: v for k, v in self._d.items()
                    if k.startswith(p)}

    def stamp(self, key: str) -> bool:
        return self.put(key, repr(self.time()))


class _SimulatedCrash(RuntimeError):
    """Raised between execute and journal-commit when a chaos harness
    arms `daemon._crash_before_commit` — models the daemon dying
    mid-action so the next incarnation's recover() path is exercised
    without os._exit'ing the test process."""


class AutoscalerDaemon:
    """The loop body: ``tick()`` once per poll interval (the caller
    owns the clock — tests and the bench drive it synchronously, a
    deployment wraps it in a timer thread).

    Per tick: flag gate (off -> return, zero KV traffic) -> lease ->
    recover any pending journal record (complete-or-rollback) ->
    ``autoscale.decide`` fault point -> `fleet_view` -> `observe` /
    `decide` -> claim an epoch (``put_new`` journal record, pending)
    -> execute with bounded retry -> commit (done) or roll back
    (rolled_back + target returned to rotation).

    `spawn` is the scale-out factory (-> ContinuousBatcher); without
    one a fresh-spawn scale-out fails (and rolls back) but reviving a
    draining replica still works.  `kv=None` uses an in-process
    `_LocalKV` — identical protocol, no server."""

    def __init__(self, router, kv=None, job_id: str = "serve",
                 policy: Optional[AutoscalePolicy] = None,
                 spawn: Optional[Callable] = None,
                 daemon_id: str = "d0"):
        if isinstance(kv, str):
            from ..distributed.launch.master import KVClient
            kv = KVClient(kv)
        self.router = router
        self.kv = kv if kv is not None else _LocalKV()
        self.job = job_id
        self.policy = policy if policy is not None else AutoscalePolicy()
        self.spawn = spawn
        self.daemon_id = daemon_id
        self.state = PolicyState()
        self._ticks = 0
        self._next_epoch = 0
        self._recovered_once = False
        self._crash_before_commit = False   # chaos harness hook

    # -- KV keys -----------------------------------------------------------
    def _lease_key(self) -> str:
        return f"{self.job}/autoscale/lease"

    def _journal_key(self, epoch: int) -> str:
        return f"{self.job}/autoscale/journal/{epoch:08d}"

    # -- lease -------------------------------------------------------------
    def _hold_lease(self) -> bool:
        """Acquire/refresh the daemon lease.  The lease is an OPTIMIZER
        (it keeps a standby daemon from burning decide cycles), not the
        fence — the per-epoch ``put_new`` is what makes double-execution
        impossible even under a split-brain lease takeover."""
        key = self._lease_key()
        now = self.kv.time() or 0.0
        mine = json.dumps({"owner": self.daemon_id,
                           "expires": now + self.policy.lease_ttl_s})
        raw = self.kv.get(key)
        if raw is None:
            if self.kv.put_new(key, mine):
                return True
            raw = self.kv.get(key)
            if raw is None:
                return False
        try:
            rec = json.loads(raw)
        except ValueError:
            rec = {}
        if rec.get("owner") == self.daemon_id:
            self.kv.put(key, mine)          # refresh
            return True
        if float(rec.get("expires") or 0.0) > now:
            return False                    # live foreign lease
        self.kv.put(key, mine)              # expired: take over
        from .. import telemetry as _tel
        _tel.counter("autoscaler.lease_takeovers").inc()
        return True

    # -- journal -----------------------------------------------------------
    def journal(self) -> List[dict]:
        """All journal records, epoch order — what autoscale_report
        renders and chaos_check audits for double-execution."""
        out = []
        for key, raw in sorted(
                self.kv.prefix(f"{self.job}/autoscale/journal").items()):
            try:
                out.append(json.loads(raw))
            except ValueError:
                continue
        return out

    def recover(self) -> int:
        """Observe any PENDING journal record a dead incarnation left
        and settle it: completed-in-the-world -> commit ``done``,
        never-happened -> roll back (target replica returned to
        rotation, ``rolled_back``).  Also advances the epoch cursor
        past every journaled epoch.  Returns the number of records
        settled — idempotent, safe on every tick."""
        settled = 0
        for rec in self.journal():
            self._next_epoch = max(self._next_epoch,
                                   int(rec.get("epoch", -1)) + 1)
            if rec.get("status") != "pending":
                continue
            kind = rec.get("kind")
            idx = rec.get("replica")
            done = False
            if kind == "scale_in":
                rep = self._rep(idx)
                done = rep is not None and (rep.draining or rep.dead)
            elif kind == "scale_out":
                done = len(self.router._reps) \
                    > int(rec.get("fleet_before") or 0) \
                    or self._revived(idx)
            elif kind == "role_flip":
                rep = self._rep(idx)
                done = rep is not None and rep.role == rec.get("role")
                if rep is not None and not rep.dead:
                    # either way the flip's drain must not linger
                    self.router.undrain_replica(idx)
            if not done and kind in ("scale_in", "role_flip") \
                    and idx is not None:
                self.router.undrain_replica(idx)
            rec = dict(rec,
                       status="done" if done else "rolled_back",
                       recovered_by=self.daemon_id)
            self.kv.put(self._journal_key(int(rec["epoch"])),
                        json.dumps(rec))
            settled += 1
            from .. import telemetry as _tel
            _tel.counter("autoscaler.recovered").inc()
            if _tel.active():
                _tel.emit("autoscaler.recover", epoch=rec["epoch"],
                          kind=kind, resolution=rec["status"])
        return settled

    def _rep(self, idx):
        reps = self.router._reps
        return reps[idx] if idx is not None and 0 <= idx < len(reps) \
            else None

    def _revived(self, idx) -> bool:
        rep = self._rep(idx)
        return rep is not None and not rep.dead and not rep.draining

    # -- the loop body -----------------------------------------------------
    def tick(self) -> dict:
        """One poll: returns a status dict ({"status": ..., "action":
        ..., "epoch": ...}) for the driver's introspection.  With
        FLAGS_autoscale off this is ONE flag read — no KV traffic, no
        view aggregation (the bench's zero-overhead gate counts)."""
        if not get_flag("autoscale"):
            return {"status": "disabled"}
        self._ticks += 1
        from .. import telemetry as _tel
        _tel.counter("autoscaler.ticks").inc()
        if not self._hold_lease():
            return {"status": "no_lease"}
        self.recover()
        try:
            f = fault.hit("autoscale.decide", key=f"tick{self._ticks}")
            if f is not None and f.mode == "skip":
                raise fault.FaultError("decide skipped")
        except fault.FaultError as e:
            # a broken metrics read / poisoned decide NEVER crashes the
            # daemon: the tick degrades to a no-op and retries next poll
            _tel.counter("autoscaler.decide_faults").inc()
            if _tel.active():
                _tel.emit("autoscaler.degraded", tick=self._ticks,
                          error=str(e))
            return {"status": "degraded", "error": str(e)}
        view = fleet_view(self.router)
        observe(self.state, view, self.policy)
        action = decide(view, self.policy, self.state)
        if action.kind == "none":
            _tel.counter("autoscaler.noop").inc()
            return {"status": "noop", "action": action.to_dict()}
        epoch = self._claim_epoch(action, view)
        if epoch is None:
            return {"status": "lost_epoch",
                    "action": action.to_dict()}
        ok, err = self._execute(action, epoch)
        if ok:
            after = fleet_view(self.router)
            self.kv.put(self._journal_key(epoch), json.dumps({
                "epoch": epoch, "tick": self._ticks,
                "owner": self.daemon_id,
                "status": "done", "kind": action.kind,
                "replica": action.replica, "role": action.role,
                "reason": action.reason,
                "fleet_before": len(self.router._reps),
                "view_before": _view_brief(view),
                "view_after": _view_brief(after)}))
            after_action(self.state, action, self.policy)
            _tel.counter(f"autoscaler.{action.kind}").inc()
            if _tel.active():
                _tel.emit("autoscaler.action", epoch=epoch,
                          kind=action.kind, replica=action.replica,
                          role=action.role, reason=action.reason)
            return {"status": "executed", "epoch": epoch,
                    "action": action.to_dict()}
        self._rollback(action, epoch, view, err)
        return {"status": "rolled_back", "epoch": epoch,
                "action": action.to_dict(), "error": err}

    def _claim_epoch(self, action: Action, view: dict
                     ) -> Optional[int]:
        """Claim the next free epoch with an atomic put-if-absent of
        the PENDING journal record — the tmp half of tmp-then-commit,
        and the fence: a 409 means another incarnation owns that
        epoch, so we step past it (bounded) without ever re-writing
        its record."""
        for _ in range(64):
            epoch = self._next_epoch
            self._next_epoch += 1
            rec = {"epoch": epoch, "tick": self._ticks,
                   "owner": self.daemon_id,
                   "status": "pending", "kind": action.kind,
                   "replica": action.replica, "role": action.role,
                   "reason": action.reason,
                   "fleet_before": len(self.router._reps),
                   "view_before": _view_brief(view)}
            if self.kv.put_new(self._journal_key(epoch),
                               json.dumps(rec)):
                return epoch
        return None

    def _execute(self, action: Action, epoch: int):
        """Run one claimed action through the lossless elastic surface
        with bounded retry (`policy.retry_budget`, `backoff_s` linear
        backoff) around the fault points.  Returns (ok, error)."""
        err = None
        for attempt in range(self.policy.retry_budget):
            if attempt and self.policy.backoff_s > 0:
                time.sleep(self.policy.backoff_s * attempt)
            try:
                self._execute_once(action, epoch)
                if self._crash_before_commit:
                    raise _SimulatedCrash(
                        f"daemon died before committing epoch {epoch}")
                return True, None
            except _SimulatedCrash:
                raise
            except Exception as e:      # FaultError, spawn failure...
                err = f"{type(e).__name__}: {e}"
                from .. import telemetry as _tel
                _tel.counter("autoscaler.exec_retries").inc()
        return False, err

    def _execute_once(self, action: Action, epoch: int):
        key = f"epoch{epoch}:rep{action.replica}"
        if action.kind == "scale_in":
            fault.hit("autoscale.drain", key=key)
            self.router.drain_replica(action.replica)
            return
        if action.kind == "scale_out":
            fault.hit("autoscale.reform", key=key)
            if action.replica is not None \
                    and self._rep(action.replica) is not None \
                    and not self._rep(action.replica).dead:
                if not self.router.undrain_replica(action.replica):
                    raise RuntimeError(
                        f"replica {action.replica} already retired")
                return
            if self.spawn is None:
                raise RuntimeError("scale_out needs a spawn factory")
            bat = self.spawn()
            self.router.add_replica(bat, role=action.role or "serve")
            return
        if action.kind == "role_flip":
            # drain first so in-flight work never straddles the flip;
            # queued requests migrate losslessly, decodes finish here
            fault.hit("autoscale.drain", key=key)
            self.router.drain_replica(action.replica)
            fault.hit("autoscale.reform", key=key)
            self.router.set_role(action.replica, action.role)
            if not self.router.undrain_replica(action.replica):
                raise RuntimeError(
                    f"replica {action.replica} retired mid-flip")

    def _rollback(self, action: Action, epoch: int, view: dict,
                  err: Optional[str]):
        """A scale action exhausted its retries: return the target to
        rotation (undrain — the drain half may have landed on any
        attempt) and journal the failure.  The fleet is exactly as
        routable as before the action; the policy's cooldown still
        applies so a persistently failing action can't hot-loop."""
        if action.replica is not None \
                and action.kind in ("scale_in", "role_flip"):
            self.router.undrain_replica(action.replica)
        after_action(self.state, action, self.policy)
        self.kv.put(self._journal_key(epoch), json.dumps({
            "epoch": epoch, "tick": self._ticks,
            "owner": self.daemon_id,
            "status": "rolled_back", "kind": action.kind,
            "replica": action.replica, "role": action.role,
            "reason": action.reason, "error": err,
            "fleet_before": len(self.router._reps),
            "view_before": _view_brief(view)}))
        from .. import telemetry as _tel
        _tel.counter("autoscaler.rollback").inc()
        if _tel.active():
            _tel.emit("autoscaler.rollback", epoch=epoch,
                      kind=action.kind, replica=action.replica,
                      error=err)


# ---------------------------------------------------------------------------
# deterministic load for tier-1 / chaos / bench
# ---------------------------------------------------------------------------

class DiurnalLoadSim:
    """A deterministic diurnal load curve: request rate follows one
    raised-cosine 'day' (`low` at the troughs, `high` at the peak)
    with per-tick prompts drawn from a tick-seeded RandomState — the
    SAME (seed, tick) always yields the same prompts in the same
    order, so chaos runs replay exactly and a fixed-fleet reference
    run sees the identical workload."""

    def __init__(self, vocab: int, seed: int = 0, period: int = 8,
                 low: int = 1, high: int = 6, prompt_len: int = 6,
                 max_new: int = 4, interactive_frac: float = 0.5):
        self.vocab = int(vocab)
        self.seed = int(seed)
        self.period = max(1, int(period))
        self.low = int(low)
        self.high = int(high)
        self.prompt_len = int(prompt_len)
        self.max_new = int(max_new)
        self.interactive_frac = float(interactive_frac)

    def rate(self, tick: int) -> int:
        phase = 2.0 * np.pi * (tick % self.period) / self.period
        r = self.low + (self.high - self.low) \
            * 0.5 * (1.0 - np.cos(phase))
        return int(round(r))

    def requests(self, tick: int) -> List[dict]:
        """The tick's request batch: [{prompt, slo, max_new}, ...] —
        reproducible from (seed, tick) alone, independent of any
        earlier call."""
        rng = np.random.RandomState(
            (self.seed * 1000003 + tick) % (2 ** 31 - 1))
        out = []
        for _ in range(self.rate(tick)):
            ids = rng.randint(0, self.vocab,
                              size=self.prompt_len).astype(np.int32)
            slo = "interactive" \
                if rng.rand() < self.interactive_frac else "batch"
            out.append({"prompt": ids, "slo": slo,
                        "max_new": self.max_new})
        return out
