"""Fleet control plane (ROADMAP item 3) — the serving-economics layer
that CLOSES THE LOOP between what the repo can observe (router stats,
per-class SLO attainment, fleet telemetry) and how it can change shape
(elastic drain/undrain/add, role metadata).

`autoscaler` is the first resident: an SLO-driven elastic autoscaler
whose decisions are pure functions, whose executions are journaled and
lease-fenced on the launch KV plane, and whose failure modes are chaos
-checked end to end (`tools/chaos_check.py --autoscale`).
"""
from . import autoscaler  # noqa: F401
from .autoscaler import (Action, AutoscalePolicy, AutoscalerDaemon,  # noqa: F401,E501
                         DiurnalLoadSim, PolicyState, decide,
                         fleet_view, observe, after_action)

__all__ = ["autoscaler", "Action", "AutoscalePolicy",
           "AutoscalerDaemon", "DiurnalLoadSim", "PolicyState",
           "decide", "fleet_view", "observe", "after_action"]
