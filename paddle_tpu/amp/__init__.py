"""Automatic mixed precision.

Reference: `python/paddle/amp/` — `auto_cast` (auto_cast.py:1029, O1
white/black lists in amp_lists.py, O2 pure fp16/bf16 with master weights),
`GradScaler` (grad_scaler.py:657).

TPU-native: bf16 is the native AMP dtype; there are no inf/nan scaling
concerns (bf16 has fp32's exponent range), so GradScaler is a functional
no-op that keeps the reference API (scale()/step()/update()/unscale_()).
O1 works by wrapping op dispatch: ops in the white list cast inputs to the
amp dtype; black-list ops compute in fp32.
"""
from __future__ import annotations

import contextlib
import functools

import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..framework import dtypes

__all__ = ["auto_cast", "amp_guard", "decorate", "GradScaler",
           "is_bfloat16_supported", "is_float16_supported",
           "white_list", "black_list"]

# reference: amp_lists.py (O1 lists) — matmul-ish ops benefit from low
# precision; reductions/norms/softmax/exp stay fp32
WHITE_LIST = {"matmul", "linear", "conv", "conv_transpose", "einsum", "bmm",
              "mm", "attention", "sdpa"}
BLACK_LIST = {"exp", "square", "log", "mean", "sum", "cos_sim", "softmax",
              "log_softmax", "cross_entropy", "layer_norm", "norm",
              "batch_norm", "group_norm", "instance_norm", "rms_norm",
              "reduce", "cumsum", "pow", "erf", "logsumexp"}


def white_list():
    return {"float16": {"O1": set(WHITE_LIST)},
            "bfloat16": {"O1": set(WHITE_LIST)}}


def black_list():
    return {"float16": {"O1": set(BLACK_LIST)},
            "bfloat16": {"O1": set(BLACK_LIST)}}


class _AmpState:
    enabled = False
    dtype = "bfloat16"
    level = "O1"
    custom_white = set()
    custom_black = set()


_state = _AmpState()


def amp_state():
    return _state


def _amp_cast_inputs(name, vals):
    """Called by dispatch.run when AMP O1 is active."""
    if not _state.enabled or _state.level != "O1":
        return vals
    base = name.split("_")[0] if name else ""
    wl = WHITE_LIST | _state.custom_white
    bl = (BLACK_LIST | _state.custom_black) - _state.custom_white
    jd = dtypes.to_jax(_state.dtype)

    def _castable(v):
        return hasattr(v, "dtype") and v.dtype in (jnp.float32, jnp.float16,
                                                   jnp.bfloat16)
    if name in wl or base in wl:
        return [v.astype(jd) if _castable(v) else v for v in vals]
    if name in bl or base in bl:
        return [v.astype(jnp.float32)
                if (hasattr(v, "dtype") and v.dtype in (jnp.float16,
                                                        jnp.bfloat16))
                else v for v in vals]
    return vals


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    """Reference: amp/auto_cast.py:1029."""
    prev = (_state.enabled, _state.dtype, _state.level,
            _state.custom_white, _state.custom_black)
    _state.enabled = enable
    _state.dtype = dtype
    _state.level = level
    _state.custom_white = set(custom_white_list or ())
    _state.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level,
         _state.custom_white, _state.custom_black) = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None, master_grad=False,
             excluded_layers=None):
    """O2: cast model params to amp dtype, keep master weights in the
    optimizer (reference: auto_cast.py decorate/amp_decorate)."""
    from ..nn import Layer
    from ..nn.layer.norm import _BatchNormBase, LayerNorm

    single_model = isinstance(models, Layer)
    model_list = [models] if single_model else list(models)
    if level == "O2":
        excluded = (excluded_layers if excluded_layers is not None
                    else [_BatchNormBase, LayerNorm])
        excl_types = tuple(excluded) if excluded else ()
        for m in model_list:
            for layer in m.sublayers(include_self=True):
                if excl_types and isinstance(layer, excl_types):
                    continue
                for pname, p in layer._parameters.items():
                    if p is not None and p.dtype.is_floating_point():
                        p._value = p._value.astype(dtypes.to_jax(dtype))
    if optimizers is None:
        return models if single_model else model_list
    single_opt = not isinstance(optimizers, (list, tuple))
    opt_list = [optimizers] if single_opt else list(optimizers)
    for o in opt_list:
        o._multi_precision = True
    return (models if single_model else model_list,
            optimizers if single_opt else opt_list)


class GradScaler:
    """Reference: amp/grad_scaler.py:657.  On TPU bf16 needs no loss
    scaling; the API is preserved (scale is identity by default) so fp16
    scripts run unchanged.  use_loss_scaling still works for fp16."""

    def __init__(self, enable=True, init_loss_scaling=65536.0,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=False):
        self._enable = enable
        self._scale = float(init_loss_scaling) if use_dynamic_loss_scaling \
            else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled_opts = set()  # id(optimizer) already unscale_()d

    def scale(self, var):
        if not self._enable or self._scale == 1.0:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable or self._scale == 1.0:
            return
        if id(optimizer) in self._unscaled_opts:
            raise RuntimeError(
                "unscale_() has already been called on this optimizer "
                "since the last update()")
        self._unscaled_opts.add(id(optimizer))
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list:
            if p.grad is not None:
                g = p.grad.value.astype(jnp.float32) * inv
                found = bool(found or not jnp.all(jnp.isfinite(g)))
                p.grad._value = g.astype(p.grad.value.dtype)
        # OR with prior optimizers' result: one overflow anywhere in the
        # iteration must trigger the scale decrease in update()
        self._found_inf = self._found_inf or found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if self._scale != 1.0 and id(optimizer) not in self._unscaled_opts:
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)
        self.update()

    def update(self):
        self._unscaled_opts.clear()
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def backoff(self):
        """Nonfinite-step loss-scale backoff — the StepAnomalyGuard
        hook (distributed/guard.py).  One call = one bad step observed
        by the compiled skip-step path: the scale decreases by
        decr_ratio (floored at 1.0) so the NEXT step's scaled loss has
        headroom, and the good-step streak resets.  A no-op for the
        bf16 default (scale already 1.0)."""
        if not self._enable:
            return
        self._scale = max(self._scale * self._decr_ratio, 1.0)
        self._good_steps = 0
        self._bad_steps = 0

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, sd):
        self._scale = sd.get("scale", self._scale)
        self._good_steps = sd.get("good_steps", 0)
        self._bad_steps = sd.get("bad_steps", 0)


# register the O1 cast hook into op dispatch
from ..framework.dispatch import set_amp_hook as _set_amp_hook
_set_amp_hook(_amp_cast_inputs)


def is_bfloat16_supported(device=None):
    return True


def is_float16_supported(device=None):
    return True


class debugging:
    """Namespace shim for paddle.amp.debugging (tensor checks)."""

    @staticmethod
    def check_numerics(tensor, op_type="", var_name=""):
        import jax.numpy as _jnp
        v = tensor.value if isinstance(tensor, Tensor) else tensor
        has_inf = bool(_jnp.any(_jnp.isinf(v)))
        has_nan = bool(_jnp.any(_jnp.isnan(v)))
        if has_inf or has_nan:
            raise FloatingPointError(
                f"check_numerics failed for {op_type}/{var_name}: "
                f"inf={has_inf} nan={has_nan}")
        return tensor
